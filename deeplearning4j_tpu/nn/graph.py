"""ComputationGraph — DAG networks with graph vertices.

Reference parity: ``org.deeplearning4j.nn.graph.ComputationGraph`` +
``ComputationGraphConfiguration.GraphBuilder`` + vertex impls
``org.deeplearning4j.nn.graph.vertex.impl.{MergeVertex, ElementWiseVertex,
SubsetVertex, L2NormalizeVertex, ScaleVertex, ShiftVertex, StackVertex,
UnstackVertex, PreprocessorVertex}`` (SURVEY.md §2.2 "ComputationGraph
vertices", call stack §3.2). ResNet skip connections and YOLO routes are
built from these.

TPU-native: same design as MultiLayerNetwork — the whole DAG traces into
ONE compiled step; topological order is computed once from the config.
Multiple inputs and multiple outputs (MultiDataSet) are supported.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.analysis import churn as _churn
from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator, MultiDataSet
from deeplearning4j_tpu.evaluation.evaluation import Evaluation
from deeplearning4j_tpu.nn import augment as _augment_mod
from deeplearning4j_tpu.nn import compilecache as _cc
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import preprocessors as pp
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import (_dynamic_scale_next,
                                              _grads_all_finite,
                                              _maybe_attach_env_profiler,
                                              _predict_batches,
                                              _process_and_apply_grads,
                                              _select_update)
from deeplearning4j_tpu.profiler import devicetime as _devicetime
from deeplearning4j_tpu.profiler import sanitizer as _sanitizer
from deeplearning4j_tpu.train import stepping as _stepping

_MASK_AWARE = (L.LSTM, L.SimpleRnn, L.Bidirectional, L.LastTimeStep,
               L.GlobalPoolingLayer, L.SelfAttentionLayer,
               L.RecurrentAttentionLayer)


class GraphVertex:
    """Non-layer DAG node (ref: org.deeplearning4j.nn.conf.graph.*Vertex)."""

    def apply(self, *inputs):
        raise NotImplementedError

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def to_config(self):
        d = {"@class": type(self).__name__}
        d.update({k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in self.__dict__.items()})
        return d

    @classmethod
    def from_config(cls, d):
        obj = cls.__new__(cls)
        for k, v in d.items():
            if k != "@class":
                setattr(obj, k, v)
        return obj


class MergeVertex(GraphVertex):
    """Concat along the channel/feature axis (ref: MergeVertex)."""

    def apply(self, *inputs):
        axis = 1 if inputs[0].ndim >= 3 else -1
        return jnp.concatenate(inputs, axis=axis)

    def output_type(self, *its: InputType) -> InputType:
        it = its[0]
        if it.kind == "cnn":
            return InputType.convolutional(it.height, it.width,
                                           sum(i.channels for i in its))
        if it.kind == "rnn":
            return InputType.recurrent(sum(i.size for i in its),
                                       it.dims.get("timesteps", -1))
        return InputType.feedForward(sum(i.arrayElementsPerExample() for i in its))


class ElementWiseVertex(GraphVertex):
    """Add/Product/Subtract/Average/Max of same-shape inputs
    (ref: ElementWiseVertex). The ResNet residual-add."""

    def __init__(self, op: str = "Add"):
        self.op = op.lower()

    def apply(self, *inputs):
        if self.op == "add":
            out = inputs[0]
            for i in inputs[1:]:
                out = out + i
            return out
        if self.op == "product":
            out = inputs[0]
            for i in inputs[1:]:
                out = out * i
            return out
        if self.op == "subtract":
            return inputs[0] - inputs[1]
        if self.op == "average":
            return sum(inputs) / len(inputs)
        if self.op == "max":
            out = inputs[0]
            for i in inputs[1:]:
                out = jnp.maximum(out, i)
            return out
        raise ValueError(self.op)


class DotProductVertex(GraphVertex):
    """Per-example dot product of two same-shape inputs, with optional L2
    normalization first (the Keras ``Dot``/cosine-proximity merge; ref:
    KerasDot in the reference's keras-import merge family)."""

    def __init__(self, normalize: bool = False):
        self.normalize = normalize

    def apply(self, a, b):
        if a.ndim != 2 or b.ndim != 2:
            raise ValueError(
                f"DotProductVertex supports rank-2 [N, C] inputs (got ranks "
                f"{a.ndim}/{b.ndim}); higher-rank Keras Dot contractions "
                f"do not import")
        if self.normalize:
            a = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True),
                                1e-12)
            b = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True),
                                1e-12)
        return jnp.sum(a * b, axis=-1, keepdims=True)

    def output_type(self, *its: InputType) -> InputType:
        return InputType.feedForward(1)

    def to_config(self):
        return {"@class": "DotProductVertex", "normalize": self.normalize}


class SubsetVertex(GraphVertex):
    """Channel-range slice (ref: SubsetVertex)."""

    def __init__(self, frm: int, to: int):
        self.frm, self.to = frm, to

    def apply(self, x):
        if x.ndim >= 3:
            return x[:, self.frm:self.to + 1]
        return x[:, self.frm:self.to + 1]

    def output_type(self, it: InputType) -> InputType:
        n = self.to - self.frm + 1
        if it.kind == "cnn":
            return InputType.convolutional(it.height, it.width, n)
        if it.kind == "rnn":
            return InputType.recurrent(n, it.dims.get("timesteps", -1))
        return InputType.feedForward(n)


class L2NormalizeVertex(GraphVertex):
    """Per-example L2 normalize (ref: L2NormalizeVertex; FaceNet uses it)."""

    def __init__(self, eps: float = 1e-8):
        self.eps = eps

    def apply(self, x):
        flat = x.reshape(x.shape[0], -1)
        n = jnp.sqrt(jnp.sum(flat * flat, axis=1, keepdims=True))
        out = flat / jnp.maximum(n, self.eps)
        return out.reshape(x.shape)


class ScaleVertex(GraphVertex):
    """(ref: ScaleVertex)"""

    def __init__(self, scale: float):
        self.scale = scale

    def apply(self, x):
        return x * self.scale


class ShiftVertex(GraphVertex):
    """(ref: ShiftVertex)"""

    def __init__(self, shift: float):
        self.shift = shift

    def apply(self, x):
        return x + self.shift


class StackVertex(GraphVertex):
    """Stack along batch (ref: StackVertex)."""

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=0)


class UnstackVertex(GraphVertex):
    """Take slice i of a StackVertex output (ref: UnstackVertex)."""

    def __init__(self, frm: int, stack_size: int):
        self.frm, self.stack_size = frm, stack_size

    def apply(self, x):
        n = x.shape[0] // self.stack_size
        return x[self.frm * n:(self.frm + 1) * n]


class PreprocessorVertex(GraphVertex):
    """Wraps an input preprocessor as a vertex (ref: PreprocessorVertex)."""

    def __init__(self, preproc):
        self.preproc = preproc

    def apply(self, x):
        return self.preproc(x)

    def output_type(self, it: InputType) -> InputType:
        return self.preproc.output_type(it)

    def to_config(self):
        return {"@class": "PreprocessorVertex",
                "preproc_class": type(self.preproc).__name__,
                "preproc_args": dict(self.preproc.__dict__)}

    @classmethod
    def from_config(cls, d):
        pc = getattr(pp, d["preproc_class"])
        obj = pc.__new__(pc)
        obj.__dict__.update(d["preproc_args"])
        return PreprocessorVertex(obj)


_VERTEX_CLASSES = {c.__name__: c for c in
                   [MergeVertex, ElementWiseVertex, SubsetVertex,
                    DotProductVertex, L2NormalizeVertex, ScaleVertex,
                    ShiftVertex, StackVertex, UnstackVertex,
                    PreprocessorVertex]}


class _GraphNode:
    def __init__(self, name: str, kind: str, obj, inputs: List[str]):
        self.name = name
        self.kind = kind      # 'layer' | 'vertex'
        self.obj = obj
        self.inputs = inputs


class GraphBuilder:
    """ref: ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, base: NeuralNetConfiguration):
        self.base = base
        self.nodes: List[_GraphNode] = []
        self.graph_inputs: List[str] = []
        self.graph_outputs: List[str] = []
        self.input_types: Dict[str, InputType] = {}

    def addInputs(self, *names):
        self.graph_inputs.extend(names)
        return self

    def setInputTypes(self, *types):
        for name, t in zip(self.graph_inputs, types):
            self.input_types[name] = t
        return self

    def addLayer(self, name: str, layer, *inputs):
        layer.name = name
        self.nodes.append(_GraphNode(name, "layer", layer, list(inputs)))
        return self

    def addVertex(self, name: str, vertex: GraphVertex, *inputs):
        self.nodes.append(_GraphNode(name, "vertex", vertex, list(inputs)))
        return self

    def setOutputs(self, *names):
        self.graph_outputs = list(names)
        return self

    def build(self) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(self)

    def validate(self, batch_size: int = None, data_devices: int = None,
                 **kw):
        """Static lint of the (possibly not-yet-buildable) graph — unlike
        ``build()``, a cyclic or dangling graph comes back as E002/E003
        diagnostics instead of a ValueError. Extra keywords pass through
        to ``analysis.analyze`` (``mesh=``, ``suppress=``, ...)."""
        from deeplearning4j_tpu.analysis import analyze
        return analyze(self, batch_size=batch_size,
                       data_devices=data_devices, **kw)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        from deeplearning4j_tpu.nn.config import _builder_typo
        raise _builder_typo(self, name)


class ComputationGraphConfiguration:
    """ref: org.deeplearning4j.nn.conf.ComputationGraphConfiguration."""

    def __init__(self, builder: GraphBuilder):
        self.base = builder.base
        self.nodes = builder.nodes
        self.graph_inputs = builder.graph_inputs
        self.graph_outputs = builder.graph_outputs
        self.input_types = builder.input_types
        self.preprocessors: Dict[str, Any] = {}
        self.node_by_name = {n.name: n for n in self.nodes}
        self._toposort()
        if self.input_types:
            self._propagate_types()

    def validate(self, batch_size: int = None, data_devices: int = None,
                 **kw):
        """Static lint — see ``deeplearning4j_tpu.analysis.analyze``."""
        from deeplearning4j_tpu.analysis import analyze
        return analyze(self, batch_size=batch_size,
                       data_devices=data_devices, **kw)

    def _toposort(self):
        order, seen = [], set(self.graph_inputs)
        remaining = list(self.nodes)
        while remaining:
            progressed = False
            for n in list(remaining):
                if all(i in seen for i in n.inputs):
                    order.append(n)
                    seen.add(n.name)
                    remaining.remove(n)
                    progressed = True
            if not progressed:
                missing = {i for n in remaining for i in n.inputs if i not in seen}
                raise ValueError(f"graph has unresolved inputs/cycle: {missing}")
        self.topo = order

    def _propagate_types(self):
        types: Dict[str, InputType] = dict(self.input_types)
        for node in self.topo:
            in_types = [types[i] for i in node.inputs]
            if node.kind == "layer":
                layer = node.obj
                pre = pp.preprocessor_for(in_types[0], layer)
                if pre is not None:
                    self.preprocessors[node.name] = pre
                    in_types[0] = pre.output_type(in_types[0])
                layer.set_defaults(self.base)
                layer.infer_nin(in_types[0])
                types[node.name] = layer.output_type(in_types[0])
            else:
                types[node.name] = node.obj.output_type(*in_types)
        self.types = types

    def to_json(self) -> str:
        import json
        return json.dumps({
            "base": self.base.to_config(),
            "inputs": self.graph_inputs,
            "outputs": self.graph_outputs,
            "input_types": {k: v.to_config() for k, v in self.input_types.items()},
            "nodes": [{"name": n.name, "kind": n.kind,
                       "inputs": n.inputs, "conf": n.obj.to_config()}
                      for n in self.nodes],
        })

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        import json
        d = json.loads(s)
        b = GraphBuilder(NeuralNetConfiguration.from_config(d["base"]))
        b.addInputs(*d["inputs"])
        b.input_types = {k: InputType.from_config(v)
                         for k, v in d["input_types"].items()}
        for nd in d["nodes"]:
            if nd["kind"] == "layer":
                obj = L.layer_from_config(nd["conf"])
                b.addLayer(nd["name"], obj, *nd["inputs"])
            else:
                cls = _VERTEX_CLASSES[nd["conf"]["@class"]]
                b.addVertex(nd["name"], cls.from_config(nd["conf"]), *nd["inputs"])
        b.setOutputs(*d["outputs"])
        return ComputationGraphConfiguration(b)


class ComputationGraph:
    """DAG network (ref: org.deeplearning4j.nn.graph.ComputationGraph)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._params: Dict[str, Dict] = {}
        self._states: Dict[str, Dict] = {}
        self._opt_state = None
        self._iteration = 0
        self._t_dev = None  # device-resident iteration counter (see _ensure_clock)
        self._epoch = 0
        self._score = float("nan")
        self._listeners: List[Any] = []
        self._train_step_cache = {}
        self._megastep_cache = {}
        self._fwd_cache = None
        self._augment = None    # DeviceAugmentation (see setDeviceAugmentation)
        self._precision = None  # PrecisionPolicy (see setPrecisionPolicy)
        self._sharding_plan = None  # ShardedTrainingPlan (see setShardingPlan)
        self._scale_state = None  # dynamic loss scale [scale, good_steps]
        self._initialized = False
        # NHWC compute layout + fused epilogues (ISSUE 14) — opt-in,
        # public NCHW API unchanged (see MultiLayerNetwork)
        self._compute_layout = "NCHW"
        self._fuse_epilogues = False
        self._epilogue_plan = None
        self._epilogue_shared = None
        fmt = getattr(conf.base, "compute_layout", None)
        if fmt and fmt != "NCHW":
            self.setComputeLayout(fmt)

    def validate(self, batch_size: int = None, data_devices: int = None,
                 **kw):
        """Static lint of this graph network (configuration analysis plus
        model-level findings) — see MultiLayerNetwork.validate."""
        from deeplearning4j_tpu.analysis import analyze
        return analyze(self, batch_size=batch_size,
                       data_devices=data_devices, **kw)

    def init(self, seed: int = None, strict: bool = False):
        if strict:
            self.validate().raise_if_errors()
        seed = self.conf.base.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        self._params, self._states = {}, {}
        for node in self.conf.topo:
            if node.kind == "layer":
                key, sub = jax.random.split(key)
                p, s = node.obj.initialize(sub)
                self._params[node.name] = p
                self._states[node.name] = s
        self._opt_state = None
        self._train_step_cache = {}
        self._megastep_cache = {}
        self._fwd_cache = None
        self._scale_state = None
        self._initialized = True
        _sanitizer.invalidate(self)   # re-init = out-of-band state reset
        return self

    # --------------------------------------------------------------- forward
    def _compute_dtype(self):
        """Effective compute dtype: attached PrecisionPolicy wins, else
        the config dataType (see MultiLayerNetwork._compute_dtype)."""
        pol = self._precision
        if pol is not None:
            return pol.compute_jnp()
        return L.compute_dtype_of(self.conf.base.dtype)

    def _forward(self, params, states, inputs: Dict[str, Any], train, key,
                 fmask=None):
        cdt = self._compute_dtype()
        nhwc = self._compute_layout == "NHWC"
        plan = self._ensure_epilogue_plan() if self._fuse_epilogues else {}
        fused_act = {act: bn for bn, (act, _c, _a) in plan.items()}
        fused_conv = {c for _a, c, _al in plan.values() if c}
        shared = self._epilogue_shared if self._fuse_epilogues else set()
        env = {k: (v.astype(jnp.float32)
                   if cdt is None and getattr(v, "dtype", None) == jnp.uint8
                   else v)
               for k, v in inputs.items()}   # on-device image-byte cast
        fmt = {k: False for k in env}        # node name -> output is NHWC
        pending_bias: Dict[str, Any] = {}    # fused conv name -> cast bias
        # shared folded convs: env[] holds the BIAS-LESS output (what the
        # fused BN wants); every other consumer reads this re-biased copy
        # (bit-identical to the unfused conv, see L.conv_bias_add)
        biased: Dict[str, Any] = {}

        def read(name, consumer=None):
            if name in biased:
                if consumer is not None and consumer in plan \
                        and plan[consumer][1] == name:
                    return env[name]     # the anchor BN folds the bias
                return biased[name]
            return env[name]

        new_states = {}
        for ti, node in enumerate(self.conf.topo):
            if node.name in fused_act:
                # folded into its BN's scale_shift_act epilogue; keep the
                # RNG stream identical to the unfused forward
                key, _ = jax.random.split(key)
                env[node.name] = env[fused_act[node.name]]
                fmt[node.name] = fmt[fused_act[node.name]]
                new_states[node.name] = states[node.name]
                continue
            scope = _devicetime.scope_name(ti, node.name)
            if node.kind == "layer":
                x = read(node.inputs[0], node.name)
                cur_nhwc = fmt[node.inputs[0]]
                if node.name in self.conf.preprocessors:
                    if cur_nhwc:
                        x, cur_nhwc = L.to_nchw(x), False
                    x = self.conf.preprocessors[node.name](x)
                x, cur_nhwc = L.layout_step(node.obj, x, cur_nhwc, nhwc)
                p = params[node.name]
                if cdt is not None:
                    p, x = L.policy_cast(node.obj, p, x, cdt)
                key, sub = jax.random.split(key)
                with jax.named_scope(scope):
                    if node.name in plan:          # BN anchoring a fusion
                        act_name, conv_name, alpha = plan[node.name]
                        out, ns = L.fused_bn_act(
                            node.obj, p, states[node.name], x, train, alpha,
                            bias=pending_bias.pop(conv_name, None))
                    elif node.name in fused_conv:  # bias folds into the BN
                        out, ns = node.obj.apply(p, states[node.name], x,
                                                 train, sub, skip_bias=True)
                        pending_bias[node.name] = p.get("b")
                        if node.name in shared:
                            biased[node.name] = L.conv_bias_add(
                                node.obj, out, p.get("b"))
                    elif isinstance(node.obj, _MASK_AWARE):
                        out, ns = node.obj.apply(p, states[node.name],
                                                 x, train, sub, mask=fmask)
                    else:
                        out, ns = node.obj.apply(p, states[node.name],
                                                 x, train, sub)
                new_states[node.name] = ns
                fmt[node.name] = cur_nhwc and getattr(out, "ndim", 0) == 4
            else:
                xs = [read(i) for i in node.inputs]
                in_fmts = [fmt[i] for i in node.inputs]
                transparent = isinstance(node.obj, (ElementWiseVertex,
                                                    ScaleVertex, ShiftVertex))
                if transparent and any(in_fmts) and all(in_fmts):
                    out_nhwc = True                # elementwise: keep NHWC
                else:
                    xs = [L.to_nchw(a) if f else a
                          for a, f in zip(xs, in_fmts)]
                    out_nhwc = False
                if cdt is not None and len(xs) > 1:
                    # merge/elementwise vertices: align mixed fp32/bf16 inputs
                    # (e.g. a BN branch meeting a conv branch)
                    if any(getattr(a, "dtype", None) == jnp.bfloat16
                           for a in xs):
                        xs = [a.astype(jnp.bfloat16)
                              if getattr(a, "dtype", None) == jnp.float32 else a
                              for a in xs]
                with jax.named_scope(scope):
                    out = node.obj.apply(*xs)
                fmt[node.name] = out_nhwc and getattr(out, "ndim", 0) == 4
            env[node.name] = out
        return [L.to_nchw(read(o)) if fmt.get(o) else read(o)
                for o in self.conf.graph_outputs], new_states

    def _as_input_dict(self, inputs) -> Dict[str, jnp.ndarray]:
        if isinstance(inputs, dict):
            return {k: jnp.asarray(v) for k, v in inputs.items()}
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return {name: jnp.asarray(a)
                for name, a in zip(self.conf.graph_inputs, inputs)}

    def output(self, *inputs, train: bool = False):
        """ref: ComputationGraph.output — returns list of output arrays
        (single array if one output)."""
        ins = self._as_input_dict(inputs[0] if len(inputs) == 1 else list(inputs))
        outs = self._jit_forward()(self._params, self._states, ins,
                                   jax.random.PRNGKey(0))
        return outs[0] if len(outs) == 1 else outs

    def _jit_forward(self):
        if self._fwd_cache is None:
            def fwd(params, states, ins, key):
                outs, _ = self._forward(params, states, ins, False, key)
                return outs
            # behind the compile-cache seam — see MultiLayerNetwork.
            # _jit_forward (serving warmup / persistent disk tier)
            self._fwd_cache = _cc.cached_dispatch(
                fwd, "graph:forward", key_parts=self._compile_key_parts(0))
        return self._fwd_cache

    def _warm_forward(self, x) -> "ComputationGraph":
        """AOT-compile the inference forward for this input signature
        without executing it (the ``compilecache.warmup`` seam). ``x``:
        one array, a list matching ``graph_inputs``, or a name->array
        dict."""
        ins = self._as_input_dict(x)
        self._jit_forward().warm(self._params, self._states, ins,
                                 jax.random.PRNGKey(0))
        return self

    def _warm_dispatch(self, x, y, fmask=None, lmask=None,
                       steps: int = 1) -> "ComputationGraph":
        """AOT-compile the train step (or K-step megastep) for this
        batch signature without executing it — see
        MultiLayerNetwork._warm_dispatch. ``x``/``y`` accept single
        arrays or lists for multi-input/multi-output graphs (``fmask``
        is unused — graph fits carry no feature mask)."""
        self._ensure_opt_state()
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        ins = {name: jnp.asarray(a)
               for name, a in zip(self.conf.graph_inputs, xs)}
        ys = list(y) if isinstance(y, (list, tuple)) else [y]
        labels = [jnp.asarray(a) for a in ys]
        lmasks = None
        if lmask is not None:
            lms = list(lmask) if isinstance(lmask, (list, tuple)) else [lmask]
            lmasks = [jnp.asarray(m) for m in lms]
        sig = lmasks is not None
        step, dummy = self._step_for(sig, steps, len(labels))
        clock = jnp.asarray(self._iteration, jnp.int32)
        args = [self._params, self._states, self._opt_state, clock]
        if self._dynamic_scaling():
            args.append(self._ensure_scale_state())
        args += [ins, labels, lmasks if lmasks is not None else dummy]
        step.warm(*args)
        return self

    def feedForward(self, inputs, train: bool = False):
        """Per-node activations, PUBLIC layout (NCHW) even under the
        NHWC compute seam."""
        ins = self._as_input_dict(inputs)
        env = dict(ins)
        key = jax.random.PRNGKey(0)
        nhwc = self._compute_layout == "NHWC"
        fmt = {k: False for k in env}
        acts = {}
        for node in self.conf.topo:
            if node.kind == "layer":
                x = env[node.inputs[0]]
                cur_nhwc = fmt[node.inputs[0]]
                if node.name in self.conf.preprocessors:
                    if cur_nhwc:
                        x, cur_nhwc = L.to_nchw(x), False
                    x = self.conf.preprocessors[node.name](x)
                x, cur_nhwc = L.layout_step(node.obj, x, cur_nhwc, nhwc)
                key, sub = jax.random.split(key)
                if isinstance(node.obj, _MASK_AWARE):
                    out, _ = node.obj.apply(self._params[node.name],
                                            self._states[node.name], x, train,
                                            sub, mask=None)
                else:
                    out, _ = node.obj.apply(self._params[node.name],
                                            self._states[node.name], x, train, sub)
                fmt[node.name] = cur_nhwc and getattr(out, "ndim", 0) == 4
            else:
                xs = [L.to_nchw(env[i]) if fmt[i] else env[i]
                      for i in node.inputs]
                out = node.obj.apply(*xs)
                fmt[node.name] = False
            env[node.name] = out
            acts[node.name] = L.to_nchw(out) if fmt[node.name] else out
        return acts

    # ------------------------------------------------------------------ loss
    def _output_layers(self):
        outs = []
        for name in self.conf.graph_outputs:
            node = self.conf.node_by_name[name]
            if node.kind != "layer" or not isinstance(node.obj, L.BaseOutputLayer):
                raise ValueError(f"graph output '{name}' must be an output layer")
            outs.append(node.obj)
        return outs

    def _loss_and_reg(self, params, states, ins, labels: List, train, key,
                      fmask, lmasks: Optional[List]):
        outs, new_states = self._forward(params, states, ins, train, key, fmask)
        out_layers = self._output_layers()
        loss = 0.0
        for i, (ol, out) in enumerate(zip(out_layers, outs)):
            lm = lmasks[i] if lmasks is not None else None
            loss = loss + ol.compute_loss(labels[i], out, mask=lm)
        reg = 0.0
        for node in self.conf.topo:
            if node.kind != "layer":
                continue
            layer = node.obj
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            p = params.get(node.name) or {}
            if l1 == 0.0 and l2 == 0.0:
                continue
            for pname, w in p.items():
                if not pname.startswith(("W", "RW")):
                    continue
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
        return loss + reg, new_states

    # ------------------------------------------------------------------- fit
    def _make_train_step(self, with_lmasks: bool, steps: int = 1):
        """Compile the train step; ``steps=K`` wraps the SAME body in one
        lax.scan program doing K update steps per dispatch (see
        MultiLayerNetwork._make_train_step)."""
        base = self.conf.base
        updater = base.updater

        seed = base.seed

        augment = self._augment
        # static loss scaling under the precision seam — see
        # MultiLayerNetwork._make_train_step
        pol = self._precision
        if pol is not None and pol.is_dynamic:
            return self._make_dynamic_train_step(steps=steps,
                                                 with_lmasks=with_lmasks)
        loss_scale = pol.loss_scale if pol is not None else None
        # GSPMD output sharding constraints — see
        # MultiLayerNetwork._make_train_step
        plan = self._sharding_plan
        psh, osh = (None, None) if plan is None \
            else plan.step_constraints(self)

        def step(params, states, opt_state, t, ins, labels, lmasks):
            # per-step RNG from the donated device counter (see
            # MultiLayerNetwork._make_train_step: avoids a host->device
            # upload per iteration, stays resume-deterministic)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            if augment is not None:
                # on-device augmentation prelude: every 4-D (NCHW image)
                # input runs the seeded chain; non-image inputs pass
                # through (nn.augment.maybe_augment)
                ins = {name: _augment_mod.maybe_augment(augment, v, t)
                       for name, v in ins.items()}

            def loss_fn(p):
                loss, ns = self._loss_and_reg(
                    p, states, ins, labels, True, key,
                    None, lmasks if with_lmasks else None)
                if loss_scale:
                    loss = loss * loss_scale
                return loss, ns
            (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if loss_scale:
                inv = 1.0 / loss_scale
                loss = loss * inv           # listeners/score see true loss
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            new_params, new_opt = _process_and_apply_grads(
                base, updater, params, grads, opt_state, t.astype(jnp.float32))
            new_params = _stepping.constrain_tree(new_params, psh)
            new_opt = _stepping.constrain_tree(new_opt, osh)
            return new_params, new_states, new_opt, t + 1, loss
        # donate params/states/opt_state/t: the step consumes and replaces
        # them, halving peak HBM for the update and letting dependent
        # dispatches pipeline on relayed TPU backends. Behind the
        # compile-cache seam (nn.compilecache) like the MLN steps.
        if steps > 1:
            return _cc.cached_dispatch(
                _stepping.scan_megastep(step, 4), "graph:megastep",
                key_parts=self._compile_key_parts(steps),
                donate_argnums=(0, 1, 2, 3))
        return _cc.cached_dispatch(
            step, "graph:train_step", key_parts=self._compile_key_parts(1),
            donate_argnums=(0, 1, 2, 3))

    def _make_dynamic_train_step(self, steps: int, with_lmasks: bool):
        """Train step under ``PrecisionPolicy(loss_scale="dynamic")`` —
        the grow/backoff automaton traced into the compiled program; see
        MultiLayerNetwork._make_dynamic_train_step (this is its graph
        mirror: ins dict + labels list, no feature mask)."""
        base = self.conf.base
        updater = base.updater
        seed = base.seed
        augment = self._augment
        pol = self._precision
        plan = self._sharding_plan
        psh, osh = (None, None) if plan is None \
            else plan.step_constraints(self)

        def step(params, states, opt_state, t, scale_state, ins, labels,
                 lmasks):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            if augment is not None:
                ins = {name: _augment_mod.maybe_augment(augment, v, t)
                       for name, v in ins.items()}
            scale = scale_state[0]

            def loss_fn(p):
                loss, ns = self._loss_and_reg(
                    p, states, ins, labels, True, key,
                    None, lmasks if with_lmasks else None)
                return loss * scale, ns
            (loss, new_states), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params)
            inv = 1.0 / scale
            loss = loss * inv           # listeners/score see true loss
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            ok = _grads_all_finite(grads)
            new_params, new_opt = _process_and_apply_grads(
                base, updater, params, grads, opt_state,
                t.astype(jnp.float32))
            new_params = _select_update(ok, new_params, params)
            new_opt = _select_update(ok, new_opt, opt_state)
            new_states = _select_update(ok, new_states, states)
            new_params = _stepping.constrain_tree(new_params, psh)
            new_opt = _stepping.constrain_tree(new_opt, osh)
            return (new_params, new_states, new_opt, t + 1,
                    _dynamic_scale_next(pol, scale_state, ok), loss)
        if steps > 1:
            return _cc.cached_dispatch(
                _stepping.scan_megastep(step, 5), "graph:megastep",
                key_parts=self._compile_key_parts(steps),
                donate_argnums=(0, 1, 2, 3, 4))
        return _cc.cached_dispatch(
            step, "graph:train_step", key_parts=self._compile_key_parts(1),
            donate_argnums=(0, 1, 2, 3, 4))

    def _step_for(self, sig, steps: int, n_labels: int):
        """(compiled step, dummy mask list) for one mask signature ×
        dispatch K — THE single lookup `_fit_one`, `_fit_mega`, and
        `_warm_dispatch` share (see MultiLayerNetwork._step_for)."""
        if steps > 1:
            if (sig, steps) not in self._megastep_cache:
                self._megastep_cache[(sig, steps)] = \
                    self._make_train_step(sig, steps=steps)
            return (self._megastep_cache[(sig, steps)],
                    [jnp.zeros((steps, 1))] * n_labels)
        if sig not in self._train_step_cache:
            self._train_step_cache[sig] = self._make_train_step(sig)
        return self._train_step_cache[sig], [jnp.zeros((1,))] * n_labels

    def _compile_key_parts(self, steps: int = 1):
        """Persistent-cache key parts — see MultiLayerNetwork."""
        pol = self._precision
        aug = self._augment
        fp = getattr(self, "_conf_fingerprint", None)
        if fp is None:
            fp = self._conf_fingerprint = _cc.model_fingerprint(self)
        plan = self._sharding_plan
        return (fp,
                pol.signature() if pol is not None else None,
                aug.signature() if aug is not None else None,
                steps, self._compute_layout,
                self._fuse_epilogues,
                plan.signature() if plan is not None else None)

    def _dynamic_scaling(self) -> bool:
        pol = self._precision
        return pol is not None and pol.is_dynamic

    def _ensure_scale_state(self):
        """Device-resident ``[scale, good_steps]`` dynamic loss-scale
        carry — see MultiLayerNetwork._ensure_scale_state."""
        if self._scale_state is None:
            s = jnp.asarray(
                [float(self._precision.loss_scale_init), 0.0], jnp.float32)
            if self._sharding_plan is not None:  # see _ensure_clock
                s = jax.device_put(s, self._sharding_plan.mesh.replicated())
            self._scale_state = s
        return self._scale_state

    def current_loss_scale(self):
        """Live dynamic loss scale / static scale / None — see
        MultiLayerNetwork.current_loss_scale."""
        if self._dynamic_scaling():
            if self._scale_state is None:
                return float(self._precision.loss_scale_init)
            return float(np.asarray(jax.device_get(self._scale_state))[0])
        pol = self._precision
        return pol.loss_scale if pol is not None else None

    def _ensure_opt_state(self):
        if self._opt_state is None:
            updater = self.conf.base.updater
            self._opt_state = jax.tree_util.tree_map(
                lambda p: updater.init_state(p), self._params,
                is_leaf=lambda x: isinstance(x, jax.Array))

    def _ensure_clock(self):
        """Device-resident iteration counter (int32 scalar), donated and
        incremented inside the compiled step — see
        MultiLayerNetwork._ensure_clock (incl. the GSPMD-plan commit)."""
        if self._t_dev is None:
            t = jnp.asarray(self._iteration, jnp.int32)
            if self._sharding_plan is not None:
                t = jax.device_put(t, self._sharding_plan.mesh.replicated())
            self._t_dev = t
        return self._t_dev

    def setComputeLayout(self, fmt: str) -> "ComputationGraph":
        """NHWC compute layout for the conv stacks — semantics identical
        to ``MultiLayerNetwork.setComputeLayout`` (channels-minor conv/
        pool/BN inside the compiled step, transpose-at-boundary, public
        NCHW API unchanged; elementwise vertices — the ResNet residual
        add — stay in NHWC between aware layers)."""
        if fmt not in ("NCHW", "NHWC"):
            raise ValueError(f"compute layout must be 'NCHW' or 'NHWC', "
                             f"got {fmt!r}")
        if fmt != getattr(self, "_compute_layout", "NCHW"):
            self._train_step_cache.clear()
            self._megastep_cache.clear()
            self._fwd_cache = None
        self._compute_layout = fmt
        # recorded on the config too, so save/load round-trips the seam
        self.conf.base.compute_layout = fmt
        self._conf_fingerprint = None    # config JSON changed
        L.stamp_layout([n.obj for n in self.conf.topo if n.kind == "layer"],
                       fmt)
        return self

    def setEpilogueFusion(self, enabled: bool = True) -> "ComputationGraph":
        """Fuse conv-bias+BN+relu / BN+leaky blocks into one
        ``scale_shift_act`` dispatch — see
        ``MultiLayerNetwork.setEpilogueFusion``. On a graph, a fusion
        anchors at a BatchNormalization node whose ONLY consumer is a
        relu/leaky ActivationLayer node.  A conv whose output feeds
        MORE consumers than the BN still folds: the BN takes the
        bias-less output (bias rides in its shift) and the other
        consumers read a bit-identical re-biased copy, so residual
        taps off a conv no longer block the fold."""
        enabled = bool(enabled)
        if enabled != self._fuse_epilogues:
            self._train_step_cache.clear()
            self._megastep_cache.clear()
            self._fwd_cache = None
            self._epilogue_plan = None
            self._epilogue_shared = None
        self._fuse_epilogues = enabled
        return self

    def _ensure_epilogue_plan(self):
        """{bn_node: (act_node, folded_conv_node|None, alpha)} — static,
        built once per fusion toggle from the graph topology.  Also
        builds ``self._epilogue_shared``: folded convs whose output has
        consumers BESIDES the anchoring BN — ``_forward`` materializes a
        bit-identical re-biased copy for those readers (the fold itself
        still skips the bias and rides it in the BN shift)."""
        if (self._epilogue_plan is not None
                and getattr(self, "_epilogue_shared", None) is not None):
            return self._epilogue_plan
        conf = self.conf
        consumers: Dict[str, List[str]] = {}
        for node in conf.topo:
            for inp in node.inputs:
                consumers.setdefault(inp, []).append(node.name)
        for out in conf.graph_outputs:
            consumers.setdefault(out, []).append("__output__")
        plan: Dict[str, tuple] = {}
        folded: set = set()          # convs already claimed by an earlier BN
        shared: set = set()          # folded convs with extra consumers
        by_name = conf.node_by_name
        for node in conf.topo:
            if node.kind != "layer" or not L.fusable_bn(node.obj):
                continue
            cons = consumers.get(node.name, [])
            if len(cons) != 1 or cons[0] == "__output__":
                continue
            act_node = by_name[cons[0]]
            if (act_node.kind != "layer" or len(act_node.inputs) != 1
                    or act_node.name in conf.preprocessors):
                continue
            alpha = L.activation_alpha(act_node.obj)
            if alpha is None:
                continue
            conv_name = None
            src = by_name.get(node.inputs[0]) if node.inputs else None
            # a conv feeding >1 consumer no longer blocks the fold; it
            # folds into AT MOST one BN (first in topo order), and any
            # other consumer reads the re-biased copy
            if (src is not None and src.kind == "layer"
                    and L.fusable_conv(src.obj) and src.obj.has_bias
                    and src.name not in folded
                    and node.name not in conf.preprocessors):
                conv_name = src.name
                folded.add(src.name)
                if len(consumers.get(src.name, [])) > 1:
                    shared.add(src.name)
            plan[node.name] = (act_node.name, conv_name, alpha)
        self._epilogue_plan = plan
        self._epilogue_shared = shared
        return plan

    def setDeviceAugmentation(self, augment) -> "ComputationGraph":
        """Attach (or detach with ``None``) a
        :class:`~deeplearning4j_tpu.nn.augment.DeviceAugmentation` — the
        seeded on-device crop/flip/normalize prelude; semantics identical
        to ``MultiLayerNetwork.setDeviceAugmentation`` (image inputs
        only; a changed chain invalidates the compiled step caches)."""
        cur = getattr(self, "_augment", None)
        same = (augment.signature() if augment is not None else None) == \
            (cur.signature() if cur is not None else None)
        self._augment = augment
        if not same:
            self._train_step_cache.clear()
            self._megastep_cache.clear()
        return self

    def setShardingPlan(self, plan) -> "ComputationGraph":
        """Attach (or detach with ``None``) a
        :class:`~deeplearning4j_tpu.distributed.gspmd.
        ShardedTrainingPlan` — semantics identical to
        ``MultiLayerNetwork.setShardingPlan`` (NamedSharding placement
        on params/updater state, plan-derived batch staging, output
        sharding constraints inside the ONE compiled step; a changed
        plan signature busts the step caches, an equal one keeps
        them)."""
        cur = self._sharding_plan
        same = (plan.signature() if plan is not None else None) == \
            (cur.signature() if cur is not None else None)
        self._sharding_plan = plan
        if not same:
            self._train_step_cache.clear()
            self._megastep_cache.clear()
            self._fwd_cache = None
            self._t_dev = None  # the device clock moves to the plan's mesh
        return self

    def setPrecisionPolicy(self, policy) -> "ComputationGraph":
        """Attach (or detach with ``None``) a
        :class:`~deeplearning4j_tpu.nn.precision.PrecisionPolicy` (or a
        dtype string like ``"bf16"``) — semantics identical to
        ``MultiLayerNetwork.setPrecisionPolicy`` (fp32 master params,
        loss scaling around the backward pass, signature-keyed cache
        bust on change, zero steady-state recompiles on re-attach)."""
        from deeplearning4j_tpu.nn.precision import (PrecisionPolicy,
                                                     runtime_check)
        policy = PrecisionPolicy.coerce(policy)
        if policy is not None:
            runtime_check(policy)
        cur = self._precision
        same = (policy.signature() if policy is not None else None) == \
            (cur.signature() if cur is not None else None)
        self._precision = policy
        if not same:
            self._train_step_cache.clear()
            self._megastep_cache.clear()
            self._fwd_cache = None
            self._scale_state = None    # dynamic loss scale restarts with
        return self                     # its policy's init value

    def fit(self, data, labels=None, epochs: int = 1,
            steps_per_dispatch: int = 1, prefetch: int = 2,
            checkpoint=None, nan_policy=None, faults=None, augment=None,
            precision=None, tune=None):
        """Accepts a DataSetIterator, DataSet, MultiDataSet, or arrays.
        ``precision=`` attaches a mixed-precision policy (see
        :meth:`setPrecisionPolicy`).
        ``tune="auto"`` applies the autotuner record store's winning
        plan for this (model, mesh, backend) — see MultiLayerNetwork.fit
        and ``tune/``; a ``TuningPlan`` instance applies directly.
        ``steps_per_dispatch=K`` runs K update steps per compiled dispatch
        with double-buffered device prefetch (``prefetch=0`` = synchronous
        consumption on the calling thread) — see MultiLayerNetwork.fit.
        ``checkpoint=``/``nan_policy=``/``faults=`` enable the fault-
        tolerance layer (atomic checkpoint + auto-resume, NaN recovery
        policies, deterministic fault injection) — semantics identical to
        MultiLayerNetwork.fit, as are ``augment=`` (on-device
        augmentation) and the native megabatch pull from staged pipeline
        iterators."""
        if not self._initialized:
            self.init()
        self._ensure_opt_state()
        if tune is not None:
            steps_per_dispatch, prefetch = _stepping.apply_tuned_plan(
                self, tune, steps_per_dispatch, prefetch)
        if augment is not None:
            self.setDeviceAugmentation(augment)
        if precision is not None:
            self.setPrecisionPolicy(precision)
        _maybe_attach_env_profiler(self)
        session = None
        if checkpoint is not None or nan_policy is not None \
                or faults is not None:
            from deeplearning4j_tpu.train import resilience as _resilience
            session, data = _resilience.begin_session(
                self, data, checkpoint, nan_policy, faults)
            # resume cold-start killer — see MultiLayerNetwork.fit
            session.warm_after_resume(steps_per_dispatch)

        def batches():
            if isinstance(data, DataSetIterator):
                if session is None or not session.consume_skip_reset():
                    data.reset()
                if _stepping.use_dispatch_stream(data, steps_per_dispatch,
                                                 session):
                    yield from data.dispatch_stream()
                    return
                while data.hasNext():
                    yield data.next()
            elif isinstance(data, (DataSet, MultiDataSet)):
                yield data
            elif isinstance(data, (list, tuple)) and data and \
                    isinstance(data[0], (DataSet, MultiDataSet)):
                yield from data
            else:
                yield DataSet(np.asarray(data), np.asarray(labels))

        def epoch_stream():
            return session.wrap_batches(batches()) if session is not None \
                else batches()

        from deeplearning4j_tpu.train.resilience import fit_scope
        with fit_scope(session, self, epochs) as n_epochs:
            for _ in range(n_epochs):
                with _prof.trace_span("train:epoch", epoch=self._epoch):
                    # data-wait vs compute split (see MultiLayerNetwork.fit)
                    if steps_per_dispatch > 1:
                        # plan-derived prefetcher placement (see
                        # MultiLayerNetwork.fit)
                        _stepping.fit_epoch_multistep(
                            self, epoch_stream(), steps_per_dispatch,
                            prefetch,
                            placement=_stepping.batch_placement(self))
                    else:
                        for ds in _prof.iter_with_data_wait(epoch_stream()):
                            self._fit_one(ds)
                self._epoch += 1
                for lst in self._listeners:
                    if hasattr(lst, "onEpochEnd"):
                        lst.onEpochEnd(self)
                if session is not None:
                    session.on_epoch_end()
        return self

    def _fit_one(self, ds):
        if self._sharding_plan is not None:
            self._sharding_plan.ensure_placed(self)  # GSPMD placement guard
        stage = lambda a: _stepping.stage_batch(self, a)
        if isinstance(ds, MultiDataSet):
            ins = {name: stage(a)
                   for name, a in zip(self.conf.graph_inputs, ds.features)}
            labels = [stage(a) for a in ds.labels]
            lmasks = [stage(m) for m in ds.labels_masks] \
                if ds.labels_masks else None
        else:
            ins = {self.conf.graph_inputs[0]: stage(ds.features)}
            labels = [stage(ds.labels)]
            lmasks = [stage(ds.labels_mask)] if ds.labels_mask is not None else None
        # recompile-churn seam (see MultiLayerNetwork._fit_one)
        _churn.get_churn_detector().record(
            "ComputationGraph.fit",
            _churn.array_fingerprint(
                [ins[k] for k in sorted(ins)], labels, lmasks), owner=self)
        sig = lmasks is not None
        step, dummy = self._step_for(sig, 1, len(labels))
        # fence read at dispatch ENTRY: any elastic recovery landing after
        # this point voids the whole dispatch, hooks included
        gen = _stepping.fence_generation(self)
        res = getattr(self, "_resilience", None)
        if res is not None:
            res.before_step()
        # provenance sanitizer — see MultiLayerNetwork._fit_one
        tok = _sanitizer.snapshot(self, "graph", ins=ins, labels=labels,
                                  lmasks=lmasks)
        for lst in self._listeners:
            if hasattr(lst, "onIterationStart"):
                # 1-based, matching iterationDone: hook pair refers to the
                # same step number
                lst.onIterationStart(self, self._iteration + 1)
        if _prof.instrumentation_active():
            # keep the amortization-factor gauge consistent with the
            # histogram samples this block records
            _stepping.STEPS_PER_DISPATCH.set(1)
            _stepping.TRAIN_ITERATIONS.inc()
        dyn = self._dynamic_scaling()
        with _prof.timed_region(
                "train:step", "dl4j_train_step_seconds",
                "Compiled train-step dispatch time per iteration",
                iteration=self._iteration + 1):
            args = [self._params, self._states, self._opt_state,
                    self._ensure_clock()]
            if dyn:     # dynamic loss scale: an extra donated carry
                args.append(self._ensure_scale_state())
            out = step(*args, ins, labels,
                       lmasks if lmasks is not None else dummy)
        with _stepping.dispatch_commit(self, gen) as ok:
            if not ok:      # elastic recovery rolled this step back while
                return      # the dispatch was hung: discard, no bookkeeping
            if dyn:
                (self._params, self._states, self._opt_state, self._t_dev,
                 self._scale_state, loss) = out
            else:
                self._params, self._states, self._opt_state, self._t_dev, \
                    loss = out
        # on-device; score() converts lazily (per-step host sync is ~20x the
        # step cost through a high-latency device link)
        self._score = loss
        _sanitizer.check(self, tok, loss,
                         context=f"loss at iteration {self._iteration}")
        self._last_batch_size = int(next(iter(ins.values())).shape[0])
        self._iteration += 1
        for lst in self._listeners:
            if hasattr(lst, "iterationDone"):
                lst.iterationDone(self, self._iteration, self._epoch)
        if res is not None:
            res.after_step()

    def _fit_mega(self, mb):
        """One multi-step dispatch over K stacked batches — the graph
        counterpart of MultiLayerNetwork._fit_mega."""
        if not self._initialized:
            self.init()
        self._ensure_opt_state()
        if self._sharding_plan is not None:
            self._sharding_plan.ensure_placed(self)  # see _fit_one
        k = mb.steps
        stage = lambda a: _stepping.stage_batch(self, a, mega=True)
        if mb.multi:
            ins = {name: stage(a)
                   for name, a in zip(self.conf.graph_inputs, mb.features)}
            labels = [stage(a) for a in mb.labels]
            lmasks = [stage(m) for m in mb.labels_mask] \
                if mb.labels_mask else None
        else:
            ins = {self.conf.graph_inputs[0]: stage(mb.features)}
            labels = [stage(mb.labels)]
            lmasks = [stage(mb.labels_mask)] \
                if mb.labels_mask is not None else None
        _churn.get_churn_detector().record(
            "ComputationGraph.megastep",
            _churn.array_fingerprint(
                [ins[k] for k in sorted(ins)], labels, lmasks), owner=self)
        sig = lmasks is not None
        step, dummy = self._step_for(sig, k, len(labels))
        gen = _stepping.fence_generation(self)  # dispatch entry (see _fit_one)
        res = getattr(self, "_resilience", None)
        if res is not None:
            res.before_dispatch()
        tok = _sanitizer.snapshot(self, "graph_mega", ins=ins, labels=labels,
                                  lmasks=lmasks)   # see _fit_one
        if _prof.instrumentation_active():
            _stepping.STEPS_PER_DISPATCH.set(k)
        dyn = self._dynamic_scaling()
        with _prof.timed_region(
                "train:megastep", "dl4j_train_step_seconds",
                "Compiled train-step dispatch time per iteration",
                iteration=self._iteration + 1, steps=k):
            args = [self._params, self._states, self._opt_state,
                    self._ensure_clock()]
            if dyn:     # dynamic loss scale: an extra scanned carry
                args.append(self._ensure_scale_state())
            out = step(*args, ins, labels,
                       lmasks if lmasks is not None else dummy)
        with _stepping.dispatch_commit(self, gen) as ok:
            if not ok:
                return      # abandoned dispatch: see dispatch_commit
            if dyn:
                (self._params, self._states, self._opt_state, self._t_dev,
                 self._scale_state, losses) = out
            else:
                self._params, self._states, self._opt_state, self._t_dev, \
                    losses = out
        _stepping.record_megastep(self, losses, k,
                                  int(next(iter(ins.values())).shape[1]),
                                  san_token=tok)

    # ------------------------------------------------------------- utilities
    def score(self, ds=None) -> float:
        if ds is None:
            if self._score is not None and not isinstance(self._score, float):
                self._score = float(self._score)
            return self._score
        if isinstance(ds, MultiDataSet):
            ins = {n: jnp.asarray(a) for n, a in zip(self.conf.graph_inputs, ds.features)}
            labels = [jnp.asarray(a) for a in ds.labels]
        else:
            ins = {self.conf.graph_inputs[0]: jnp.asarray(ds.features)}
            labels = [jnp.asarray(ds.labels)]
        loss, _ = self._loss_and_reg(self._params, self._states, ins, labels,
                                     False, jax.random.PRNGKey(0), None, None)
        return float(loss)

    def evaluate(self, iterator, evaluation=None, pull_chunk: int = None,
                 prefetch: bool = True) -> Evaluation:
        """Accepts a DataSetIterator or any iterable of DataSets; forwards
        dispatch per batch, predictions pulled D2H in chunked bulk
        device_gets (see nn.multilayer._predict_batches; ``pull_chunk``
        bounds on-device prediction residency, ``prefetch=False`` keeps
        consumption on the calling thread)."""
        from deeplearning4j_tpu.nn.multilayer import _EVAL_PULL_CHUNK
        ev = evaluation or Evaluation()
        for labels, preds, mask in _predict_batches(
                self.output, iterator, pull_chunk or _EVAL_PULL_CHUNK,
                prefetch):
            ev.eval(labels, preds, mask=mask)
        return ev

    def params(self) -> jnp.ndarray:
        # host-side gather before concat for heterogeneously-sharded
        # GSPMD leaves — see MultiLayerNetwork.params() (device-side
        # concatenate over mixed shardings silently misassembles on
        # this jax version); uniform shardings keep the device path
        leaves = jax.tree_util.tree_leaves(self._params)
        if not leaves:
            return jnp.zeros((0,))
        if len({getattr(p, "sharding", None) for p in leaves}) > 1:
            host = jax.device_get(leaves)
            return jnp.asarray(np.concatenate([np.ravel(p) for p in host]))
        return jnp.concatenate([jnp.ravel(p) for p in leaves])

    def numParams(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self._params))

    def setListeners(self, *listeners):
        self._listeners = list(listeners)

    def getLayer(self, name: str):
        return self.conf.node_by_name[name].obj

    def summary(self) -> str:
        lines = ["=" * 78,
                 f"{'Name (Type)':<38}{'In':<20}{'Params':<10}", "=" * 78]
        total = 0
        for node in self.conf.topo:
            n = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(self._params.get(node.name, {})))
            total += n
            lines.append(f"{f'{node.name} ({type(node.obj).__name__})':<38}"
                         f"{','.join(node.inputs):<20}{n:<10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    # ------------------------------------------------------------ save / load
    def save(self, path: str, save_updater: bool = True):
        """Atomic (temp + os.replace) model archive — a crash mid-write
        never leaves a truncated zip under ``path`` (serializer parity
        with ModelSerializer.writeModel)."""
        from deeplearning4j_tpu.train.serializer import write_model_zip
        meta = {"type": "ComputationGraph", "iteration": self._iteration,
                "epoch": self._epoch,
                "save_updater": bool(save_updater and self._opt_state is not None)}
        arrays = {}
        for name, p in self._params.items():
            for k, arr in p.items():
                arrays[f"p::{name}::{k}"] = np.asarray(arr)
        for name, s in self._states.items():
            for k, arr in s.items():
                arrays[f"s::{name}::{k}"] = np.asarray(arr)
        if meta["save_updater"]:
            leaves, _ = jax.tree_util.tree_flatten(self._opt_state)
            for j, leaf in enumerate(leaves):
                arrays[f"u::{j}"] = np.asarray(leaf)
        write_model_zip(path, self.conf.to_json(), meta, arrays)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "ComputationGraph":
        """Raises ``serializer.CorruptModelError`` naming the bad entry on
        a truncated/damaged archive instead of a raw KeyError."""
        from deeplearning4j_tpu.train.serializer import (CorruptModelError,
                                                         read_model_zip,
                                                         require_array)
        conf_json, meta, arrays = read_model_zip(path)
        try:
            conf = ComputationGraphConfiguration.from_json(conf_json)
        except Exception as e:
            raise CorruptModelError(path, "conf.json",
                                    f"unparseable configuration ({e})") from e
        net = ComputationGraph(conf)
        net.init()
        for k in arrays.files:
            parts = k.split("::")
            if parts[0] == "p":
                net._params[parts[1]][parts[2]] = jnp.asarray(arrays[k])
            elif parts[0] == "s":
                net._states[parts[1]][parts[2]] = jnp.asarray(arrays[k])
        net._iteration = meta["iteration"]
        net._epoch = meta["epoch"]
        if load_updater and meta.get("save_updater"):
            net._ensure_opt_state()
            leaves, treedef = jax.tree_util.tree_flatten(net._opt_state)
            new_leaves = [jnp.asarray(require_array(arrays, f"u::{j}", path))
                          for j in range(len(leaves))]
            net._opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net
