"""Persistent/AOT compilation cache + the unified warmup API — kill
cold start.

Every fresh process pays full XLA compile on its first dispatch; that
cost is exactly why preemption resume (train.resilience), elastic
shrink re-warm (parallel.elastic), serving bucket-ladder warmup
(serving.server), and registry hot-swap staging (serving.registry) are
the expensive moments at scale. TVM and PyGraph (PAPERS.md) both show
ahead-of-time graph compilation/capture amortizing compile cost across
runs — this module is that layer for the whole stack:

- :class:`DiskCompileCache` — a content-addressed on-disk store of
  serialized XLA executables. The key is a SHA-256 over (scope,
  lowered StableHLO text — which already embeds the graph structure,
  input signature/bucket shape, mesh/sharding annotations, and the
  PrecisionPolicy's traced casts — explicit key parts like the model
  fingerprint and policy signature, and the jax/jaxlib/backend
  versions). Corrupt entries are QUARANTINED (renamed aside, never
  trusted); version-mismatched entries are ignored and rewritten.
  Writes are atomic (temp file + ``os.replace``, the PR-5 checkpoint
  pattern), so concurrent writers — many processes warming the same
  model — race safely: last identical write wins.
- :class:`CachedDispatch` — a ``jax.jit`` drop-in that sits behind the
  networks' existing signature-keyed step caches. Until the persistent
  cache is enabled (or :meth:`CachedDispatch.warm` is called) it
  delegates straight to the jitted function — zero behavioural change.
  With a cache dir configured it goes AOT: ``lower()`` the program,
  content-address it, ``deserialize`` from disk on a hit (warm) or
  ``compile()`` + persist on a miss (cold). ``warm()`` compiles WITHOUT
  executing — warmup never touches model state.
- :func:`warmup` — the ONE entry point fit, resume, shrink, and
  serving all call: ``warmup(model, [((32, 784), (32, 10))])`` AOT-
  compiles the train step (megastep with ``steps_per_dispatch=K``),
  ``warmup(model, [(8, 3, 32, 32)])`` the inference forward, and
  ``warmup(server, [(4,)])`` delegates to the serving bucket-ladder
  warmup. A registry hot-swap on a previously-seen (model, bucket,
  mesh, policy) tuple therefore hits disk instead of recompiling.

Enable with ``configure("/path/to/cache")`` or the
``DL4J_TPU_COMPILE_CACHE_DIR`` environment variable (read lazily, so
tests and launchers can set it before the first compile).

Metrics: ``dl4j_compile_cache_{hits,misses,evictions}_total{scope=
disk|memory}``, ``dl4j_compile_cache_quarantined_total``, and
``dl4j_compile_seconds{state=cold|warm}`` (cold = real XLA compile,
warm = disk-hit deserialize). ``bench.py --cold-start`` measures the
end-to-end effect: first-dispatch latency of a fresh process with the
cache off vs. populated, across fit, resume, and serving warmup.

IMPORTANT: jax-free at module scope — ``analysis/serving.py`` consults
:func:`cache_dir_status` for the DL4J-W112 lint from environments with
no accelerator stack (the jax-blocked subprocess pin covers ``nn``'s
static half). jax loads lazily, only on the compile path.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Optional

from deeplearning4j_tpu.profiler.metrics import get_registry

_REG = get_registry()
CACHE_HITS = _REG.counter(
    "dl4j_compile_cache_hits_total",
    "Compile-cache hits by tier: memory = an already-AOT-compiled "
    "executable served a dispatch, disk = a fresh program was "
    "deserialized from the persistent store instead of compiled",
    labelnames=("scope",))
CACHE_MISSES = _REG.counter(
    "dl4j_compile_cache_misses_total",
    "Compile-cache misses by tier: memory = first sight of a dispatch "
    "signature in this process, disk = the persistent store had no "
    "entry (a real XLA compile followed)",
    labelnames=("scope",))
CACHE_EVICTIONS = _REG.counter(
    "dl4j_compile_cache_evictions_total",
    "Entries evicted from a compile-cache tier (disk: LRU past "
    "max_entries; memory: never — programs live with their model)",
    labelnames=("scope",))
CACHE_QUARANTINED = _REG.counter(
    "dl4j_compile_cache_quarantined_total",
    "Corrupt persistent-cache entries (bad magic/header/checksum) "
    "renamed aside at read time instead of trusted")
COMPILE_SECONDS = _REG.histogram(
    "dl4j_compile_seconds",
    "Program acquisition latency split by state: cold = real XLA "
    "compile, warm = deserialize of a persistent-cache hit",
    labelnames=("state",),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))

# prebound children: the memory-hit increment sits on the dispatch hot path
_HITS_MEM = CACHE_HITS.labels(scope="memory")
_HITS_DISK = CACHE_HITS.labels(scope="disk")
_MISS_MEM = CACHE_MISSES.labels(scope="memory")
_MISS_DISK = CACHE_MISSES.labels(scope="disk")
_EVICT_DISK = CACHE_EVICTIONS.labels(scope="disk")
# registered so the series exists even though memory entries never evict
CACHE_EVICTIONS.labels(scope="memory")
_COLD = COMPILE_SECONDS.labels(state="cold")
_WARM = COMPILE_SECONDS.labels(state="warm")

ENV_DIR = "DL4J_TPU_COMPILE_CACHE_DIR"
ENV_MAX_ENTRIES = "DL4J_TPU_COMPILE_CACHE_MAX_ENTRIES"

_UNSET = object()
_LOCK = threading.RLock()
_CONFIGURED_DIR = _UNSET            # explicit configure() overrides the env
_CONFIGURED_MAX: Optional[int] = None
_DISK: Optional["DiskCompileCache"] = None

#: per-process aggregates for cache_stats() / the cold-start probe —
#: plain ints mutated under the GIL (single += per event)
_STATS = {"memory_hits": 0, "memory_misses": 0,
          "disk_hits": 0, "disk_misses": 0,
          "cold_seconds": 0.0, "warm_seconds": 0.0,
          "cold_compiles": 0, "warm_loads": 0}


def configure(directory: Optional[str], max_entries: Optional[int] = None
              ) -> None:
    """Set (or clear, with ``None``) the persistent cache directory for
    this process, overriding ``DL4J_TPU_COMPILE_CACHE_DIR``. Call with
    the sentinel-free default to re-enable env resolution:
    ``configure(os.environ.get(ENV_DIR))``."""
    global _CONFIGURED_DIR, _CONFIGURED_MAX, _DISK
    with _LOCK:
        _CONFIGURED_DIR = directory
        _CONFIGURED_MAX = max_entries
        _DISK = None                     # rebuilt lazily at the new path


def reset_configuration() -> None:
    """Drop the explicit configure() override (env resolution returns)."""
    global _CONFIGURED_DIR, _CONFIGURED_MAX, _DISK
    with _LOCK:
        _CONFIGURED_DIR = _UNSET
        _CONFIGURED_MAX = None
        _DISK = None


def cache_dir() -> Optional[str]:
    """The resolved persistent-cache directory (explicit configure()
    wins, else the env var), or None when the disk tier is disabled."""
    with _LOCK:
        if _CONFIGURED_DIR is not _UNSET:
            return _CONFIGURED_DIR
    return os.environ.get(ENV_DIR) or None


def cache_dir_status():
    """(directory, writable) — what the DL4J-W112 serving lint checks:
    ``(None, False)`` means no persistent cache is configured and every
    fresh process/rollout pays full XLA compile. jax-free."""
    d = cache_dir()
    if d is None:
        return None, False
    try:
        os.makedirs(d, exist_ok=True)
        probe = os.path.join(d, f".wprobe_{os.getpid()}_{threading.get_ident()}")
        with open(probe, "w") as f:
            f.write("w")
        os.remove(probe)
        return d, True
    except OSError:
        return d, False


_DISK_WARNED: set = set()


def disk_cache() -> Optional["DiskCompileCache"]:
    """The process-wide disk tier at the resolved directory (None when
    disabled OR the directory cannot be created — an unusable cache
    degrades to no cache, never to a failed dispatch; the W112 lint is
    what surfaces the misconfiguration). Rebuilt when configure()
    changes the path."""
    global _DISK
    d = cache_dir()
    if d is None:
        return None
    with _LOCK:
        if _DISK is None or _DISK.dir != d:
            max_entries = _CONFIGURED_MAX
            if max_entries is None:
                max_entries = int(os.environ.get(ENV_MAX_ENTRIES, "512"))
            try:
                _DISK = DiskCompileCache(d, max_entries=max_entries)
            except OSError as e:
                if d not in _DISK_WARNED:
                    _DISK_WARNED.add(d)
                    warnings.warn(
                        f"persistent compile cache at {d!r} unusable "
                        f"({e}) — running without the disk tier "
                        "(DL4J-W112 territory)", stacklevel=2)
                return None
        return _DISK


def cache_stats() -> dict:
    """Per-process snapshot: tier hit/miss counts, compile-seconds split
    cold/warm, and the disk store's entry count. The cross-process pin
    asserts ``disk.misses == 0`` and ``compile_seconds.cold == 0`` for a
    second fresh process over previously-seen keys."""
    disk = None
    d = cache_dir()
    if d is not None and os.path.isdir(d):
        disk = disk_cache()
    return {
        "memory": {"hits": _STATS["memory_hits"],
                   "misses": _STATS["memory_misses"]},
        "disk": {"enabled": d is not None,
                 "dir": d,
                 "hits": _STATS["disk_hits"],
                 "misses": _STATS["disk_misses"],
                 "entries": disk.entry_count() if disk is not None else 0},
        "compile_seconds": {"cold": _STATS["cold_seconds"],
                            "warm": _STATS["warm_seconds"],
                            "cold_compiles": _STATS["cold_compiles"],
                            "warm_loads": _STATS["warm_loads"]},
    }


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0.0 if k.endswith("seconds") else 0


# --------------------------------------------- shared event accounting
# ONE bookkeeping path for both tiers' consumers (CachedDispatch and the
# native runtime's disk seam): the Prometheus series and the per-process
# cache_stats() aggregates — which the cold-start probe and the
# cross-process pins read — can never disagree.
def note_disk_hit(seconds: float) -> None:
    _STATS["disk_hits"] += 1
    _STATS["warm_seconds"] += seconds
    _STATS["warm_loads"] += 1
    _HITS_DISK.inc()
    _WARM.observe(seconds)


def note_disk_miss() -> None:
    _STATS["disk_misses"] += 1
    _MISS_DISK.inc()


def note_cold_compile(seconds: float) -> None:
    _STATS["cold_seconds"] += seconds
    _STATS["cold_compiles"] += 1
    _COLD.observe(seconds)


# ------------------------------------------------------------------- keys
_RUNTIME_FP = None


def runtime_fingerprint() -> str:
    """jax/jaxlib/backend identity baked into every key: an executable
    serialized by one runtime must never be loaded by another."""
    global _RUNTIME_FP
    if _RUNTIME_FP is None:
        import jax
        import jaxlib
        _RUNTIME_FP = (f"jax={jax.__version__};jaxlib={jaxlib.__version__};"
                       f"backend={jax.default_backend()}")
    return _RUNTIME_FP


def content_key(scope: str, content: bytes, key_parts=()) -> str:
    """SHA-256 hex over (runtime fingerprint, scope, explicit key parts,
    program content). The content is the lowered StableHLO text, so the
    graph fingerprint, input signature/bucket shape, mesh sharding
    annotations, and precision-policy casts are all content-addressed;
    ``key_parts`` (model fingerprint, policy signature, ...) add
    defense-in-depth namespacing and observability."""
    h = hashlib.sha256()
    h.update(runtime_fingerprint().encode())
    h.update(b"\x00" + scope.encode() + b"\x00")
    h.update(repr(tuple(key_parts)).encode())
    h.update(b"\x00")
    h.update(content)
    return h.hexdigest()


# -------------------------------------------------------------- disk tier
_MAGIC = b"DL4JCC1\n"
_FORMAT = 1


class DiskCompileCache:
    """Content-addressed store of serialized executables (module doc).

    One entry = one file ``cc_<sha256>.bin``: magic line, one JSON
    header line (format, runtime fingerprint, payload SHA-256, scope,
    creation time), then the pickled serialized-executable payload.
    Readers validate magic + header + checksum; corrupt entries are
    quarantined (renamed ``quarantine_cc_...``), version-mismatched
    ones ignored (the caller recompiles and overwrites). Writes are
    atomic: temp file + ``os.replace``.
    """

    def __init__(self, directory: str, max_entries: int = 512):
        self.dir = directory
        self.max_entries = int(max_entries)
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"cc_{key}.bin")

    def entry_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.dir)
                       if n.startswith("cc_") and n.endswith(".bin"))
        except OSError:
            return 0

    # ------------------------------------------------------------- read
    def get(self, key: str) -> Optional[bytes]:
        """Payload bytes for ``key``, or None (absent, version-
        mismatched, transiently unreadable, or quarantined-corrupt).
        Does NOT touch the hit/miss counters — :class:`CachedDispatch`
        owns those."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    raise ValueError(f"bad magic {magic!r}")
                header = json.loads(f.readline().decode())
                payload = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            # an I/O error (EIO, a stale NFS handle, momentary EACCES on
            # a fleet-shared dir) is NOT evidence of corruption — miss
            # now, retry next time; only content damage quarantines
            return None
        except (ValueError, UnicodeDecodeError) as e:
            self._quarantine(path, str(e))
            return None
        if header.get("format") != _FORMAT \
                or header.get("runtime") != runtime_fingerprint():
            # stale jax/jaxlib/backend (or format) — ignored, and the
            # caller's fresh compile overwrites it in place
            return None
        digest = hashlib.sha256(payload).hexdigest()
        if digest != header.get("sha256"):
            self._quarantine(
                path, f"payload checksum mismatch (header "
                      f"{str(header.get('sha256'))[:12]}..., actual "
                      f"{digest[:12]}...)")
            return None
        try:                # LRU clock for eviction ordering
            os.utime(path, None)
        except OSError:
            pass
        return payload

    # ------------------------------------------------------------ write
    def put(self, key: str, payload: bytes, scope: str = "") -> str:
        """Atomic write (temp + ``os.replace``): a crash mid-write can
        never leave a half-entry under the real name, and concurrent
        writers of the same key land whole either way."""
        path = self._path(key)
        header = {"format": _FORMAT, "runtime": runtime_fingerprint(),
                  "sha256": hashlib.sha256(payload).hexdigest(),
                  "scope": scope, "created": time.time()}
        tmp = os.path.join(
            self.dir, f".tmp_cc_{key[:16]}_{os.getpid()}_"
                      f"{threading.get_ident()}")
        try:
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(json.dumps(header).encode() + b"\n")
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            # a failed (or interrupted) write must not orphan the temp
            # file in a long-lived fleet-shared directory
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        self._evict()
        return path

    #: temp files older than this are considered abandoned by a killed
    #: writer and swept by _evict (a live write takes milliseconds)
    _TMP_MAX_AGE_S = 3600.0

    #: entries younger than this are NEVER evicted, whatever the entry
    #: count (the multi-host grace window): on a fleet-shared directory
    #: another host may have just written an entry it has not dispatched
    #: yet — its mtime is its only defense against a neighbor's LRU
    #: pass, and an autotuning sweep multiplying entries must not let
    #: host A's churn delete host B's seconds-old executable
    _EVICT_GRACE_S = 300.0

    def _evict(self) -> None:
        """Best-effort LRU over the shared directory — correct under
        concurrent multi-host writers WITHOUT any cross-host lock.

        Scoring is mtime-based: ``get()`` touches entries on every hit
        (the LRU clock), so the oldest mtime is the coldest entry on
        ANY host.  Every filesystem call tolerates losing a race — a
        file another evictor removed first, an entry vanishing between
        ``listdir`` and ``getmtime`` — by skipping, never by aborting
        the sweep; and entries inside the grace window are left alone
        even when the directory is over capacity (capacity recovers on
        a later pass once they age; deleting fresh entries would break
        the writer that has not loaded them yet)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        # wall clock on purpose: it is compared against file MTIMES,
        # which are wall-clock too (monotonic would be wrong here)
        now = time.time()
        entries = []
        for n in names:
            p = os.path.join(self.dir, n)
            if n.startswith(".tmp_cc_"):
                try:
                    age = now - os.path.getmtime(p)  # dl4j: noqa=W210
                    if age > self._TMP_MAX_AGE_S:
                        os.remove(p)    # a crashed writer's orphan
                except OSError:
                    pass
                continue
            if n.startswith("cc_") and n.endswith(".bin"):
                try:
                    entries.append((os.path.getmtime(p), n))
                except OSError:
                    continue        # concurrently evicted/quarantined
        entries.sort()              # oldest mtime (coldest) first
        excess = len(entries) - max(1, self.max_entries)
        for mtime, name in entries:
            if excess <= 0:
                break
            if now - mtime < self._EVICT_GRACE_S:  # dl4j: noqa=W210
                break       # sorted: everything after is younger still
            try:
                os.remove(os.path.join(self.dir, name))
                _EVICT_DISK.inc()
            except OSError:
                pass        # a concurrent evictor got it first — the
                            # entry is gone either way, count it
            excess -= 1

    def _quarantine(self, path: str, reason: str) -> None:
        dst = os.path.join(os.path.dirname(path),
                           "quarantine_" + os.path.basename(path))
        try:
            os.replace(path, dst)
        except OSError:
            return
        CACHE_QUARANTINED.inc()
        warnings.warn(
            f"compile cache: quarantined corrupt entry {path}: {reason}",
            stacklevel=3)


# -------------------------------------------------- serialized executables
def _serialize_executable(compiled) -> bytes:
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree), protocol=4)


def _deserialize_executable(blob: bytes):
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


# --------------------------------------------------------- cached dispatch
#: sentinel parked in CachedDispatch._compiled for signatures whose AOT
#: acquisition failed — the plain-jit fallback is permanent per signature,
#: never a re-lowering per dispatch
_AOT_FAILED = object()


def _leaf_signature(a):
    """Jit-cache-equivalent identity of one argument leaf: shape, dtype,
    weak-type, and (for committed jax arrays) the sharding object itself
    — shardings are hashable, and a mesh/placement change must map to a
    different compiled program."""
    shard = getattr(a, "sharding", None)
    return (tuple(getattr(a, "shape", ())),
            str(getattr(a, "dtype", type(a).__name__)),
            bool(getattr(a, "weak_type", False)),
            shard)


class CachedDispatch:
    """``jax.jit`` drop-in backed by the two-tier compile cache.

    Construction jits ``fn`` exactly as before. ``__call__`` delegates
    straight to that jit until the AOT path is engaged (persistent
    cache configured, or :meth:`warm` used) — the default behaviour is
    byte-identical to plain ``jax.jit``. On the AOT path each concrete
    call signature maps to one compiled executable held in ``_compiled``
    (the memory tier); acquisition lowers the program, content-
    addresses the StableHLO, and either deserializes a disk hit (warm)
    or compiles + persists (cold). Any failure in the AOT machinery
    falls back to the plain jit with a single warning — the cache is an
    accelerant, never a correctness dependency.

    Cost note: the AOT path computes a Python-side signature (flatten +
    per-leaf shape/dtype/sharding) on every call, replacing jit's C++
    dispatch cache — microseconds per hundred leaves. The FULL argument
    tree is keyed deliberately: the parallel wrapper swaps params to
    mesh-replicated shardings without busting the outer step caches, so
    keying only on the data leaves would silently reuse an executable
    compiled for the wrong placement. Deployments that never enable the
    persistent cache never pay this — the disabled path IS plain jit.
    """

    __slots__ = ("_jit", "scope", "key_parts", "_compiled", "_warned")

    def __init__(self, fn, scope: str, key_parts=(), donate_argnums=()):
        import jax
        self._jit = jax.jit(fn, donate_argnums=donate_argnums)
        self.scope = scope
        self.key_parts = tuple(key_parts)
        self._compiled = {}
        self._warned = False

    # ------------------------------------------------------------- call
    def _signature(self, args):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(_leaf_signature(a) for a in leaves))

    def __call__(self, *args):
        if not self._compiled and disk_cache() is None:
            return self._jit(*args)     # cache disabled, never warmed:
        sig = self._signature(args)     # the pre-existing fast path
        exe = self._compiled.get(sig)
        if exe is _AOT_FAILED:
            return self._jit(*args)     # known-bad signature: permanent
        if exe is not None:             # plain-jit fallback, no re-trace
            _STATS["memory_hits"] += 1
            _HITS_MEM.inc()
            return exe(*args)
        _STATS["memory_misses"] += 1
        _MISS_MEM.inc()
        exe = self._acquire(args, sig)
        if exe is None:
            # remember the failure: re-running the (expensive) lowering
            # on every subsequent dispatch would turn each step into a
            # re-trace — the fallback must be as permanent as the
            # warning says it is
            self._compiled[sig] = _AOT_FAILED
            return self._jit(*args)
        return exe(*args)

    def warm(self, *args) -> "CachedDispatch":
        """AOT-compile (or load from disk) the program for this argument
        signature WITHOUT executing it — model/optimizer state is never
        touched, donation consumes nothing."""
        sig = self._signature(args)
        if sig not in self._compiled:
            if self._acquire(args, sig) is None:
                self._compiled[sig] = _AOT_FAILED
        return self

    def warmed_signatures(self) -> int:
        return sum(1 for v in self._compiled.values()
                   if v is not _AOT_FAILED)

    # -------------------------------------------------------- acquisition
    def _warn_once(self, what: str, err: BaseException) -> None:
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"compile cache [{self.scope}]: {what} failed "
                f"({type(err).__name__}: {err}) — falling back to plain "
                "jit dispatch for this program", stacklevel=3)

    def _acquire(self, args, sig):
        try:
            lowered = self._jit.lower(*args)
        except Exception as e:
            self._warn_once("AOT lowering", e)
            return None
        disk = disk_cache()
        key = None
        if disk is not None:
            try:
                text = lowered.as_text()
                key = content_key(self.scope, text.encode(), self.key_parts)
                blob = disk.get(key)
            except Exception as e:
                self._warn_once("persistent-cache lookup", e)
                disk, blob = None, None
            if blob is not None:
                try:
                    t0 = time.perf_counter()
                    exe = _deserialize_executable(blob)
                    note_disk_hit(time.perf_counter() - t0)
                    self._compiled[sig] = exe
                    return exe
                except Exception as e:
                    # checksum-valid but unloadable (e.g. an executable
                    # from a subtly different device topology): recompile
                    # and overwrite — never fail the dispatch
                    self._warn_once("persistent-cache deserialize", e)
            if disk is not None:
                note_disk_miss()
        try:
            t0 = time.perf_counter()
            exe = lowered.compile()
            dt = time.perf_counter() - t0
        except Exception as e:
            self._warn_once("AOT compile", e)
            return None
        note_cold_compile(dt)
        if disk is not None and key is not None:
            try:
                disk.put(key, _serialize_executable(exe), scope=self.scope)
            except Exception as e:
                self._warn_once("persistent-cache write", e)
        self._compiled[sig] = exe
        return exe


def cached_dispatch(fn, scope: str, key_parts=(), donate_argnums=()
                    ) -> CachedDispatch:
    """The seam the networks' step caches call instead of ``jax.jit``."""
    return CachedDispatch(fn, scope, key_parts=key_parts,
                          donate_argnums=donate_argnums)


def model_fingerprint(model) -> str:
    """Stable cross-process identity of a model's architecture: SHA-256
    of the configuration JSON when the config serializes, else a
    process-local id (disables cross-process sharing for that model but
    keeps in-process AOT correct)."""
    conf = getattr(model, "conf", model)
    try:
        return hashlib.sha256(conf.to_json().encode()).hexdigest()[:16]
    except Exception:
        return f"pid{os.getpid()}-id{id(conf):x}"


# ----------------------------------------------------------------- warmup
def _is_shape(spec) -> bool:
    return isinstance(spec, (tuple, list)) \
        and all(isinstance(d, (int,)) for d in spec)


def _zeros(shape, dtype):
    import numpy as np
    return np.zeros(tuple(int(d) for d in shape), dtype=dtype)


def warmup(target, shapes, *, mesh=None, policy=None,
           steps_per_dispatch: int = 1, dtype=None, label_dtype=None,
           strict: bool = False, placement=None, tuned: bool = False):
    """Unified AOT warmup for fit, resume, shrink, and serving.

    ``target`` is a :class:`~deeplearning4j_tpu.serving.server.
    ModelServer` (delegates to its bucket-ladder ``warmup``) or a
    network (MultiLayerNetwork / ComputationGraph). ``shapes`` entries:

    - ``(features_shape, labels_shape)`` — a pair of shape tuples —
      AOT-compiles the TRAIN step for that batch signature (the
      ``lax.scan`` megastep when ``steps_per_dispatch=K>1``; pass the
      per-batch shapes, the K axis is added here). This is what resume
      and elastic shrink warm before re-entering the fit loop.
    - ``features_shape`` — a bare shape tuple — AOT-compiles the
      inference FORWARD (what serving dispatches).

    ``mesh`` enters the device-mesh context during compilation (the
    trace-cache key contains the entered-mesh stack — warm under the
    same context the dispatch will run in); ``placement`` is an
    optional callable staging warm arrays the way the dispatch path
    stages real ones (the elastic wrapper's sharded megabatch layout);
    ``policy`` attaches a PrecisionPolicy first (same as
    ``fit(precision=...)``). ``tuned=True`` consults the autotuner
    record store (ISSUE 17) and applies the winning plan for this
    (model, mesh, backend) BEFORE compiling, so the warmed programs are
    the ones the tuned fit/serve path will dispatch — the plan's
    ``steps_per_dispatch`` also takes over when the caller left the
    default.  Nothing executes: warmup populates the compile caches —
    and, when the persistent cache is configured, the on-disk store —
    without touching model/optimizer state."""
    import numpy as np
    if hasattr(target, "buckets") and hasattr(target, "submit"):
        # a ModelServer: its ladder warmup is already the serving-side
        # entry point (and records the zero-recompile churn baseline)
        if tuned:
            from deeplearning4j_tpu.tune import records as _trecords
            m = getattr(target, "model", None)
            if m is not None:
                _trecords.auto_apply(m, mesh=mesh, context="warmup")
        return target.warmup(shapes, strict=strict)
    model = target
    if tuned:
        from deeplearning4j_tpu.tune import records as _trecords
        plan = _trecords.auto_apply(model, mesh=mesh, context="warmup")
        if plan is not None and steps_per_dispatch == 1:
            steps_per_dispatch = plan.steps_per_dispatch
    if policy is not None:
        model.setPrecisionPolicy(policy)
    if not model._initialized:
        model.init()
    model._ensure_opt_state()
    fdt = np.dtype(dtype) if dtype is not None else np.float32
    ldt = np.dtype(label_dtype) if label_dtype is not None else np.float32
    k = max(int(steps_per_dispatch), 1)

    from contextlib import nullcontext
    with (mesh if mesh is not None else nullcontext()):
        for spec in shapes:
            if _is_shape(spec):
                x = _zeros(spec, fdt)
                if placement is not None:
                    x = placement(x)
                model._warm_forward(x)
                continue
            if not (isinstance(spec, (tuple, list)) and len(spec) == 2):
                raise ValueError(
                    f"warmup shape spec {spec!r}: expected a feature shape "
                    "tuple (forward) or a (features_shape, labels_shape) "
                    "pair (train step)")
            fshape, lshape = spec
            if k > 1:
                x = _zeros((k,) + tuple(fshape), fdt)
                y = _zeros((k,) + tuple(lshape), ldt)
            else:
                x = _zeros(fshape, fdt)
                y = _zeros(lshape, ldt)
            if placement is not None:
                x, y = placement(x), placement(y)
            model._warm_dispatch(x, y, steps=k)
    return model


def warm_from_batch_signature(model, batch_sig: dict,
                              steps_per_dispatch: int = 1) -> bool:
    """Warm a train step from the signature a resilience checkpoint
    recorded (``{"features": [shape, dtype], "labels": [...]}``) — the
    resume path's cold-start killer. Best-effort: returns False (never
    raises) when the signature is absent/unusable."""
    if not batch_sig:
        return False
    try:
        f = batch_sig.get("features")
        lab = batch_sig.get("labels")
        if not f or not lab:
            return False
        warmup(model, [(tuple(f[0]), tuple(lab[0]))],
               steps_per_dispatch=steps_per_dispatch,
               dtype=f[1], label_dtype=lab[1])
        return True
    except Exception as e:
        warnings.warn(f"resume warmup skipped: {type(e).__name__}: {e}",
                      stacklevel=2)
        return False


def describe_batch(ds) -> Optional[dict]:
    """The checkpoint-manifest batch signature ``warm_from_batch_
    signature`` consumes: shapes/dtypes of a single-input DataSet (the
    overwhelmingly common resume case). MultiDataSet batches return
    None — their warmup happens through the explicit API."""
    feats = getattr(ds, "features", None)
    labels = getattr(ds, "labels", None)
    if feats is None or labels is None \
            or isinstance(feats, (list, tuple)):
        return None
    try:
        sig = {"features": [list(feats.shape), str(feats.dtype)],
               "labels": [list(labels.shape), str(labels.dtype)]}
    except AttributeError:
        return None
    if getattr(ds, "features_mask", None) is not None \
            or getattr(ds, "labels_mask", None) is not None:
        return None                  # masked signatures: explicit warmup
    return sig
