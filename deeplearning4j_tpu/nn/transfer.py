"""Transfer learning: freeze/replace layers of a pretrained network.

Reference parity: ``org.deeplearning4j.nn.transferlearning.{
TransferLearning, TransferLearningHelper, FineTuneConfiguration}``
(SURVEY.md §2.2 "Transfer learning").

TPU-native: freezing is a static property of the compiled train step —
frozen layers get a zero update (their grads still flow through for
upstream layers, exactly like the reference's FrozenLayer). The helper's
featurize-and-cache mode runs the frozen prefix ONCE per dataset and
trains only the head.
"""

from __future__ import annotations

import copy
from typing import Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class FineTuneConfiguration:
    """ref: FineTuneConfiguration — overrides applied to all layers."""

    def __init__(self, updater=None, l1: float = None, l2: float = None,
                 seed: int = None):
        self.updater = updater
        self.l1 = l1
        self.l2 = l2
        self.seed = seed

    class Builder:
        def __init__(self):
            self._kw = {}

        def updater(self, u):
            self._kw["updater"] = u
            return self

        def l1(self, v):
            self._kw["l1"] = v
            return self

        def l2(self, v):
            self._kw["l2"] = v
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def build(self):
            return FineTuneConfiguration(**self._kw)


class TransferLearning:
    """ref: TransferLearning.Builder for MultiLayerNetwork."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self.net = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._n_removed = 0
            self._added = []
            self._nout_replaced = {}

        def fineTuneConfiguration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def setFeatureExtractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] inclusive (ref semantics)."""
            self._freeze_until = layer_idx
            return self

        def removeOutputLayer(self):
            self._n_removed += 1
            return self

        def removeLayersFromOutput(self, n: int):
            self._n_removed += n
            return self

        def addLayer(self, layer):
            self._added.append(layer)
            return self

        def nOutReplace(self, layer_idx: int, n_out: int, weight_init="xavier"):
            """Replace layer_idx's nOut (and re-init it + the next layer's
            nIn) — ref: nOutReplace."""
            self._nout_replaced[layer_idx] = (n_out, weight_init)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self.net
            conf = src.conf
            keep = len(conf.layers) - self._n_removed
            new_layers = [copy.deepcopy(l) for l in conf.layers[:keep]]
            for idx, (n_out, w_init) in self._nout_replaced.items():
                new_layers[idx].nOut = n_out
                new_layers[idx].weight_init = w_init
                if idx + 1 < len(new_layers):
                    new_layers[idx + 1].nIn = None  # re-infer
            new_layers.extend(copy.deepcopy(l) for l in self._added)

            base = copy.deepcopy(conf.base)
            if self._ftc:
                if self._ftc.updater is not None:
                    base.updater = self._ftc.updater
                if self._ftc.l1 is not None:
                    base.l1 = self._ftc.l1
                if self._ftc.l2 is not None:
                    base.l2 = self._ftc.l2
                if self._ftc.seed is not None:
                    base.seed = self._ftc.seed

            new_conf = MultiLayerConfiguration(base, new_layers, conf.input_type)
            net = MultiLayerNetwork(new_conf)
            net.init()
            # copy source params for retained, un-replaced layers
            for i in range(keep):
                if i in self._nout_replaced:
                    continue
                if i + 1 in self._nout_replaced or (i - 1) in self._nout_replaced:
                    pass  # neighbours of a replaced layer keep shapes unless nIn changed
                # jnp.copy: the new net's fit() donates its buffers — an
                # aliasing copy would delete the SOURCE net's params
                for name, arr in src._params[i].items():
                    if name in net._params[i] and net._params[i][name].shape == arr.shape:
                        net._params[i][name] = jnp.copy(arr)
                for name, arr in src._states[i].items():
                    if name in net._states[i] and net._states[i][name].shape == arr.shape:
                        net._states[i][name] = jnp.copy(arr)
            if self._freeze_until is not None:
                net._frozen_layers = set(range(self._freeze_until + 1))
            return net


# Frozen-layer handling lives inside MultiLayerNetwork._make_train_step
# (the restore must happen INSIDE the jit: the step donates its param
# buffers, so re-using the caller's old arrays outside it would read
# deleted buffers).


class TransferLearningHelper:
    """ref: TransferLearningHelper — featurize the frozen prefix once,
    train only the unfrozen head."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.net = net
        self.frozen_until = frozen_until

    def featurize(self, ds: DataSet) -> DataSet:
        """Run inputs through the frozen prefix (ref: featurize)."""
        acts = self.net.feedForward(ds.features, train=False)
        # activation index: acts[0] is the input; +1 per layer
        feat = np.asarray(acts[self.frozen_until + 1])
        return DataSet(feat, ds.labels, ds.features_mask, ds.labels_mask)

    def unfrozenMLN(self) -> MultiLayerNetwork:
        """A network of only the unfrozen layers, sharing params."""
        conf = self.net.conf
        head_layers = conf.layers[self.frozen_until + 1:]
        base = conf.base
        new_conf = MultiLayerConfiguration.__new__(MultiLayerConfiguration)
        new_conf.base = base
        new_conf.layers = head_layers
        new_conf.input_type = None
        new_conf.preprocessors = {}
        new_conf.layer_input_types = []
        net = MultiLayerNetwork(new_conf)
        # copies, not aliases: head.fit() donates its buffers, and the
        # trained params flow back explicitly in fitFeaturized
        net._params = [{k: jnp.copy(v) for k, v in d.items()}
                       for d in self.net._params[self.frozen_until + 1:]]
        net._states = [{k: jnp.copy(v) for k, v in d.items()}
                       for d in self.net._states[self.frozen_until + 1:]]
        net._initialized = True
        return net

    def fitFeaturized(self, featurized: DataSet, epochs: int = 1):
        head = self.unfrozenMLN()
        head.fit(featurized, epochs=epochs)
        # write trained head params back
        for off, p in enumerate(head._params):
            self.net._params[self.frozen_until + 1 + off] = p
        return self.net
