"""Layer configurations + functional forward passes — the DL4J layer zoo.

Reference parity: ``org.deeplearning4j.nn.conf.layers.*`` (configs) and
``org.deeplearning4j.nn.layers.*`` (implementations) — SURVEY.md §2.2
"DL4J layers". Weight layouts match the reference: dense W [nIn, nOut],
bias [nOut]; conv W [nOut, nIn, kH, kW]; recurrent input W [nIn, 4H].
Recurrent data layout is the reference's [N, channels, T] (NCW).

TPU-native: NO hand-written ``backpropGradient`` anywhere — each layer is
a pure ``apply(params, state, x, train, key)`` traced into the network's
single compiled step; autodiff is program-level (SURVEY.md §7 item 4).
Layer-level ``dropout`` follows the reference's semantics: the value is
the RETAIN probability, applied to the layer's input.
"""

from __future__ import annotations

import difflib
import inspect
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import _initialize
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.ops import activations as act
from deeplearning4j_tpu.ops import attention as attention_ops
from deeplearning4j_tpu.ops import convolution as conv_ops
from deeplearning4j_tpu.ops import losses as loss_ops
from deeplearning4j_tpu.ops import normalization as norm_ops
from deeplearning4j_tpu.ops import recurrent as rnn_ops


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


_KNOWN_KWARGS_CACHE: Dict[type, frozenset] = {}


def _known_kwargs(cls) -> frozenset:
    """Every keyword a layer class's constructor chain accepts (collected
    over the MRO so subclass kwargs and base Layer kwargs both count)."""
    cached = _KNOWN_KWARGS_CACHE.get(cls)
    if cached is not None:
        return cached
    keys = set()
    for c in cls.__mro__:
        init = c.__dict__.get("__init__")
        if init is None:
            continue
        for name, p in inspect.signature(init).parameters.items():
            if name == "self" or p.kind in (p.VAR_KEYWORD, p.VAR_POSITIONAL):
                continue
            keys.add(name)
    cached = _KNOWN_KWARGS_CACHE[cls] = frozenset(keys)
    return cached


def _reject_unknown_kwargs(cls, extra: Dict[str, Any]) -> None:
    """Typo'd/unknown config keys fail loudly with a did-you-mean instead
    of an opaque TypeError (or, worse, silently configuring nothing)."""
    if not extra:
        return
    known = sorted(_known_kwargs(cls))
    parts = []
    for k in sorted(extra):
        close = difflib.get_close_matches(k, known, n=1)
        parts.append(f"'{k}'" + (f" (did you mean '{close[0]}'?)"
                                 if close else ""))
    raise TypeError(f"{cls.__name__}: unknown config key(s) "
                    f"{', '.join(parts)}; known keys: {', '.join(known)}")


class Layer:
    """Base layer config. Subclasses define params + forward."""

    input_kind: Optional[str] = "ff"
    has_params = True
    #: compute layout for spatial (4-D) inputs. "NCHW" is the reference's
    #: public layout everywhere; the networks' ``setComputeLayout("NHWC")``
    #: stamps layout-aware layers with an instance attribute so conv/pool/
    #: BN/LRN paths run channels-minor on the MXU while the public API
    #: (weights [O,I,kH,kW], inputs/outputs NCHW) is unchanged — the
    #: forward transposes once at each layout boundary.
    data_format = "NCHW"

    def __init__(self, nOut: int = None, nIn: int = None, activation: str = None,
                 weightInit: str = None, biasInit: float = 0.0,
                 dropOut: float = 0.0, l1: float = None, l2: float = None,
                 name: str = None, tiedWith: str = None,
                 dataType: str = None, **extra):
        _reject_unknown_kwargs(type(self), extra)
        self.nOut = nOut
        self.nIn = nIn
        self.activation = activation
        self.weight_init = weightInit
        self.bias_init = biasInit
        self.dropout = dropOut       # RETAIN probability (reference semantics)
        self.l1 = l1
        self.l2 = l2
        self.name = name or type(self).__name__
        # weight-tie group label: layers sharing one group must land on
        # the same pipeline stage (analysis/distribution.py E103)
        self.tied_with = tiedWith
        # per-layer dtype override under a PrecisionPolicy: "float32"
        # declares an explicit fp32 island, anything contradicting the
        # network policy is the analysis pass's E301/W301 material
        if dataType is not None:
            from deeplearning4j_tpu.nn.precision import normalize_dtype
            dataType = normalize_dtype(dataType)
        self.dtype_override = dataType

    # -- config plumbing --
    def set_defaults(self, base):
        if self.activation is None:
            self.activation = base.activation
        if self.weight_init is None:
            self.weight_init = base.weight_init
        if self.l1 is None:
            self.l1 = base.l1
        if self.l2 is None:
            self.l2 = base.l2

    def infer_nin(self, it: InputType):
        if self.nIn is None and it.kind in ("ff", "cnn_flat"):
            self.nIn = it.arrayElementsPerExample()
        elif self.nIn is None and it.kind == "cnn":
            self.nIn = it.channels
        elif self.nIn is None and it.kind == "rnn":
            self.nIn = it.size

    def expected_nin(self, it: InputType) -> Optional[int]:
        """Declared-shape hook for ``analysis/``: the nIn this layer's
        ``infer_nin`` would derive from ``it``, computed on a throwaway
        copy so the static linter can compare a user-declared nIn against
        the propagated input WITHOUT mutating the config. May raise —
        subclasses' infer_nin validates geometry (the analyzer maps the
        exception to a diagnostic)."""
        import copy
        probe = copy.deepcopy(self)
        probe.nIn = None
        probe.infer_nin(it)
        return probe.nIn

    def mxu_lane_dims(self):
        """Declared-shape hook for the TPU layout lints: the lane
        (minor-most) dims of this layer's MXU matmuls. Default: nOut for
        any param-bearing layer; elementwise param layers override to []
        and gated recurrent layers report their fused gate width."""
        return [self.nOut] if self.has_params and self.nOut else []

    def param_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """Declared parameter shapes WITHOUT initializing anything — the
        jax-free static hook ``analysis/distribution.py`` sizes shards,
        HBM footprints, and FLOP estimates from. Dense-ish default
        (W [nIn, nOut] + optional b [nOut]); geometry-bearing subclasses
        override to match their ``initialize``. Returns {} while
        nIn/nOut are unresolved."""
        if not self.has_params or not self.nIn or not self.nOut:
            return {}
        shapes = {"W": (self.nIn, self.nOut)}
        if getattr(self, "has_bias", True):
            shapes["b"] = (self.nOut,)
        return shapes

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(self.nOut)

    # -- runtime --
    def initialize(self, key) -> Tuple[Dict, Dict]:
        return {}, {}

    def apply(self, params, state, x, train: bool, key):
        raise NotImplementedError

    def _maybe_dropout(self, x, train, key):
        if self.dropout and self.dropout < 1.0:
            return norm_ops.dropout(x, 1.0 - self.dropout, key, train=train)
        return x

    def n_params(self, params) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))

    # -- serialization --
    def to_config(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if isinstance(v, tuple):
                v = list(v)
            d[k] = v
        return d

    @classmethod
    def from_config(cls, d):
        obj = cls.__new__(cls)
        for k, v in d.items():
            if k == "@class":
                continue
            if isinstance(v, list) and k in ("kernel", "stride", "padding",
                                             "dilation", "scale", "crop",
                                             "dims"):
                v = tuple(v)
            setattr(obj, k, v)
        return obj

    def __repr__(self):
        return f"{type(self).__name__}(nIn={self.nIn}, nOut={self.nOut})"


class DenseLayer(Layer):
    """ref: layers.feedforward.dense.DenseLayer — W [nIn, nOut], out = act(xW + b)."""

    def __init__(self, nOut=None, hasBias: bool = True, **kw):
        super().__init__(nOut=nOut, **kw)
        self.has_bias = hasBias

    def initialize(self, key):
        params = {"W": _initialize((self.nIn, self.nOut), self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def apply(self, params, state, x, train, key):
        x = self._maybe_dropout(x, train, key)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return act.get(self.activation)(z), state


class EmbeddingLayer(Layer):
    """ref: layers.feedforward.embedding.EmbeddingLayer — int indices [N] or
    one-hot rows -> embedding vectors [N, nOut]."""

    def __init__(self, nOut=None, hasBias: bool = False, **kw):
        super().__init__(nOut=nOut, **kw)
        self.has_bias = hasBias

    def initialize(self, key):
        params = {"W": _initialize((self.nIn, self.nOut), self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def apply(self, params, state, x, train, key):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim == 2 and x.shape[1] == self.nIn:
            out = x @ params["W"]  # one-hot rows
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim == 2 and idx.shape[1] == 1:
                idx = idx[:, 0]
            out = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            out = out + params["b"]
        return act.get(self.activation)(out), state


class EmbeddingSequenceLayer(Layer):
    """ref: EmbeddingSequenceLayer — [N, T] int -> [N, nOut, T] (NCW)."""

    input_kind = None

    def initialize(self, key):
        return {"W": _initialize((self.nIn, self.nOut), self.weight_init, key)}, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        return {"W": (self.nIn, self.nOut)}

    def apply(self, params, state, x, train, key):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:  # [N, 1, T]
            idx = idx[:, 0, :]
        emb = jnp.take(params["W"], idx, axis=0)  # [N, T, nOut]
        return jnp.transpose(emb, (0, 2, 1)), state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1) if it.kind == "rnn" else it.dims.get("size", -1)
        return InputType.recurrent(self.nOut, t)


class ConvolutionLayer(Layer):
    """ref: layers.convolution.ConvolutionLayer — NCHW, W [nOut, nIn, kH, kW]."""

    input_kind = "cnn"

    def __init__(self, kernelSize=(3, 3), stride=(1, 1), padding=(0, 0),
                 nOut=None, dilation=(1, 1), convolutionMode: str = "truncate",
                 hasBias: bool = True, **kw):
        super().__init__(nOut=nOut, **kw)
        self.kernel = _pair(kernelSize)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.dilation = _pair(dilation)
        self.mode = convolutionMode
        self.has_bias = hasBias

    class Builder:
        def __init__(self, *kernel):
            self._kw = {"kernelSize": kernel if kernel else (3, 3)}

        def nIn(self, v): self._kw["nIn"] = v; return self
        def nOut(self, v): self._kw["nOut"] = v; return self
        def stride(self, *s): self._kw["stride"] = s; return self
        def padding(self, *p): self._kw["padding"] = p; return self
        def activation(self, a): self._kw["activation"] = a; return self
        def convolutionMode(self, m): self._kw["convolutionMode"] = m; return self
        def weightInit(self, w): self._kw["weightInit"] = w; return self
        def name(self, n): self._kw["name"] = n; return self
        def build(self): return ConvolutionLayer(**self._kw)

    def initialize(self, key):
        shape = (self.nOut, self.nIn) + self.kernel
        params = {"W": _initialize(shape, self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        shapes = {"W": (self.nOut, self.nIn) + tuple(self.kernel)}
        if self.has_bias:
            shapes["b"] = (self.nOut,)
        return shapes

    def apply(self, params, state, x, train, key, *, skip_bias=False):
        x = self._maybe_dropout(x, train, key)
        out = conv_ops.conv2d(x, params["W"],
                              None if skip_bias else params.get("b"),
                              stride=self.stride, pad=self.padding,
                              dilation=self.dilation, mode=self.mode,
                              data_format=self.data_format)
        return act.get(self.activation)(out), state

    def output_type(self, it: InputType) -> InputType:
        h = conv_ops.conv_output_size(it.height, self.kernel[0], self.stride[0],
                                      self.padding[0], self.dilation[0], self.mode)
        w = conv_ops.conv_output_size(it.width, self.kernel[1], self.stride[1],
                                      self.padding[1], self.dilation[1], self.mode)
        return InputType.convolutional(h, w, self.nOut)


class Deconvolution2D(ConvolutionLayer):
    """ref: layers.convolution.Deconvolution2DLayer."""

    def apply(self, params, state, x, train, key):
        out = conv_ops.deconv2d(x, params["W"], params.get("b"),
                                stride=self.stride, pad=self.padding,
                                mode=self.mode, data_format=self.data_format)
        return act.get(self.activation)(out), state

    def output_type(self, it: InputType) -> InputType:
        if self.mode.lower() == "same":
            h, w = it.height * self.stride[0], it.width * self.stride[1]
        else:
            h = (it.height - 1) * self.stride[0] + self.kernel[0] - 2 * self.padding[0]
            w = (it.width - 1) * self.stride[1] + self.kernel[1] - 2 * self.padding[1]
        return InputType.convolutional(h, w, self.nOut)


class DepthwiseConvolution2D(ConvolutionLayer):
    """ref: DepthwiseConvolution2DLayer — W [mult, nIn, kH, kW]."""

    def __init__(self, depthMultiplier: int = 1, **kw):
        super().__init__(**kw)
        self.depth_multiplier = depthMultiplier

    def infer_nin(self, it):
        super().infer_nin(it)
        if self.nOut is None:
            self.nOut = self.nIn * self.depth_multiplier

    def initialize(self, key):
        shape = (self.depth_multiplier, self.nIn) + self.kernel
        params = {"W": _initialize(shape, self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        shapes = {"W": (self.depth_multiplier, self.nIn) + tuple(self.kernel)}
        if self.has_bias:
            shapes["b"] = (self.nOut,)
        return shapes

    def apply(self, params, state, x, train, key):
        out = conv_ops.depthwise_conv2d(x, params["W"], params.get("b"),
                                        stride=self.stride, pad=self.padding,
                                        dilation=self.dilation, mode=self.mode,
                                        data_format=self.data_format)
        return act.get(self.activation)(out), state


class SeparableConvolution2D(ConvolutionLayer):
    """ref: SeparableConvolution2DLayer — depthwise + pointwise."""

    def __init__(self, depthMultiplier: int = 1, **kw):
        super().__init__(**kw)
        self.depth_multiplier = depthMultiplier

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        params = {
            "Wd": _initialize((self.depth_multiplier, self.nIn) + self.kernel,
                              self.weight_init, k1),
            "Wp": _initialize((self.nOut, self.nIn * self.depth_multiplier, 1, 1),
                              self.weight_init, k2),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        shapes = {"Wd": (self.depth_multiplier, self.nIn) + tuple(self.kernel),
                  "Wp": (self.nOut, self.nIn * self.depth_multiplier, 1, 1)}
        if self.has_bias:
            shapes["b"] = (self.nOut,)
        return shapes

    def apply(self, params, state, x, train, key):
        out = conv_ops.separable_conv2d(x, params["Wd"], params["Wp"],
                                        params.get("b"), stride=self.stride,
                                        pad=self.padding, dilation=self.dilation,
                                        mode=self.mode,
                                        data_format=self.data_format)
        return act.get(self.activation)(out), state


class SubsamplingLayer(Layer):
    """ref: layers.subsampling.SubsamplingLayer (max/avg/pnorm pooling)."""

    input_kind = "cnn"
    has_params = False

    def __init__(self, poolingType: str = "max", kernelSize=(2, 2), stride=(2, 2),
                 padding=(0, 0), convolutionMode: str = "truncate", pnorm: int = 2, **kw):
        super().__init__(**kw)
        self.pooling = poolingType.lower()
        self.kernel = _pair(kernelSize)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.mode = convolutionMode
        self.pnorm = pnorm

    class Builder:
        def __init__(self, poolingType="max", *kernel):
            self._kw = {"poolingType": poolingType}
            if kernel:
                self._kw["kernelSize"] = kernel

        def kernelSize(self, *k): self._kw["kernelSize"] = k; return self
        def stride(self, *s): self._kw["stride"] = s; return self
        def padding(self, *p): self._kw["padding"] = p; return self
        def build(self): return SubsamplingLayer(**self._kw)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        fn = {"max": conv_ops.maxpool2d, "avg": conv_ops.avgpool2d,
              "pnorm": conv_ops.pnormpool2d}[self.pooling]
        kw = {"kernel": self.kernel, "stride": self.stride, "pad": self.padding,
              "mode": self.mode, "data_format": self.data_format}
        if self.pooling == "pnorm":
            kw["pnorm"] = self.pnorm
        return fn(x, **kw), state

    def output_type(self, it: InputType) -> InputType:
        h = conv_ops.conv_output_size(it.height, self.kernel[0], self.stride[0],
                                      self.padding[0], 1, self.mode)
        w = conv_ops.conv_output_size(it.width, self.kernel[1], self.stride[1],
                                      self.padding[1], 1, self.mode)
        return InputType.convolutional(h, w, it.channels)


class BatchNormalization(Layer):
    """ref: layers.normalization.BatchNormalization — running stats carried
    functionally in layer state (decay default 0.9 like the reference)."""

    input_kind = None
    has_params = True

    def __init__(self, decay: float = 0.9, eps: float = 1e-5, **kw):
        super().__init__(**kw)
        self.decay = decay
        self.eps = eps

    def infer_nin(self, it: InputType):
        if it.kind == "cnn":
            self.nIn = self.nOut = it.channels
        else:
            self.nIn = self.nOut = it.arrayElementsPerExample()

    def initialize(self, key):
        n = self.nIn
        params = {"gamma": jnp.ones((n,)), "beta": jnp.zeros((n,))}
        state = {"mean": jnp.zeros((n,)), "var": jnp.ones((n,))}
        return params, state

    def mxu_lane_dims(self):
        return []   # elementwise scale/shift — no matmul

    def param_shapes(self):
        if not self.nIn:
            return {}
        return {"gamma": (self.nIn,), "beta": (self.nIn,)}

    def _channel_axis(self, x) -> int:
        if x.ndim == 4 and self.data_format == "NHWC":
            return x.ndim - 1
        return 1 if x.ndim >= 3 else x.ndim - 1

    def apply(self, params, state, x, train, key):
        # mixed-precision island handled inside the ops: stats accumulate
        # fp32, the normalize is an FMA in x.dtype (no fp32 activation copy)
        axis = self._channel_axis(x)
        if train:
            out, new_mean, new_var = norm_ops.batch_norm_train(
                x, params["gamma"], params["beta"], state["mean"], state["var"],
                eps=self.eps, decay=self.decay, axis=axis)
            return out, {"mean": new_mean, "var": new_var}
        out = norm_ops.batch_norm(x, params["gamma"], params["beta"],
                                  state["mean"], state["var"], eps=self.eps,
                                  axis=axis)
        return out, state

    def output_type(self, it: InputType) -> InputType:
        return it


class LocalResponseNormalization(Layer):
    """ref: layers.normalization.LocalResponseNormalization."""

    input_kind = "cnn"
    has_params = False

    def __init__(self, n: int = 5, alpha: float = 1e-4, beta: float = 0.75,
                 k: float = 2.0, **kw):
        super().__init__(**kw)
        self.n = n
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        return norm_ops.lrn(x, depth=self.n, alpha=self.alpha, beta=self.beta,
                            bias=self.k, data_format=self.data_format), state

    def output_type(self, it):
        return it


class ActivationLayer(Layer):
    """ref: layers.ActivationLayer."""

    input_kind = None
    has_params = False

    def __init__(self, activation="relu", **kw):
        super().__init__(activation=activation, **kw)

    def set_defaults(self, base):
        pass  # keeps its own activation

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        return act.get(self.activation)(x), state

    def output_type(self, it):
        return it


class DropoutLayer(Layer):
    """ref: layers.DropoutLayer — dropOut value is the RETAIN probability."""

    input_kind = None
    has_params = False

    def __init__(self, dropOut=0.5, **kw):
        super().__init__(dropOut=dropOut, **kw)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        return self._maybe_dropout(x, train, key), state

    def output_type(self, it):
        return it


class SpatialDropoutLayer(Layer):
    """Channel dropout: zeroes WHOLE feature maps per example (ref:
    SpatialDropout in the reference's dropout family / Keras
    SpatialDropout1D-3D semantics). ``rate`` is the DROP probability.
    Input layout [N, C, *spatial]."""

    input_kind = None
    has_params = False

    def __init__(self, rate=0.5, **kw):
        super().__init__(**kw)
        self.rate = float(rate)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        if not train or self.rate <= 0.0:
            return x, state
        keep = 1.0 - self.rate
        shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        mask = jax.random.bernoulli(key, keep, shape).astype(x.dtype)
        return x * mask / keep, state

    def output_type(self, it):
        return it


class ZeroPaddingLayer(Layer):
    """ref: layers.ZeroPaddingLayer."""

    input_kind = "cnn"
    has_params = False

    def __init__(self, padding=(1, 1), **kw):
        super().__init__(**kw)
        if isinstance(padding, int):
            self.pad = (padding, padding)
        elif all(isinstance(p, (int, np.integer)) for p in padding):
            self.pad = tuple(int(p) for p in padding)
        else:   # asymmetric ((top, bottom), (left, right))
            self.pad = tuple(tuple(int(v) for v in p) for p in padding)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        return conv_ops.zero_padding2d(x, self.pad,
                                       data_format=self.data_format), state

    def output_type(self, it):
        p = self.pad
        if isinstance(p[0], int):
            return InputType.convolutional(it.height + 2 * p[0], it.width + 2 * p[1],
                                           it.channels)
        return InputType.convolutional(it.height + sum(p[0]), it.width + sum(p[1]),
                                       it.channels)


class Upsampling2D(Layer):
    """ref: layers.Upsampling2D."""

    input_kind = "cnn"
    has_params = False

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.scale = _pair(size)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        return conv_ops.upsampling2d(x, self.scale,
                                     data_format=self.data_format), state

    def output_type(self, it):
        return InputType.convolutional(it.height * self.scale[0],
                                       it.width * self.scale[1], it.channels)


class Cropping2D(Layer):
    """ref: layers.convolutional.Cropping2D."""

    input_kind = "cnn"
    has_params = False

    def __init__(self, crop=(1, 1), **kw):
        super().__init__(**kw)
        self.crop = tuple(crop)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        return conv_ops.cropping2d(x, self.crop,
                                   data_format=self.data_format), state

    def output_type(self, it):
        c = self.crop
        if isinstance(c[0], int):
            return InputType.convolutional(it.height - 2 * c[0], it.width - 2 * c[1],
                                           it.channels)
        return InputType.convolutional(it.height - sum(c[0]), it.width - sum(c[1]),
                                       it.channels)


class GlobalPoolingLayer(Layer):
    """ref: layers.pooling.GlobalPoolingLayer — cnn [N,C,H,W] -> [N,C] or
    rnn [N,C,T] -> [N,C]; supports masks for rnn input."""

    input_kind = None
    has_params = False

    def __init__(self, poolingType: str = "max", **kw):
        super().__init__(**kw)
        self.pooling = poolingType.lower()

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels if it.kind in ("cnn", "cnn3d") \
            else it.size if it.kind == "rnn" else it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key, mask=None):
        # the NHWC stamp only applies to spatial input; rnn [N,C,T] input
        # stays channels-second regardless of the compute layout
        fmt = self.data_format if x.ndim == 4 else "NCHW"
        return conv_ops.global_pool(x, self.pooling, data_format=fmt,
                                    mask=mask), state

    def output_type(self, it):
        n = it.channels if it.kind in ("cnn", "cnn3d") else it.size
        return InputType.feedForward(n)


# ------------------------------------------------------------------ recurrent
class LSTM(Layer):
    """ref: layers.recurrent.LSTM — input [N, nIn, T] -> [N, nOut, T].
    Forget-gate bias initialized to 1.0 like the reference."""

    input_kind = "rnn"

    def __init__(self, nOut=None, forgetGateBiasInit: float = 1.0, **kw):
        super().__init__(nOut=nOut, **kw)
        self.forget_bias = forgetGateBiasInit
        if self.activation is None:
            self.activation = "tanh"

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "tanh"

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        H = self.nOut
        b = np.zeros((4 * H,), np.float32)
        b[H:2 * H] = self.forget_bias  # gate order [i, f, g, o]
        params = {
            "W": _initialize((self.nIn, 4 * H), self.weight_init, k1),
            "RW": _initialize((H, 4 * H), self.weight_init, k2),
            "b": jnp.asarray(b),
        }
        return params, {}

    def mxu_lane_dims(self):
        return [4 * self.nOut] if self.nOut else []   # fused [i,f,g,o] gates

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        H = self.nOut
        return {"W": (self.nIn, 4 * H), "RW": (H, 4 * H), "b": (4 * H,)}

    def apply(self, params, state, x, train, key, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))  # [N,C,T] -> [T,N,C]
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        outs, _ = rnn_ops.lstm(x_tnc, params["W"], params["RW"], params["b"],
                               mask_tn=mask_tn)
        return jnp.transpose(outs, (1, 2, 0)), state  # [T,N,H] -> [N,H,T]

    def apply_with_state(self, params, x, rnn_state, mask=None):
        """Streaming forward carrying (h, c) across calls
        (ref: MultiLayerNetwork.rnnTimeStep state keeping)."""
        x_tnc = jnp.transpose(x, (2, 0, 1))
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        h0 = c0 = None
        if rnn_state is not None:
            h0, c0 = rnn_state
        outs, (hT, cT) = rnn_ops.lstm(x_tnc, params["W"], params["RW"],
                                      params["b"], h0=h0, c0=c0, mask_tn=mask_tn)
        return jnp.transpose(outs, (1, 2, 0)), (hT, cT)

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class GRU(Layer):
    """ref: layers.recurrent.GRU (gruCell op underneath) — input
    [N, nIn, T] -> [N, nOut, T], gate order [r, z, n] like the reference's
    libnd4j gruCell (and torch)."""

    input_kind = "rnn"

    def __init__(self, nOut=None, **kw):
        super().__init__(nOut=nOut, **kw)
        if self.activation in (None, "identity"):
            self.activation = "tanh"

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "tanh"

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        H = self.nOut
        params = {
            "W": _initialize((self.nIn, 3 * H), self.weight_init, k1),
            "RW": _initialize((H, 3 * H), self.weight_init, k2),
            "b": jnp.zeros((3 * H,), jnp.float32),
            "bR": jnp.zeros((3 * H,), jnp.float32),
        }
        return params, {}

    def mxu_lane_dims(self):
        return [3 * self.nOut] if self.nOut else []   # fused [r,z,n] gates

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        H = self.nOut
        return {"W": (self.nIn, 3 * H), "RW": (H, 3 * H),
                "b": (3 * H,), "bR": (3 * H,)}

    def apply(self, params, state, x, train, key, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        outs, _ = rnn_ops.gru(x_tnc, params["W"], params["RW"], params["b"],
                              params["bR"], mask_tn=mask_tn)
        return jnp.transpose(outs, (1, 2, 0)), state

    def apply_with_state(self, params, x, rnn_state, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        outs, hT = rnn_ops.gru(x_tnc, params["W"], params["RW"], params["b"],
                               params["bR"], h0=rnn_state, mask_tn=mask_tn)
        return jnp.transpose(outs, (1, 2, 0)), hT

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class ConvLSTM2D(Layer):
    """Convolutional LSTM over image sequences (ref: the reference's
    KerasConvLSTM2D import target). Input [N, C, T, H, W] (cnn3d layout,
    depth = time); output [N, nOut, H', W'] (last state) or
    [N, nOut, T, H', W'] with ``returnSequences``. Input convs use the
    configured padding/stride; recurrent convs are SAME-padded on the
    state grid (Keras semantics). Gate order [i, f, g, o]."""

    input_kind = "cnn3d"

    def __init__(self, nOut=None, kernelSize=(3, 3), stride=(1, 1),
                 convolutionMode: str = "truncate",
                 returnSequences: bool = False,
                 forgetGateBiasInit: float = 1.0, **kw):
        super().__init__(nOut=nOut, **kw)
        self.kernel = _pair(kernelSize)
        self.stride = _pair(stride)
        self.mode = convolutionMode
        self.return_sequences = returnSequences
        self.forget_bias = forgetGateBiasInit

    def infer_nin(self, it: InputType):
        self.nIn = it.channels

    def mxu_lane_dims(self):
        return [4 * self.nOut] if self.nOut else []

    def param_shapes(self):
        """Gate convs, matching ``initialize`` exactly — the base class's
        dense [nIn, nOut] guess undercounted both the HBM footprint and
        the W105 FLOP estimate for conv-LSTM stages."""
        if not self.nIn or not self.nOut:
            return {}
        H = self.nOut
        return {"W": (4 * H, self.nIn) + self.kernel,
                "RW": (4 * H, H) + self.kernel,
                "b": (4 * H,)}

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        H = self.nOut
        b = np.zeros((4 * H,), np.float32)
        b[H:2 * H] = self.forget_bias
        params = {
            "W": _initialize((4 * H, self.nIn) + self.kernel,
                             self.weight_init, k1),
            "RW": _initialize((4 * H, H) + self.kernel,
                              self.weight_init, k2),
            "b": jnp.asarray(b),
        }
        return params, {}

    def apply(self, params, state, x, train, key):
        H = self.nOut
        x_t = jnp.moveaxis(x, 2, 0)              # [T, N, C, H, W]
        # hoist the time-parallel input convs out of the recurrence
        T, N = x_t.shape[0], x_t.shape[1]
        xg = conv_ops.conv2d(
            x_t.reshape((T * N,) + x_t.shape[2:]), params["W"], params["b"],
            stride=self.stride, pad=(0, 0), mode=self.mode)
        xg = xg.reshape((T, N) + xg.shape[1:])   # [T, N, 4H, H', W']
        sp = xg.shape[3:]

        ret_seq = self.return_sequences

        def step(carry, g_in):
            h, c = carry
            gates = g_in + conv_ops.conv2d(h, params["RW"], None,
                                           stride=(1, 1), mode="same")
            i, f, g, o = jnp.split(gates, 4, axis=1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            # only stack per-step outputs when the caller wants sequences
            # (a [T, N, H, H', W'] stack is T x the necessary memory)
            return (h, c), (h if ret_seq else None)

        h0 = jnp.zeros((N, H) + sp, xg.dtype)
        (h_last, _), hs = jax.lax.scan(step, (h0, h0), xg)
        if ret_seq:
            return jnp.moveaxis(hs, 0, 2), state  # [N, H, T, H', W']
        return h_last, state

    def output_type(self, it: InputType) -> InputType:
        h = conv_ops.conv_output_size(it.height, self.kernel[0],
                                      self.stride[0], 0, 1, self.mode)
        w = conv_ops.conv_output_size(it.width, self.kernel[1],
                                      self.stride[1], 0, 1, self.mode)
        if self.return_sequences:
            return InputType.convolutional3D(it.depth, h, w, self.nOut)
        return InputType.convolutional(h, w, self.nOut)


class Convolution1D(Layer):
    """ref: layers.convolution.Convolution1DLayer — input [N, nIn, T]
    (NCW), W [nOut, nIn, k]; supports causal mode like the reference."""

    input_kind = "rnn"

    def __init__(self, kernelSize: int = 3, stride: int = 1, padding: int = 0,
                 nOut=None, dilation: int = 1, convolutionMode: str = "same",
                 hasBias: bool = True, **kw):
        super().__init__(nOut=nOut, **kw)
        self.kernel = int(kernelSize if not isinstance(kernelSize, (tuple, list))
                          else kernelSize[0])
        self.stride = int(stride if not isinstance(stride, (tuple, list))
                          else stride[0])
        self.padding = int(padding if not isinstance(padding, (tuple, list))
                           else padding[0])
        self.dilation = int(dilation if not isinstance(dilation, (tuple, list))
                            else dilation[0])
        self.mode = convolutionMode
        self.has_bias = hasBias

    def initialize(self, key):
        params = {"W": _initialize((self.nOut, self.nIn, self.kernel),
                                   self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        shapes = {"W": (self.nOut, self.nIn, self.kernel)}
        if self.has_bias:
            shapes["b"] = (self.nOut,)
        return shapes

    def apply(self, params, state, x, train, key, mask=None):
        out = conv_ops.conv1d(x, params["W"], params.get("b"),
                              stride=self.stride, pad=self.padding,
                              dilation=self.dilation, mode=self.mode)
        return act.get(self.activation)(out), state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1)
        if t and t > 0:
            t = conv_ops.conv_output_size(t, self.kernel, self.stride,
                                          self.padding, self.dilation,
                                          self.mode)
        return InputType.recurrent(self.nOut, t)


class GravesLSTM(LSTM):
    """ref: layers.recurrent.GravesLSTM (legacy peephole variant; the
    peephole connections are omitted — reference deprecated it in favor of
    LSTM, and their effect is negligible; kept for API parity)."""


class SimpleRnn(Layer):
    """ref: layers.recurrent.SimpleRnn."""

    input_kind = "rnn"

    def __init__(self, nOut=None, **kw):
        super().__init__(nOut=nOut, **kw)
        if self.activation is None:
            self.activation = "tanh"

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "tanh"

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        params = {
            "W": _initialize((self.nIn, self.nOut), self.weight_init, k1),
            "RW": _initialize((self.nOut, self.nOut), self.weight_init, k2),
            "b": jnp.zeros((self.nOut,)),
        }
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        return {"W": (self.nIn, self.nOut), "RW": (self.nOut, self.nOut),
                "b": (self.nOut,)}

    def apply(self, params, state, x, train, key, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        outs, _ = rnn_ops.simple_rnn(x_tnc, params["W"], params["RW"], params["b"],
                                     mask_tn=mask_tn,
                                     activation=act.get(self.activation))
        return jnp.transpose(outs, (1, 2, 0)), state

    def apply_with_state(self, params, x, rnn_state, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))
        mask_tn = jnp.transpose(mask, (1, 0)) if mask is not None else None
        h0 = rnn_state
        outs, hT = rnn_ops.simple_rnn(x_tnc, params["W"], params["RW"],
                                      params["b"], h0=h0, mask_tn=mask_tn,
                                      activation=act.get(self.activation))
        return jnp.transpose(outs, (1, 2, 0)), hT

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class Bidirectional(Layer):
    """ref: layers.recurrent.Bidirectional — wraps a recurrent layer,
    merge modes CONCAT/ADD/MUL/AVERAGE."""

    input_kind = "rnn"

    def __init__(self, rnn_layer: Layer, mode: str = "concat", **kw):
        super().__init__(**kw)
        self.fwd = rnn_layer
        import copy
        self.bwd = copy.deepcopy(rnn_layer)
        self.mode = mode.lower()

    def set_defaults(self, base):
        self.fwd.set_defaults(base)
        self.bwd.set_defaults(base)

    def infer_nin(self, it):
        self.fwd.infer_nin(it)
        self.bwd.infer_nin(it)
        self.nIn = self.fwd.nIn
        self.nOut = self.fwd.nOut * (2 if self.mode == "concat" else 1)

    def mxu_lane_dims(self):
        return self.fwd.mxu_lane_dims() + self.bwd.mxu_lane_dims()

    def param_shapes(self):
        out = {f"fwd/{k}": v for k, v in self.fwd.param_shapes().items()}
        out.update({f"bwd/{k}": v for k, v in self.bwd.param_shapes().items()})
        return out

    def initialize(self, key):
        k1, k2 = jax.random.split(key)
        pf, _ = self.fwd.initialize(k1)
        pb, _ = self.bwd.initialize(k2)
        return {"fwd": pf, "bwd": pb}, {}

    def apply(self, params, state, x, train, key, mask=None):
        yf, _ = self.fwd.apply(params["fwd"], {}, x, train, key, mask=mask)
        x_rev = jnp.flip(x, axis=2)
        mask_rev = jnp.flip(mask, axis=1) if mask is not None else None
        yb, _ = self.bwd.apply(params["bwd"], {}, x_rev, train, key, mask=mask_rev)
        yb = jnp.flip(yb, axis=2)
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=1), state
        if self.mode == "add":
            return yf + yb, state
        if self.mode == "mul":
            return yf * yb, state
        if self.mode == "average":
            return 0.5 * (yf + yb), state
        raise ValueError(self.mode)

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))

    def to_config(self):
        # class-aware: subclasses (BidirectionalLastStep) round-trip intact
        return {"@class": type(self).__name__, "mode": self.mode,
                "fwd": self.fwd.to_config(), "bwd": self.bwd.to_config(),
                "name": self.name, "nIn": self.nIn, "nOut": self.nOut}

    @classmethod
    def from_config(cls, d):
        inner = layer_from_config(d["fwd"])
        obj = cls(inner, mode=d["mode"])
        if "bwd" in d:    # independently-weighted directions (Keras import)
            obj.bwd = layer_from_config(d["bwd"])
        obj.nIn, obj.nOut = d.get("nIn"), d.get("nOut")
        return obj


class BidirectionalLastStep(Bidirectional):
    """Bidirectional collapsed to one step with KERAS semantics: the
    forward direction's LAST output merged with the backward direction's
    FINAL state (which corresponds to input position 0). NOTE this differs
    from LastTimeStep(Bidirectional(...)), which takes position T-1 of
    both directions (the reference's composition); this class exists for
    Keras model import parity."""

    def apply(self, params, state, x, train, key, mask=None):
        if mask is not None:
            raise ValueError("BidirectionalLastStep does not support "
                             "sequence masks (imported-model inference "
                             "path); pad-free batches only")
        yf, _ = self.fwd.apply(params["fwd"], {}, x, train, key, mask=None)
        x_rev = jnp.flip(x, axis=2)
        yb, _ = self.bwd.apply(params["bwd"], {}, x_rev, train, key,
                               mask=None)
        f = yf[:, :, -1]
        b = yb[:, :, -1]       # last step of reversed run = state at t=0
        if self.mode == "concat":
            return jnp.concatenate([f, b], axis=1), state
        if self.mode == "add":
            return f + b, state
        if self.mode == "mul":
            return f * b, state
        return (f + b) / 2.0, state

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(self.nOut)


class LastTimeStep(Layer):
    """ref: layers.recurrent.LastTimeStep — wraps an RNN layer, returns
    its final (mask-aware) timestep as feedforward output."""

    input_kind = "rnn"

    def __init__(self, rnn_layer: Layer, **kw):
        super().__init__(**kw)
        self.inner = rnn_layer

    def set_defaults(self, base):
        self.inner.set_defaults(base)

    def infer_nin(self, it):
        self.inner.infer_nin(it)
        self.nIn, self.nOut = self.inner.nIn, self.inner.nOut

    def initialize(self, key):
        return self.inner.initialize(key)

    def apply(self, params, state, x, train, key, mask=None):
        y, state = self.inner.apply(params, state, x, train, key, mask=mask)
        if mask is not None:
            # index of last active timestep per example
            idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
            return y[jnp.arange(y.shape[0]), :, idx], state
        return y[:, :, -1], state

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(self.inner.nOut)

    def to_config(self):
        return {"@class": "LastTimeStep", "inner": self.inner.to_config(),
                "name": self.name, "nIn": self.nIn, "nOut": self.nOut}

    @classmethod
    def from_config(cls, d):
        obj = LastTimeStep(layer_from_config(d["inner"]))
        obj.nIn, obj.nOut = d.get("nIn"), d.get("nOut")
        return obj


# ------------------------------------------------------------------- outputs
class BaseOutputLayer(Layer):
    """Common loss plumbing (ref: BaseOutputLayer)."""

    def __init__(self, lossFunction: str = "mcxent", **kw):
        super().__init__(**kw)
        self.loss_fn = lossFunction

    def compute_loss(self, labels, preds, mask=None):
        # the stable fused path when activation is softmax/sigmoid + matching loss
        return loss_ops.get(self.loss_fn)(labels, preds, mask=mask)


class OutputLayer(BaseOutputLayer):
    """ref: layers.OutputLayer — dense + activation + loss."""

    def __init__(self, nOut=None, lossFunction="mcxent", hasBias: bool = True, **kw):
        super().__init__(lossFunction=lossFunction, nOut=nOut, **kw)
        self.has_bias = hasBias
        if self.activation is None:
            self.activation = "softmax"

    class Builder:
        def __init__(self, lossFunction="mcxent"):
            self._kw = {"lossFunction": lossFunction}

        def nIn(self, v): self._kw["nIn"] = v; return self
        def nOut(self, v): self._kw["nOut"] = v; return self
        def activation(self, a): self._kw["activation"] = a; return self
        def build(self): return OutputLayer(**self._kw)

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "softmax"

    def initialize(self, key):
        params = {"W": _initialize((self.nIn, self.nOut), self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def apply(self, params, state, x, train, key):
        x = self._maybe_dropout(x, train, key)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return act.get(self.activation)(z), state

    def pre_activation(self, params, x):
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z


class LossLayer(BaseOutputLayer):
    """ref: layers.LossLayer — activation + loss, no params."""

    has_params = False
    input_kind = None

    def __init__(self, lossFunction="mcxent", **kw):
        super().__init__(lossFunction=lossFunction, **kw)
        if self.activation is None:
            self.activation = "identity"

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        return act.get(self.activation)(x), state

    def output_type(self, it):
        return it


class RnnOutputLayer(BaseOutputLayer):
    """ref: layers.recurrent.RnnOutputLayer — per-timestep dense + loss.
    Input [N, nIn, T] -> [N, nOut, T]."""

    input_kind = "rnn"

    def __init__(self, nOut=None, lossFunction="mcxent", **kw):
        super().__init__(lossFunction=lossFunction, nOut=nOut, **kw)
        if self.activation is None:
            self.activation = "softmax"

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "softmax"

    def initialize(self, key):
        return {"W": _initialize((self.nIn, self.nOut), self.weight_init, key),
                "b": jnp.zeros((self.nOut,))}, {}

    def apply(self, params, state, x, train, key):
        # [N, C, T]: per-timestep projection = einsum over C
        z = jnp.einsum("nct,ch->nht", x, params["W"]) + params["b"][None, :, None]
        a = act.get(self.activation)(z, axis=1) if self.activation in ("softmax", "logsoftmax") \
            else act.get(self.activation)(z)
        return a, state

    def compute_loss(self, labels, preds, mask=None):
        """labels/preds [N, C, T]; mask [N, T]. The reference sums each
        example's per-timestep losses and divides by the minibatch size N
        (NOT by N*T) — preserved here so LR settings transfer from reference
        configs. Flattens time into batch for the loss kernel, then rescales
        the per-row mean back to sum-over-time / N."""
        n = labels.shape[0]
        lab = jnp.reshape(jnp.transpose(labels, (0, 2, 1)), (-1, labels.shape[1]))
        pre = jnp.reshape(jnp.transpose(preds, (0, 2, 1)), (-1, preds.shape[1]))
        m = jnp.reshape(mask, (-1,)) if mask is not None else None
        per_row_mean = loss_ops.get(self.loss_fn)(lab, pre, mask=m)
        n_rows = jnp.maximum(jnp.sum(m), 1.0) if m is not None else lab.shape[0]
        return per_row_mean * n_rows / n

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class PReLULayer(Layer):
    """ref: layers.feedforward.PReLULayer."""

    input_kind = None

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def mxu_lane_dims(self):
        return []   # elementwise slope — no matmul

    def param_shapes(self):
        return {"alpha": (self.nIn,)} if self.nIn else {}

    def initialize(self, key):
        return {"alpha": jnp.full((self.nIn,), 0.25)}, {}

    def apply(self, params, state, x, train, key):
        a = params["alpha"]
        if x.ndim == 4:  # NCHW: alpha per channel plane
            a = a.reshape(1, -1, 1, 1) if a.size == x.shape[1] else a.reshape((1,) + x.shape[1:])
        return jnp.where(x >= 0, x, a * x), state

    def output_type(self, it):
        return it


class Subsampling1DLayer(Layer):
    """ref: layers.subsampling.Subsampling1DLayer — [N, C, T] pooling.

    LIMITATION: sequence masks are not downsampled through the pool (the
    reference downsamples the mask alongside); a masked fit() with a
    strided pool before a mask-aware layer fails loudly on the length
    mismatch rather than silently mis-pooling padding."""

    input_kind = "rnn"
    has_params = False

    def __init__(self, poolingType: str = "max", kernelSize: int = 2,
                 stride: int = None, padding: int = 0,
                 convolutionMode: str = "truncate", **kw):
        super().__init__(**kw)
        self.pooling = poolingType.lower()
        self.kernel = int(kernelSize if not isinstance(kernelSize, (tuple, list))
                          else kernelSize[0])
        self.stride = int(stride if stride is not None else self.kernel)
        self.padding = int(padding)
        self.mode = convolutionMode

    def infer_nin(self, it):
        self.nIn = self.nOut = it.size

    def apply(self, params, state, x, train, key, mask=None):
        fn = conv_ops.maxpool1d if self.pooling == "max" else conv_ops.avgpool1d
        return fn(x, kernel=self.kernel, stride=self.stride,
                  pad=self.padding, mode=self.mode), state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1)
        if t and t > 0:
            t = conv_ops.conv_output_size(t, self.kernel, self.stride,
                                          self.padding, 1, self.mode)
        return InputType.recurrent(it.size, t)


class LayerNorm(Layer):
    """ref: layers.LayerNorm (a.k.a. Keras LayerNormalization) — per-sample
    normalization over the feature axis with learned gain/bias. Feature
    axis: -1 for [N, D], the CHANNEL axis (1) for [N, C, T]."""

    input_kind = None
    has_params = True

    def __init__(self, eps: float = 1e-5, **kw):
        super().__init__(**kw)
        self.eps = eps

    def infer_nin(self, it: InputType):
        if it.kind == "cnn":
            raise ValueError(
                "LayerNorm supports dense [N, D] and recurrent [N, C, T] "
                "inputs; 4-D CNN feature maps are not supported")
        self.nIn = self.nOut = it.size if it.kind == "rnn" \
            else it.arrayElementsPerExample()

    def mxu_lane_dims(self):
        return []   # elementwise gain/bias — no matmul

    def param_shapes(self):
        return {"gamma": (self.nIn,), "beta": (self.nIn,)} if self.nIn else {}

    def initialize(self, key):
        return {"gamma": jnp.ones((self.nIn,), jnp.float32),
                "beta": jnp.zeros((self.nIn,), jnp.float32)}, {}

    def _ln(self, x, params):
        # resolve through the registry so Pallas platform overrides apply
        from deeplearning4j_tpu.ops import registry as _registry
        return _registry.get("layer_norm")(x, params["gamma"],
                                           params["beta"], eps=self.eps)

    def apply(self, params, state, x, train, key, mask=None):
        if x.ndim == 3:   # [N, C, T]: normalize the channel axis
            xt = jnp.swapaxes(x, 1, 2)         # [N, T, C]
            return jnp.swapaxes(self._ln(xt, params), 1, 2), state
        return self._ln(x, params), state

    def output_type(self, it: InputType) -> InputType:
        return it


class GroupNorm(Layer):
    """Group normalization over channel groups (ref: the reference's
    GroupNormalization keras-import target; layout [N, C, *spatial],
    normalize within each of ``groups`` channel groups + spatial dims)."""

    input_kind = None
    has_params = True

    def __init__(self, groups: int = 32, eps: float = 1e-3, **kw):
        super().__init__(**kw)
        self.groups = int(groups)
        self.eps = eps

    def infer_nin(self, it: InputType):
        self.nIn = self.nOut = it.channels if it.kind in ("cnn", "cnn3d") \
            else it.size if it.kind == "rnn" else it.arrayElementsPerExample()
        if self.groups == -1:           # Keras shorthand: instance norm
            self.groups = self.nIn
        if self.groups < 1 or self.nIn % self.groups:
            raise ValueError(f"GroupNorm: {self.nIn} channels not divisible "
                             f"by {self.groups} groups")

    def mxu_lane_dims(self):
        return []   # elementwise gain/bias — no matmul

    def param_shapes(self):
        return {"gamma": (self.nIn,), "beta": (self.nIn,)} if self.nIn else {}

    def initialize(self, key):
        return {"gamma": jnp.ones((self.nIn,), jnp.float32),
                "beta": jnp.zeros((self.nIn,), jnp.float32)}, {}

    def apply(self, params, state, x, train, key):
        N, C = x.shape[0], x.shape[1]
        G = self.groups
        xg = x.reshape((N, G, C // G) + x.shape[2:]).astype(jnp.float32)
        axes = tuple(range(2, xg.ndim))
        m = jnp.mean(xg, axis=axes, keepdims=True)
        v = jnp.mean(jnp.square(xg - m), axis=axes, keepdims=True)
        y = ((xg - m) * jax.lax.rsqrt(v + self.eps)).reshape(x.shape)
        shape = (1, C) + (1,) * (x.ndim - 2)
        y = y * params["gamma"].reshape(shape) + params["beta"].reshape(shape)
        return y.astype(x.dtype), state

    def output_type(self, it: InputType) -> InputType:
        return it


class UnitNormLayer(Layer):
    """L2-normalize the channel/feature axis (Keras UnitNormalization)."""

    input_kind = None
    has_params = False

    def __init__(self, **kw):
        super().__init__(**kw)

    def infer_nin(self, it: InputType):
        self.nIn = self.nOut = it.channels if it.kind in ("cnn", "cnn3d") \
            else it.size if it.kind == "rnn" else it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        axis = 1 if x.ndim > 2 else -1
        n = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axis,
                             keepdims=True))
        return (x / jnp.maximum(n, 1e-12).astype(x.dtype)), state

    def output_type(self, it: InputType) -> InputType:
        return it


class Permute(Layer):
    """ref: Keras Permute — reorder NON-batch axes (1-based dims)."""

    input_kind = None
    has_params = False

    def __init__(self, dims=(2, 1), **kw):
        super().__init__(**kw)
        self.dims = tuple(int(d) for d in dims)

    def infer_nin(self, it):
        self.nIn = self.nOut = None

    def apply(self, params, state, x, train, key):
        perm = (0,) + self.dims
        return jnp.transpose(x, perm), state

    def output_type(self, it: InputType) -> InputType:
        if it.kind == "rnn" and self.dims == (2, 1):
            return InputType.recurrent(it.dims.get("timesteps", -1), it.size)
        return it


class RepeatVector(Layer):
    """ref: Keras RepeatVector — [N, D] -> [N, D, n] (NCW layout)."""

    input_kind = "ff"
    has_params = False

    def __init__(self, n: int = 2, **kw):
        super().__init__(**kw)
        self.n = int(n)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def apply(self, params, state, x, train, key):
        return jnp.repeat(x[:, :, None], self.n, axis=2), state

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, self.n)


_LAYER_CLASSES = {}
for _cls in [DenseLayer, EmbeddingLayer, EmbeddingSequenceLayer, ConvolutionLayer,
             Convolution1D, Subsampling1DLayer, LayerNorm, Permute,
             RepeatVector, Deconvolution2D, DepthwiseConvolution2D, SeparableConvolution2D,
             SubsamplingLayer, BatchNormalization, LocalResponseNormalization,
             ActivationLayer, DropoutLayer, ZeroPaddingLayer, Upsampling2D,
             Cropping2D, GlobalPoolingLayer, LSTM, GravesLSTM, GRU, SimpleRnn,
             Bidirectional, BidirectionalLastStep, LastTimeStep,
             OutputLayer, LossLayer, RnnOutputLayer,
             PReLULayer]:
    _LAYER_CLASSES[_cls.__name__] = _cls


def layer_from_config(d: Dict) -> Layer:
    cls = _LAYER_CLASSES[d["@class"]]
    return cls.from_config(d)


# ------------------------------------------------------------- dtype policy
# BASELINE.md's open perf item ("bf16 plumbing" in the nn/ stack): master
# parameters stay fp32 (updater math, BatchNorm statistics, losses), while
# matmul/conv/pool layers compute in bfloat16 — the MXU-native dtype
# (SURVEY.md §6). Enabled per-network via NeuralNetConfiguration.dataType
# ("bfloat16"); the cast happens inside the compiled step so XLA fuses it
# into the consuming convolution.

# Param-side fp32 islands: BatchNorm/LRN keep fp32 params and cast
# internally (activations stay bf16 through them); output/loss layers get
# fp32 activations AND fp32 params (softmax + loss numerics).
_POLICY_FP32_PARAM_LAYERS = (BatchNormalization, LocalResponseNormalization,
                             BaseOutputLayer)


def compute_dtype_of(conf_dtype) -> Optional[Any]:
    """None = no policy (pure fp32); jnp.bfloat16 = mixed-precision."""
    if str(conf_dtype).lower() in ("bfloat16", "bf16"):
        return jnp.bfloat16
    return None


def policy_cast(layer, params, x, compute_dt):
    """Cast (params, input) for one layer under the dtype policy.

    A per-layer ``dataType=`` override refines the policy: "float32"
    declares an explicit fp32 island (params and activations stay/return
    to fp32 through this layer); an override matching the compute dtype
    is a no-op.  Overrides that contradict the policy are the analysis
    pass's E301 — the runtime honors fp32 islands and policy-matching
    overrides only."""
    if compute_dt is None:
        return params, x
    override = getattr(layer, "dtype_override", None)
    if override == "float32" and not isinstance(layer, BaseOutputLayer):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
            x = x.astype(jnp.float32)
        elif x.dtype == jnp.uint8:
            x = x.astype(jnp.float32)
        return params, x
    if isinstance(layer, BaseOutputLayer):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
            x = x.astype(jnp.float32)
        return params, x
    if isinstance(layer, _POLICY_FP32_PARAM_LAYERS):
        return params, x
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != compute_dt:
        x = x.astype(compute_dt)
    elif x.dtype == jnp.uint8:
        # image bytes straight off the host pipeline: cast ON DEVICE (fused
        # into the first conv program) so the host ships 1/4 the bandwidth
        # and never pays a float conversion (data/pipeline.py)
        x = x.astype(compute_dt)
    if params:
        params = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dt)
            if getattr(a, "dtype", None) == jnp.float32 else a, params)
    return params, x


# ----------------------------------------------------------- compute layout
# NHWC seam (ISSUE 14): image convs on TPU want channels on the lane
# (minor-most) axis — XLA's NCHW lowering transposes internally per op or
# runs channel-padded tiles (the W101 story). The networks'
# ``setComputeLayout("NHWC")`` keeps the PUBLIC layout NCHW (inputs,
# outputs, weights [O,I,kH,kW], checkpoints) and transposes once at each
# layout boundary inside the compiled step; layout-aware layers carry a
# ``data_format`` stamp their apply reads.

#: layers whose apply computes natively in NHWC when stamped (the conv
#: family covers Deconvolution/Depthwise/Separable via subclassing)
LAYOUT_AWARE = (ConvolutionLayer, SubsamplingLayer, BatchNormalization,
                LocalResponseNormalization, ZeroPaddingLayer, Upsampling2D,
                Cropping2D, GlobalPoolingLayer)

#: elementwise layers that keep whatever layout flows in (no transpose)
LAYOUT_TRANSPARENT = (ActivationLayer, DropoutLayer)


def to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def layout_step(layer, x, cur_nhwc: bool, nhwc_active: bool):
    """THE transpose-at-boundary rule, one layer at a time: returns
    ``(x, now_nhwc)``. Aware layers pull spatial input into NHWC,
    transparent layers keep whatever flows in, everything else (dense,
    output heads, preprocess boundaries) forces NCHW back. Shared by the
    compiled forwards, ``feedForward``, the sanitizer's eager replay
    walkers, and the devicetime bridge so the mirrors cannot drift."""
    if getattr(x, "ndim", 0) != 4:
        return x, False
    want = (nhwc_active and isinstance(layer, LAYOUT_AWARE)) or \
        (cur_nhwc and isinstance(layer, LAYOUT_TRANSPARENT))
    if want and not cur_nhwc:
        return to_nhwc(x), True
    if not want and cur_nhwc:
        return to_nchw(x), False
    return x, cur_nhwc


def stamp_layout(layers, fmt: str) -> None:
    """Stamp ``data_format`` on every layout-aware layer (instance attr,
    so it round-trips through to_config/from_config). ``"NCHW"`` removes
    the stamp, restoring the class default."""
    if fmt not in ("NCHW", "NHWC"):
        raise ValueError(f"compute layout must be 'NCHW' or 'NHWC', "
                         f"got {fmt!r}")
    for layer in layers:
        if isinstance(layer, LAYOUT_AWARE):
            if fmt == "NHWC":
                layer.data_format = "NHWC"
            elif "data_format" in layer.__dict__:
                del layer.data_format


# --------------------------------------------------------- fused epilogues
# bias+BN+activation epilogue fusion (ISSUE 14): the conv stacks' hot
# non-matmul block is BatchNorm followed by relu/leaky-relu. Fused here
# into ONE scale_shift_act op — batch statistics stay the fp32
# reductions of norm_ops.batch_norm_train, the normalize+activation
# becomes a single FMA+select the 'scale_shift_act' registry op executes
# (a Pallas VMEM one-pass kernel when the platform override is installed
# and the shape tiles; the composed-jnp generic otherwise, which is
# bit-identical to the unfused batch_norm+activation path). A preceding
# identity-activation conv's bias folds into the shift algebraically
# (BN subtracts the mean, so the bias cancels in train mode and shifts
# the recorded running mean; inference un-shifts it from the running
# stats) — the conv itself dispatches bias-less.


def activation_alpha(layer) -> Optional[float]:
    """The epilogue slope for an ActivationLayer: 0.0 for relu, the leak
    for leakyrelu, None for anything else (not fusable)."""
    if type(layer) is not ActivationLayer or layer.dropout:
        return None
    name = str(layer.activation or "").lower()
    if name == "relu":
        return 0.0
    if name == "leakyrelu":
        return 0.01      # ops.activations.leakyrelu default slope
    return None


def fusable_conv(layer) -> bool:
    """A plain ConvolutionLayer whose own epilogue is empty (identity
    activation, no dropout) and whose bias can therefore fold into the
    following BN's shift."""
    return (type(layer) is ConvolutionLayer
            and str(layer.activation or "identity").lower() == "identity"
            and not layer.dropout)


def fusable_bn(layer) -> bool:
    return type(layer) is BatchNormalization and not layer.dropout


def fused_bn_act(bn, params, state, x, train, alpha: float, bias=None):
    """BatchNorm + relu/leaky epilogue (+ optional folded conv bias) as
    one ``scale_shift_act`` dispatch. Returns ``(out, new_bn_state)``.

    Statistics are bit-identical to ``norm_ops.batch_norm_train`` (fp32
    accumulate); with ``bias`` the batch stats run over the BIAS-LESS
    conv output (variance is bias-invariant; the recorded running mean
    adds the bias back so inference-mode behaviour matches the unfused
    stack).
    """
    from deeplearning4j_tpu.ops import registry as _registry
    axis = bn._channel_axis(x)
    gamma, beta = params["gamma"], params["beta"]
    b32 = bias.astype(jnp.float32) if bias is not None else None
    if train:
        axes = tuple(i for i in range(x.ndim) if i != axis)
        m = jnp.mean(x, axis=axes, dtype=jnp.float32)
        m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
        v = jnp.maximum(m2 - jnp.square(m), 0.0)
        m_rec = m + b32 if b32 is not None else m
        new_state = {"mean": bn.decay * state["mean"] + (1.0 - bn.decay) * m_rec,
                     "var": bn.decay * state["var"] + (1.0 - bn.decay) * v}
        mean_eff = m        # the folded bias cancels against the batch mean
    else:
        mean_eff = state["mean"] - b32 if b32 is not None else state["mean"]
        v = state["var"]
        new_state = state
    inv = jax.lax.rsqrt(v.astype(jnp.float32) + bn.eps)
    scale = (gamma * inv).astype(x.dtype)
    shift = (beta - gamma * mean_eff * inv).astype(x.dtype)
    out = _registry.get("scale_shift_act")(x, scale, shift, alpha=alpha,
                                           axis=axis)
    return out, new_state


def conv_bias_add(layer, out, b):
    """Re-attach a conv bias to a ``skip_bias=True`` conv output,
    bit-identical to the unfused path: ``conv_ops.conv2d`` applies its
    bias as this exact broadcast add AFTER the conv and the output dtype
    cast (and a fusable conv's activation is identity), so
    ``conv_bias_add(layer, conv_no_bias, b) == conv2d(..., b=b)`` to the
    bit.  Used when a folded conv's output also feeds consumers OUTSIDE
    its fused BN epilogue: they read the re-biased tensor while the BN
    consumes the bias-less one (the bias rides in its shift)."""
    return out + conv_ops._bias_reshape(b, 2, layer.data_format)


def build_epilogue_plan(layers, preprocessors=()) -> Dict[int, Tuple[int, bool, float]]:
    """Static fusion plan over a sequential layer list:
    ``{start_index: (n_layers_consumed, conv_leads, alpha)}`` —
    3 for conv(identity,bias)+BN+act triples (bias folds), 2 for BN+act
    pairs. Built once at step-compile time; fit dispatch consults it.

    ``preprocessors`` are the layer indices carrying an input
    preprocessor: a block whose INTERIOR index has one cannot fuse (the
    fused dispatch jumps straight through and would drop it); one at the
    block's start is fine — it runs before the block either way."""
    plan: Dict[int, Tuple[int, bool, float]] = {}
    pre = frozenset(preprocessors)
    i = 0
    while i < len(layers):
        if (i + 2 < len(layers) and fusable_conv(layers[i])
                and layers[i].has_bias and fusable_bn(layers[i + 1])
                and activation_alpha(layers[i + 2]) is not None
                and not (pre & {i + 1, i + 2})):
            plan[i] = (3, True, activation_alpha(layers[i + 2]))
            i += 3
            continue
        if (i + 1 < len(layers) and fusable_bn(layers[i])
                and activation_alpha(layers[i + 1]) is not None
                and i + 1 not in pre):
            plan[i] = (2, False, activation_alpha(layers[i + 1]))
            i += 2
            continue
        i += 1
    return plan


class SelfAttentionLayer(Layer):
    """ref: layers.samediff.SelfAttentionLayer — multi-head dot-product
    self-attention over a time series [N, nIn, T] -> [N, nOut, T].

    ``projectInput=True`` (required when nHeads > 1 or nOut != nIn) learns
    Wq/Wk/Wv: [nIn, nHeads*headSize] and Wo: [nHeads*headSize, nOut];
    without projection it is plain scaled dot-product attention and
    nOut == nIn. Masking: padded timesteps neither attend nor are
    attended to (reference semantics)."""

    input_kind = "rnn"

    def __init__(self, nOut=None, nHeads: int = 1, headSize: int = None,
                 projectInput: bool = True, useBias: bool = False, **kw):
        super().__init__(nOut=nOut, **kw)
        self.n_heads = nHeads
        self.head_size = headSize
        self.project = projectInput
        self.use_bias = useBias

    def infer_nin(self, it: InputType):
        super().infer_nin(it)
        if self.nOut is None:
            self.nOut = self.nIn
        if self.head_size is None:
            self.head_size = self.nOut // self.n_heads
        if not self.project:
            if self.n_heads != 1 or self.nOut != self.nIn:
                raise ValueError(
                    "SelfAttentionLayer: projectInput=False requires "
                    f"nHeads=1 and nOut==nIn (got nHeads={self.n_heads}, "
                    f"nIn={self.nIn}, nOut={self.nOut})")

    def param_shapes(self):
        if not self.project or not self.nIn or not self.nOut \
                or not self.head_size:
            return {}
        E = self.n_heads * self.head_size
        shapes = {"Wq": (self.nIn, E), "Wk": (self.nIn, E),
                  "Wv": (self.nIn, E), "Wo": (E, self.nOut)}
        if getattr(self, "use_bias", False):
            shapes.update({"bq": (E,), "bk": (E,), "bv": (E,),
                           "bo": (self.nOut,)})
        return shapes

    def initialize(self, key):
        if not self.project:
            return {}, {}
        E = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        params = {"Wq": _initialize((self.nIn, E), self.weight_init, ks[0]),
                  "Wk": _initialize((self.nIn, E), self.weight_init, ks[1]),
                  "Wv": _initialize((self.nIn, E), self.weight_init, ks[2]),
                  "Wo": _initialize((E, self.nOut), self.weight_init, ks[3])}
        if getattr(self, "use_bias", False):
            params.update({"bq": jnp.zeros((E,)), "bk": jnp.zeros((E,)),
                           "bv": jnp.zeros((E,)),
                           "bo": jnp.zeros((self.nOut,))})
        return params, {}

    def _project_attend(self, params, q_btc, kv_btc, m):
        """Projected multi-head attention with nIn != nHeads*headSize
        allowed (the mha registry op assumes square E x E projections)."""
        B, Tq = q_btc.shape[0], q_btc.shape[1]
        H, hs = self.n_heads, self.head_size

        def proj(x, w, b):
            y = x @ w
            if b is not None:
                y = y + b
            return y.reshape(x.shape[0], x.shape[1], H, hs)
        qh = proj(q_btc, params["Wq"], params.get("bq"))
        kh = proj(kv_btc, params["Wk"], params.get("bk"))
        vh = proj(kv_btc, params["Wv"], params.get("bv"))
        if m is None and Tq >= 1024:
            # long unmasked sequences: the fused flash path (Pallas kernel
            # when installed, scan formulation otherwise) avoids the
            # [T, T] score matrix
            ctx = attention_ops.flash_attention(qh, kh, vh)
        else:
            ctx = attention_ops.dot_product_attention(qh, kh, vh, mask=m)
        out = ctx.reshape(B, Tq, H * hs) @ params["Wo"]
        if params.get("bo") is not None:
            out = out + params["bo"]
        return out

    def _attend(self, params, x, mask):
        x_btc = jnp.transpose(x, (0, 2, 1))            # [N, T, C]
        m = None
        if mask is not None:
            # block attention TO padded keys; padded queries zeroed after
            m = mask[:, None, None, :]                 # [N, 1, 1, Tk]
        if self.project:
            y = self._project_attend(params, x_btc, x_btc, m)
        else:
            q = x_btc[:, :, None, :]
            y = attention_ops.dot_product_attention(q, q, q, mask=m)[:, :, 0]
        if mask is not None:
            y = y * mask[:, :, None]
        return jnp.transpose(y, (0, 2, 1))             # [N, nOut, T]

    def apply(self, params, state, x, train, key, mask=None):
        return self._attend(params, x, mask), state

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """ref: layers.samediff.LearnedSelfAttentionLayer — attention with
    nQueries LEARNED query vectors instead of per-timestep queries:
    [N, nIn, T] -> [N, nOut, nQueries] (a fixed-size summary of a
    variable-length sequence)."""

    def __init__(self, nOut=None, nQueries: int = 1, **kw):
        super().__init__(nOut=nOut, **kw)
        self.n_queries = nQueries

    def initialize(self, key):
        params, state = super().initialize(key)
        kq = jax.random.fold_in(key, 7)
        params["Q"] = _initialize((self.n_queries, self.nIn),
                                  self.weight_init, kq)
        return params, state

    def apply(self, params, state, x, train, key, mask=None):
        x_btc = jnp.transpose(x, (0, 2, 1))            # [N, T, C]
        q_bqc = jnp.broadcast_to(params["Q"][None],
                                 (x.shape[0],) + params["Q"].shape)
        m = mask[:, None, None, :] if mask is not None else None
        if self.project:
            y = self._project_attend(params, q_bqc, x_btc, m)
        else:
            q = q_bqc[:, :, None, :]
            kv = x_btc[:, :, None, :]
            y = attention_ops.dot_product_attention(q, kv, kv, mask=m)[:, :, 0]
        return jnp.transpose(y, (0, 2, 1)), state      # [N, nOut, nQueries]

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, self.n_queries)


class RecurrentAttentionLayer(Layer):
    """ref: layers.samediff.RecurrentAttentionLayer — recurrent cell whose
    per-step input is augmented with attention over the WHOLE sequence,
    queried by the previous hidden state:

        a_t = attention(q = y_{t-1}, keys = values = x)        # [N, nIn]
        y_t = activation(W x_t + R a_t + b)                    # [N, nOut]

    Input [N, nIn, T] -> [N, nOut, T]. Sequential by construction (scan
    over T) — the reference documents the same O(T) dependency."""

    input_kind = "rnn"

    def __init__(self, nOut=None, nHeads: int = 1, **kw):
        super().__init__(nOut=nOut, **kw)
        self.n_heads = nHeads
        if self.activation is None:
            self.activation = "tanh"

    def set_defaults(self, base):
        super().set_defaults(base)
        if self.activation == "identity":
            self.activation = "tanh"

    def initialize(self, key):
        ks = jax.random.split(key, 4)
        return {"W": _initialize((self.nIn, self.nOut), self.weight_init, ks[0]),
                "R": _initialize((self.nIn, self.nOut), self.weight_init, ks[1]),
                "Wq": _initialize((self.nOut, self.nIn), self.weight_init, ks[2]),
                "b": jnp.zeros((self.nOut,))}, {}

    def apply(self, params, state, x, train, key, mask=None):
        x_tnc = jnp.transpose(x, (2, 0, 1))            # [T, N, C]
        act_fn = act.get(self.activation)
        keys_btc = jnp.transpose(x, (0, 2, 1))         # [N, T, C]
        key_mask = mask                                 # [N, T] or None
        H = self.n_heads
        if self.nIn % H:
            raise ValueError(f"RecurrentAttentionLayer: nIn={self.nIn} not "
                             f"divisible by nHeads={H}")
        hd = self.nIn // H
        keys_h = keys_btc.reshape(keys_btc.shape[0], keys_btc.shape[1], H, hd)

        def step(y_prev, x_t):
            q = (y_prev @ params["Wq"]).reshape(-1, H, hd)   # [N, H, hd]
            scores = jnp.einsum("nhd,nthd->nht", q, keys_h) \
                / np.sqrt(hd).astype(np.float32)
            if key_mask is not None:
                scores = jnp.where(key_mask[:, None, :] > 0, scores, -1e30)
            w = jax.nn.softmax(scores, axis=-1)
            a_t = jnp.einsum("nht,nthd->nhd", w, keys_h).reshape(
                -1, self.nIn)
            y_t = act_fn(x_t @ params["W"] + a_t @ params["R"] + params["b"])
            return y_t, y_t

        y0 = jnp.zeros((x.shape[0], self.nOut), x.dtype)
        _, ys = jax.lax.scan(step, y0, x_tnc)          # [T, N, H]
        out = jnp.transpose(ys, (1, 2, 0))
        if mask is not None:
            out = out * mask[:, None, :]
        return out, state

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))


class SameDiffLayer(Layer):
    """ref: nn.conf.layers.samediff.SameDiffLayer — the extensibility
    escape hatch: define a layer as a GRAPH FRAGMENT instead of a new
    Layer subclass with hand-written forward/backward.

    Subclass and override:
    - ``defineParameters() -> {name: shape}``
    - ``defineLayer(sd, layerInput, paramTable, mask) -> SDVariable``

    The fragment is recorded ONCE into a private SameDiff instance and
    its traced function is inlined into the enclosing network's compiled
    step — gradients flow through it via jax.grad like any other layer
    (the reference gets this for free from SameDiff autodiff; here both
    the layer fragment and the host network are the same jax program).
    """

    def defineParameters(self) -> Dict[str, Tuple[int, ...]]:
        raise NotImplementedError

    def defineLayer(self, sd, layerInput, paramTable, mask=None):
        raise NotImplementedError

    def infer_nin(self, it: InputType):
        super().infer_nin(it)
        if self.nOut is None:
            self.nOut = self.nIn

    def initialize(self, key):
        shapes = self.defineParameters()
        keys = jax.random.split(key, max(len(shapes), 1))
        params = {name: _initialize(tuple(shape), self.weight_init, k)
                  for (name, shape), k in zip(shapes.items(), keys)}
        self._fragment = None
        return params, {}

    def _build_fragment(self, params, x_shape):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        xv = sd.placeHolder("layer_input", shape=x_shape)
        pvs = {k: sd.placeHolder(k, shape=tuple(v.shape))
               for k, v in params.items()}
        out = self.defineLayer(sd, xv, pvs, None)
        return sd._build_fn((out.name,)), out.name

    def apply(self, params, state, x, train, key):
        if getattr(self, "_fragment", None) is None:
            self._fragment = self._build_fragment(params, tuple(x.shape))
        fn, out_name = self._fragment
        feeds = {"layer_input": x, **params}
        res = fn({}, {}, feeds, key, train)
        return res[out_name], state


class Convolution3D(Layer):
    """ref: layers.convolution.Convolution3D — NCDHW, W [nOut, nIn, kD, kH, kW]."""

    input_kind = "cnn3d"

    def __init__(self, kernelSize=(3, 3, 3), stride=(1, 1, 1),
                 padding=(0, 0, 0), nOut=None,
                 convolutionMode: str = "truncate", hasBias: bool = True,
                 **kw):
        super().__init__(nOut=nOut, **kw)
        self.kernel = tuple(kernelSize) if isinstance(kernelSize, (tuple, list)) \
            else (kernelSize,) * 3
        self.stride = tuple(stride) if isinstance(stride, (tuple, list)) \
            else (stride,) * 3
        self.padding = tuple(padding) if isinstance(padding, (tuple, list)) \
            else (padding,) * 3
        self.mode = convolutionMode
        self.has_bias = hasBias

    def infer_nin(self, it: InputType):
        if self.nIn is None:
            self.nIn = it.channels

    def initialize(self, key):
        shape = (self.nOut, self.nIn) + self.kernel
        params = {"W": _initialize(shape, self.weight_init, key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def param_shapes(self):
        if not self.nIn or not self.nOut:
            return {}
        shapes = {"W": (self.nOut, self.nIn) + tuple(self.kernel)}
        if self.has_bias:
            shapes["b"] = (self.nOut,)
        return shapes

    def apply(self, params, state, x, train, key):
        x = self._maybe_dropout(x, train, key)
        out = conv_ops.conv3d(x, params["W"],
                              params.get("b") if self.has_bias else None,
                              stride=self.stride, pad=self.padding,
                              mode=self.mode)
        return act.get(self.activation)(out), state

    def output_type(self, it: InputType) -> InputType:
        dims = [conv_ops.conv_output_size(s, self.kernel[i], self.stride[i],
                                          self.padding[i], 1, self.mode)
                for i, s in enumerate((it.depth, it.height, it.width))]
        return InputType.convolutional3D(dims[0], dims[1], dims[2], self.nOut)


class Subsampling3DLayer(Layer):
    """ref: layers.convolution.Subsampling3DLayer — NCDHW pooling."""

    input_kind = "cnn3d"
    has_params = False

    def __init__(self, poolingType: str = "max", kernelSize=(2, 2, 2),
                 stride=None, padding=(0, 0, 0), **kw):
        super().__init__(**kw)
        self.pooling = poolingType.lower()
        self.kernel = tuple(kernelSize) if isinstance(kernelSize, (tuple, list)) \
            else (kernelSize,) * 3
        self.stride = tuple(stride) if stride is not None else self.kernel
        self.padding = tuple(padding) if isinstance(padding, (tuple, list)) \
            else (padding,) * 3

    def infer_nin(self, it: InputType):
        self.nIn = self.nOut = it.channels

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        fn = conv_ops.maxpool3d if self.pooling == "max" else conv_ops.avgpool3d
        return fn(x, kernel=self.kernel, stride=self.stride,
                  pad=self.padding), state

    def output_type(self, it: InputType) -> InputType:
        dims = [conv_ops.conv_output_size(s, self.kernel[i], self.stride[i],
                                          self.padding[i], 1, "truncate")
                for i, s in enumerate((it.depth, it.height, it.width))]
        return InputType.convolutional3D(dims[0], dims[1], dims[2], it.channels)


def _triple_pads(spec):
    """int | (a, b, c) | ((lo, hi), ...) -> three (lo, hi) pairs."""
    if isinstance(spec, (int, np.integer)):
        spec = (spec,) * 3
    return tuple((int(p), int(p)) if isinstance(p, (int, np.integer))
                 else (int(p[0]), int(p[1])) for p in spec)


class ZeroPadding3DLayer(Layer):
    """ref: layers.convolution.ZeroPadding3DLayer — NCDHW."""

    input_kind = "cnn3d"
    has_params = False

    def __init__(self, padding=(1, 1, 1), **kw):
        super().__init__(**kw)
        self.pad = _triple_pads(padding)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        return jnp.pad(x, [(0, 0), (0, 0)] + list(self.pad)), state

    def output_type(self, it):
        d, h, w = ((s + sum(p)) for s, p in
                   zip((it.depth, it.height, it.width), self.pad))
        return InputType.convolutional3D(d, h, w, it.channels)


class Cropping3D(Layer):
    """ref: layers.convolution.Cropping3D — NCDHW."""

    input_kind = "cnn3d"
    has_params = False

    def __init__(self, crop=(1, 1, 1), **kw):
        super().__init__(**kw)
        self.crop = _triple_pads(crop)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        (d0, d1), (h0, h1), (w0, w1) = self.crop
        D, H, W = x.shape[2:]
        return x[:, :, d0:D - d1, h0:H - h1, w0:W - w1], state

    def output_type(self, it):
        d, h, w = ((s - sum(c)) for s, c in
                   zip((it.depth, it.height, it.width), self.crop))
        return InputType.convolutional3D(d, h, w, it.channels)


class Upsampling3D(Layer):
    """ref: layers.convolution.Upsampling3D — nearest repeat, NCDHW."""

    input_kind = "cnn3d"
    has_params = False

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.scale = tuple(size) if isinstance(size, (tuple, list)) \
            else (int(size),) * 3

    def infer_nin(self, it):
        self.nIn = self.nOut = it.channels

    def apply(self, params, state, x, train, key):
        for ax, s in zip((2, 3, 4), self.scale):
            if s != 1:
                x = jnp.repeat(x, s, axis=ax)
        return x, state

    def output_type(self, it):
        return InputType.convolutional3D(it.depth * self.scale[0],
                                         it.height * self.scale[1],
                                         it.width * self.scale[2],
                                         it.channels)


class Upsampling1D(Layer):
    """ref: layers.convolution.Upsampling1D — [N, C, T] repeat along T."""

    input_kind = "rnn"
    has_params = False

    def __init__(self, size: int = 2, **kw):
        super().__init__(**kw)
        self.size = int(size)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.size

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        return jnp.repeat(x, self.size, axis=2), state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1)
        return InputType.recurrent(it.size, t * self.size if t > 0 else -1)


class ZeroPadding1DLayer(Layer):
    """ref: layers.convolution.ZeroPadding1DLayer — pad along T."""

    input_kind = "rnn"
    has_params = False

    def __init__(self, padding=1, **kw):
        super().__init__(**kw)
        self.pad = tuple(padding) if isinstance(padding, (tuple, list)) \
            else (int(padding), int(padding))

    def infer_nin(self, it):
        self.nIn = self.nOut = it.size

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        return jnp.pad(x, [(0, 0), (0, 0), tuple(self.pad)]), state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1)
        return InputType.recurrent(it.size,
                                   t + sum(self.pad) if t > 0 else -1)


class Cropping1D(Layer):
    """ref: layers.convolution.Cropping1D."""

    input_kind = "rnn"
    has_params = False

    def __init__(self, cropping=1, **kw):
        super().__init__(**kw)
        self.crop = tuple(cropping) if isinstance(cropping, (tuple, list)) \
            else (int(cropping), int(cropping))

    def infer_nin(self, it):
        self.nIn = self.nOut = it.size

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        t = x.shape[2]
        return x[:, :, self.crop[0]:t - self.crop[1]], state

    def output_type(self, it: InputType) -> InputType:
        t = it.dims.get("timesteps", -1)
        return InputType.recurrent(it.size,
                                   t - sum(self.crop) if t > 0 else -1)


class MaskZeroLayer(Layer):
    """ref: layers.recurrent.MaskZeroLayer / Keras Masking — zero out
    timesteps whose EVERY feature equals ``maskValue`` (the mask itself
    flows separately; this matches Keras Masking's forward zeroing)."""

    input_kind = "rnn"
    has_params = False

    def __init__(self, maskValue: float = 0.0, **kw):
        super().__init__(**kw)
        self.mask_value = float(maskValue)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.size

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        keep = jnp.any(x != self.mask_value, axis=1, keepdims=True)
        return jnp.where(keep, x, 0.0), state

    def output_type(self, it: InputType) -> InputType:
        return it


class GaussianNoiseLayer(Layer):
    """ref/Keras: GaussianNoise — additive N(0, stddev) noise, train only."""

    input_kind = None
    has_params = False

    def __init__(self, stddev: float = 0.1, **kw):
        super().__init__(**kw)
        self.stddev = float(stddev)

    def infer_nin(self, it):
        self.nIn = self.nOut = it.arrayElementsPerExample()

    def initialize(self, key):
        return {}, {}

    def apply(self, params, state, x, train, key):
        if not train:
            return x, state
        return x + self.stddev * jax.random.normal(key, x.shape, x.dtype), state

    def output_type(self, it):
        return it


class GaussianDropoutLayer(GaussianNoiseLayer):
    """ref/Keras: GaussianDropout — multiplicative N(1, rate/(1-rate))."""

    def __init__(self, rate: float = 0.1, **kw):
        super(GaussianNoiseLayer, self).__init__(**kw)
        self.rate = float(rate)

    def apply(self, params, state, x, train, key):
        if not train or self.rate <= 0:
            return x, state
        stddev = float(np.sqrt(self.rate / (1.0 - self.rate)))
        noise = 1.0 + stddev * jax.random.normal(key, x.shape, x.dtype)
        return x * noise, state


class AlphaDropoutLayer(GaussianNoiseLayer):
    """ref/Keras: AlphaDropout — SELU self-normalizing dropout."""

    def __init__(self, rate: float = 0.1, **kw):
        super(GaussianNoiseLayer, self).__init__(**kw)
        self.rate = float(rate)

    def apply(self, params, state, x, train, key):
        if not train or self.rate <= 0:
            return x, state
        from deeplearning4j_tpu.ops import registry as _R
        return _R.get("alpha_dropout")(key, x, self.rate), state


class TimeDistributed(Layer):
    """ref/Keras: TimeDistributed(Dense) — the wrapped dense applied at
    every timestep of [N, C, T] (DL4J expresses this as DenseLayer with
    RnnToFF/FFToRnn preprocessors; here it is one einsum)."""

    input_kind = "rnn"

    def __init__(self, inner: "DenseLayer" = None, nOut=None, **kw):
        if inner is not None and not isinstance(inner, DenseLayer):
            raise ValueError("TimeDistributed supports a Dense inner layer")
        super().__init__(nOut=nOut if nOut is not None
                         else (inner.nOut if inner else None), **kw)
        if inner is not None and self.activation is None:
            self.activation = inner.activation
        self.has_bias = inner.has_bias if inner is not None else True

    def initialize(self, key):
        params = {"W": _initialize((self.nIn, self.nOut), self.weight_init,
                                   key)}
        if self.has_bias:
            params["b"] = jnp.full((self.nOut,), self.bias_init, jnp.float32)
        return params, {}

    def apply(self, params, state, x, train, key):
        z = jnp.einsum("nct,ch->nht", x, params["W"])
        if self.has_bias:
            z = z + params["b"][None, :, None]
        a = act.get(self.activation)(z, axis=1) \
            if self.activation in ("softmax", "logsoftmax") \
            else act.get(self.activation)(z)
        return a, state

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.nOut, it.dims.get("timesteps", -1))
