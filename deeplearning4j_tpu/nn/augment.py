"""On-device image augmentation compiled into the jitted train step.

The host pipeline (data/pipeline.py) ships raw decoded uint8 NCHW; the
crop/flip/normalize work the reference runs on host threads (the
``ImageTransform`` hierarchy) happens HERE, as a prelude fused into the
compiled train step — the host never pays a float conversion or an
augment pass, and the H2D link carries 1/4 the bytes. Augmentation RNG
derives from ``fold_in(PRNGKey(aug_seed), t)`` on the device-resident
step counter, so it is bit-reproducible per seed, exact-resume stable,
and identical inside a ``lax.scan`` megastep (each scanned step sees
its own ``t``).

Fixed shapes: every op maps a ``[B, C, H, W]`` batch to a fixed output
shape (random crop picks a random *offset* into a fixed ``[H-c, W-c]``
window rather than the host path's variable-margin crop), so the train
step compiles exactly once — the zero-steady-state-recompile property
the W201 churn detector pins.

Use :meth:`DeviceAugmentation.from_transforms` to compile the
``ImageTransform`` presets that have device kernels — Flip, Crop, Scale,
Brightness, ColorConversion, Resize (``jax.image.resize`` bilinear), and
Rotate (inverse-mapped bilinear gather) all do. Transforms without one
(probabilistic/shuffled pipelines) raise — keep those on the host path
(``decode(transform=...)``), which remains fully supported::

    aug = (DeviceAugmentation(seed=7)
           .crop(4)                  # random 4px crop -> [H-4, W-4]
           .flip(1)                  # deterministic horizontal flip
           .scale_to(0.0, 1.0))      # pixel [0,255] -> [0,1] on device
    net.fit(it, epochs=5, steps_per_dispatch=4, augment=aug)

    # or compile host presets:
    aug = DeviceAugmentation.from_transforms(
        [FlipImageTransform(1), ScaleImageTransform(1 / 255.0)], seed=7)

Deterministic ops (fixed-mode flip, scale, normalize, grayscale) are
numerically identical to their host counterparts on uint8 input — the
loss-parity tests pin this. Random ops (crop, random flip, random
brightness) draw from the device PRNG and therefore differ draw-by-draw
from the host numpy RNG while matching its distribution (the crop
differs as noted above).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class DeviceAugmentation:
    """A chain of fixed-shape augmentation ops applied inside the jitted
    train step. Chainable builder; :meth:`signature` is a hashable
    identity the networks use to know when a recompile is actually
    needed (same-signature augmentations reuse the compiled step)."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._ops: List[Tuple[Tuple, callable]] = []   # (sig, fn)

    # ------------------------------------------------------------ builders
    def flip(self, mode: int = 1) -> "DeviceAugmentation":
        """Deterministic flip (host ``FlipImageTransform`` codes):
        1 = horizontal, 0 = vertical, -1 = both."""
        if mode not in (0, 1, -1):
            raise ValueError(f"flip mode must be 0, 1 or -1, got {mode}")

        def op(x, key):
            if mode in (1, -1):
                x = x[..., ::-1]
            if mode in (0, -1):
                x = x[..., ::-1, :]
            return x
        self._ops.append((("flip", mode), op))
        return self

    def random_flip(self) -> "DeviceAugmentation":
        """Per-image random flip: one of {vertical, horizontal, both},
        uniformly (host ``FlipImageTransform(None)`` semantics)."""

        def op(x, key):
            mode = jax.random.randint(key, (x.shape[0],), 0, 3)
            hor = ((mode == 1) | (mode == 2))[:, None, None, None]
            ver = ((mode == 0) | (mode == 2))[:, None, None, None]
            x = jnp.where(hor, x[..., ::-1], x)
            return jnp.where(ver, x[..., ::-1, :], x)
        self._ops.append((("random_flip",), op))
        return self

    def crop(self, crop: int) -> "DeviceAugmentation":
        """Per-image random crop to the fixed shape ``[H-crop, W-crop]``
        (random offset in ``[0, crop]`` per side). Fixed output shape is
        what keeps the compiled step signature stable; the host
        ``CropImageTransform`` draws each margin independently and emits
        variable shapes, which would recompile every step."""
        c = int(crop)
        if c < 0:
            raise ValueError("crop must be >= 0")

        def op(x, key):
            b, ch, h, w = x.shape
            off = jax.random.randint(key, (b, 2), 0, c + 1)

            def one(img, o):
                return jax.lax.dynamic_slice(img, (0, o[0], o[1]),
                                             (ch, h - c, w - c))
            return jax.vmap(one)(x, off)
        self._ops.append((("crop", c), op))
        return self

    def scale(self, factor: float) -> "DeviceAugmentation":
        """Multiply pixel values (host ``ScaleImageTransform``)."""
        f = float(factor)
        self._ops.append((("scale", f), lambda x, key: x * f))
        return self

    def scale_to(self, a: float = 0.0, b: float = 1.0) -> "DeviceAugmentation":
        """Pixel ``[0, 255] -> [a, b]`` (host ``ImagePreProcessingScaler``
        moved on device)."""
        a, b = float(a), float(b)
        self._ops.append((("scale_to", a, b),
                          lambda x, key: x / 255.0 * (b - a) + a))
        return self

    def normalize(self, mean: Sequence[float],
                  std: Sequence[float]) -> "DeviceAugmentation":
        """Per-channel ``(x - mean) / std`` (the NormalizerStandardize
        image case, fused on device)."""
        m = tuple(float(v) for v in mean)
        s = tuple(float(v) for v in std)

        def op(x, key):
            mm = jnp.asarray(m, x.dtype).reshape(1, -1, 1, 1)
            ss = jnp.asarray(s, x.dtype).reshape(1, -1, 1, 1)
            return (x - mm) / ss
        self._ops.append((("normalize", m, s), op))
        return self

    def brightness(self, delta: float,
                   random: bool = False) -> "DeviceAugmentation":
        """Add ``delta`` (or a per-image uniform draw in ``[-delta,
        delta]``) and clip to ``[0, 255]`` (host ``BrightnessTransform``)."""
        d = float(delta)

        def op(x, key):
            if random:
                dd = jax.random.uniform(key, (x.shape[0], 1, 1, 1),
                                        minval=-d, maxval=d)
            else:
                dd = d
            return jnp.clip(x + dd, 0.0, 255.0)
        self._ops.append((("brightness", d, bool(random)), op))
        return self

    def resize(self, height: int, width: int) -> "DeviceAugmentation":
        """Bilinear resize to a fixed ``[height, width]`` (host
        ``ResizeImageTransform`` moved on device via ``jax.image.resize``
        — same bilinear family as the host PIL kernel; edge-sample
        weights differ by implementation, so parity is distributional,
        like the random ops)."""
        h, w = int(height), int(width)
        if h <= 0 or w <= 0:
            raise ValueError("resize dims must be positive")

        def op(x, key):
            b, c = x.shape[0], x.shape[1]
            return jax.image.resize(x, (b, c, h, w), "linear")
        self._ops.append((("resize", h, w), op))
        return self

    def rotate(self, angle: float, random: bool = False
               ) -> "DeviceAugmentation":
        """Rotate about the image center by ``angle`` degrees (or a
        per-image uniform draw in ``[-angle, angle]`` when ``random``) —
        host ``RotateImageTransform`` moved on device: inverse-mapped
        coordinate grid + bilinear gather, out-of-bounds filled with 0
        (PIL's fill). Output shape is unchanged, so the compiled step
        signature stays stable."""
        a = float(angle)

        def op(x, key):
            b, c, h, w = x.shape
            if random:
                deg = jax.random.uniform(key, (b,), minval=-a, maxval=a)
            else:
                deg = jnp.full((b,), a, jnp.float32)
            # PIL rotates counter-clockwise; inverse-map each output
            # pixel back into the source image (hence the negated angle)
            rad = -deg * (jnp.pi / 180.0)
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
            yy = jnp.arange(h, dtype=jnp.float32)[:, None] - cy   # [H,1]
            xx = jnp.arange(w, dtype=jnp.float32)[None, :] - cx   # [1,W]
            cos = jnp.cos(rad)[:, None, None]
            sin = jnp.sin(rad)[:, None, None]
            sy = cos * yy - sin * xx + cy                         # [B,H,W]
            sx = sin * yy + cos * xx + cx

            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy = sy - y0
            wx = sx - x0
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)

            def corner(img, yi, xi):
                """img [C,H,W], yi/xi [H,W] -> gathered [C,H,W] with
                out-of-bounds as 0."""
                inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                yc = jnp.clip(yi, 0, h - 1)
                xc = jnp.clip(xi, 0, w - 1)
                g = img[:, yc, xc]
                return jnp.where(inb[None], g, 0.0)

            def one(img, y0i, x0i, wy, wx):
                tl = corner(img, y0i, x0i)
                tr = corner(img, y0i, x0i + 1)
                bl = corner(img, y0i + 1, x0i)
                br = corner(img, y0i + 1, x0i + 1)
                top = tl * (1 - wx) + tr * wx
                bot = bl * (1 - wx) + br * wx
                return top * (1 - wy) + bot * wy
            return jax.vmap(one)(x, y0i, x0i, wy, wx).astype(x.dtype)
        self._ops.append((("rotate", a, bool(random)), op))
        return self

    def grayscale(self) -> "DeviceAugmentation":
        """RGB -> luma, kept 3-channel (host ``ColorConversionTransform``)."""

        def op(x, key):
            if x.shape[1] != 3:
                return x
            g = (0.299 * x[:, 0] + 0.587 * x[:, 1] + 0.114 * x[:, 2])
            return jnp.stack([g, g, g], axis=1)
        self._ops.append((("grayscale",), op))
        return self

    # ----------------------------------------------------- host-preset map
    @classmethod
    def from_transforms(cls, transforms, seed: int = 0
                        ) -> "DeviceAugmentation":
        """Compile host ``ImageTransform`` presets (and
        ``ImagePreProcessingScaler``) into a device chain. Raises
        ``ValueError`` for a transform with no device kernel — catch it
        and keep that transform on the host path
        (``decode(transform=...)``), which stays fully supported."""
        from deeplearning4j_tpu.data.dataset import ImagePreProcessingScaler
        from deeplearning4j_tpu.data import image as _img
        aug = cls(seed=seed)

        def add(t):
            if isinstance(t, _img.PipelineImageTransform):
                if t.shuffle or any(p < 1.0 for _, p in t.steps):
                    raise ValueError(
                        "PipelineImageTransform with shuffle/probabilistic "
                        "steps has no device kernel (the device chain is "
                        "unconditional); keep it on the host path")
                for sub, _ in t.steps:
                    add(sub)
            elif isinstance(t, _img.FlipImageTransform):
                if t.mode is None:
                    aug.random_flip()
                else:
                    aug.flip(t.mode)
            elif isinstance(t, _img.CropImageTransform):
                aug.crop(t.crop)
            elif isinstance(t, _img.ResizeImageTransform):
                aug.resize(t.height, t.width)
            elif isinstance(t, _img.RotateImageTransform):
                aug.rotate(t.angle, t.random)
            elif isinstance(t, _img.ScaleImageTransform):
                aug.scale(t.scale)
            elif isinstance(t, _img.BrightnessTransform):
                aug.brightness(t.delta, t.random)
            elif isinstance(t, _img.ColorConversionTransform):
                aug.grayscale()
            elif isinstance(t, ImagePreProcessingScaler):
                aug.scale_to(t.a, t.b)
            else:
                raise ValueError(
                    f"{type(t).__name__} has no device kernel; keep it on "
                    f"the host path (decode(transform=...))")
        for t in (transforms if isinstance(transforms, (list, tuple))
                  else [transforms]):
            add(t)
        return aug

    # -------------------------------------------------------------- apply
    def signature(self) -> Tuple:
        """Hashable identity: op chain + seed. Two augmentations with
        equal signatures compile to the same program."""
        return (self.seed,) + tuple(sig for sig, _ in self._ops)

    def apply(self, x, key):
        """Run the chain on one batch inside the compiled step: uint8
        input is cast to float32 first (fused by XLA into the chain and
        the consuming conv), each op gets ``fold_in(key, op_index)``."""
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32)
        for i, (_, op) in enumerate(self._ops):
            x = op(x, jax.random.fold_in(key, i))
        return x

    def step_key(self, t):
        """The per-step augmentation key: ``fold_in(PRNGKey(seed), t)``
        on the device-resident iteration counter — reproducible per seed,
        independent of the dropout stream."""
        return jax.random.fold_in(jax.random.PRNGKey(self.seed), t)

    def output_hw(self, height: int, width: int) -> Tuple[int, int]:
        """Static output spatial dims for declared input dims (crops
        shrink them, resizes replace them) — what the model's InputType
        should declare."""
        for sig, _ in self._ops:
            if sig[0] == "crop":
                height, width = height - sig[1], width - sig[1]
            elif sig[0] == "resize":
                height, width = sig[1], sig[2]
        return height, width

    def __repr__(self):
        ops = ", ".join(".".join(map(str, sig)) for sig, _ in self._ops)
        return f"DeviceAugmentation(seed={self.seed}, ops=[{ops}])"


def maybe_augment(augment: Optional[DeviceAugmentation], x, t):
    """The train-step prelude hook both network classes call: identity
    when no augmentation is attached, else the seeded device chain.
    Only 4-D (NCHW image) inputs are augmented — a ComputationGraph with
    mixed inputs augments its image inputs and passes the rest through."""
    if augment is None or getattr(x, "ndim", 0) != 4:
        return x
    return augment.apply(x, augment.step_key(t))
