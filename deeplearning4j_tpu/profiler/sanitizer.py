"""Nonfinite-provenance sanitizer — NAN_PANIC that names the culprit.

The seed's ``NAN_PANIC``/``INF_PANIC`` modes raise "NaN detected in
loss at iteration 12" — true, and useless: by the time a nonfinite
reaches the loss it has flowed through every layer, and the question
that matters ("WHICH layer, WHICH op, WHICH step first went bad — the
PR-4 YOLO triage burned a day answering it by hand") is unanswerable
from the loss scalar.  This module extends the panic modes into a
provenance sanitizer:

- **Hot path** (one flag check when OFF, the standard instrumentation
  gate): while a panic mode is active, each model keeps a *provenance
  window* — a device-side snapshot of (params, states, opt_state)
  taken every ``snapshot_every`` dispatches (default 8; ONE fused copy
  dispatch via the ``train.resilience`` ``_device_copy``, lazy, no
  host sync) plus references to every batch since (the compiled step
  does NOT donate its batch args, so they stay valid).  Out-of-band
  state mutations (fault poisons, checkpoint restores, elastic
  rollbacks) void the window via :func:`invalidate` / the iteration-
  gap check, forcing a fresh snapshot.
- **Failure path**: when the post-dispatch loss scan finds a
  nonfinite, the sanitizer rolls the snapshot forward through the
  retained dispatches via the model's OWN compiled single-step program
  (bit-exact — the scanned megastep body is byte-identical to it),
  then REPLAYS the failing step eagerly, layer by layer, with the same
  ``fold_in(seed, t)`` RNG stream, policy casts, and augmentation
  prelude the compiled step traced — and attributes the FIRST
  nonfinite to a specific (layer, op, step): params / forward / loss /
  backward / updater.  Under a ``lax.scan`` megastep the K-loss vector
  names the first bad step j.  The raise is a
  :class:`NonfiniteAttributionError` (a ``NumericsPanicError``, so
  every existing NAN_PANIC handler still catches it) carrying the
  site, also exported as the ``dl4j_nonfinite_first_site{model,layer,
  op}`` info metric (value = the 1-based step).
- **Opt-in value-range tracking** (:func:`track_value_ranges`): every
  N-th step additionally records per-layer activation |max| into
  ``dl4j_tensor_absmax{model,layer}`` (log-scaled buckets spanning
  1e-4..1e38) and, for bf16/fp16 policies, sets
  ``dl4j_overflow_proximity`` = max |act| / finfo(compute).max — the
  "how close is this run to E303" gauge the bf16 rollout watches.

Costs: OFF = one enum read per dispatch.  ON = the loss sync the panic
modes always paid + one fused copy dispatch every ``snapshot_every``
steps (``benchmarks/probe_numerics_overhead.py`` pins provenance at
< 5% over the legacy panic gate; measured ~1%); the roll-forward /
eager replay and range walks run only on failure / sampled steps.
TBPTT fits attribute through the same window (kind ``"tbptt"``): each
segment dispatch retains its carried RNN state, the replay rolls the
segment steps through the compiled TBPTT body, and the eager walk names
the (layer, op, step) — including a poisoned carried state crossing a
segment boundary (``carried-state``).

Like the rest of ``profiler/``, module scope imports no jax — jax
enters lazily on the first active snapshot.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from deeplearning4j_tpu.profiler.metrics import get_registry
from deeplearning4j_tpu.profiler.modes import ProfilingMode, get_profiling_mode
from deeplearning4j_tpu.utils.environment import NumericsPanicError

#: |max| buckets for dl4j_tensor_absmax: decades up to fp16-max, then the
#: bf16/fp32 range — a histogram shaped for "how far from overflow".
ABSMAX_BUCKETS = (1e-4, 1e-2, 1.0, 1e1, 1e2, 1e3, 1e4, 65504.0,
                  1e6, 1e9, 1e12, 1e18, 1e24, 1e30, 1e38)

_FIRST_SITE = get_registry().gauge(
    "dl4j_nonfinite_first_site",
    "First nonfinite site attributed by the provenance sanitizer "
    "(value = 1-based update step; labels name the model, layer, op)",
    labelnames=("model", "layer", "op"))
_PANICS = get_registry().counter(
    "dl4j_nonfinite_panics_total",
    "Nonfinite losses caught (and attributed) by the panic sanitizer")
_ABSMAX = get_registry().histogram(
    "dl4j_tensor_absmax",
    "Per-layer activation |max| samples from opt-in value-range tracking",
    labelnames=("model", "layer"), buckets=ABSMAX_BUCKETS)
_PROXIMITY = get_registry().gauge(
    "dl4j_overflow_proximity",
    "max per-layer activation |max| / finfo(compute dtype).max from the "
    "most recent range-tracking walk (bf16/fp16 policies; 1.0 = at the "
    "overflow ceiling)")

# -------------------------------------------------- value-range tracking
_TRACK_RANGES = False
_TRACK_EVERY = 1
_PROVENANCE = True
#: dispatches between device-side state snapshots: the copy cost is paid
#: 1/N of the time and attribution rolls the last snapshot forward
#: through the SAME compiled step programs (bit-exact) using the
#: retained batches — memory bound: N dispatches' worth of batch refs
_SNAPSHOT_EVERY = 8


def enable_provenance(enabled: bool = True,
                      snapshot_every: Optional[int] = None) -> None:
    """``enable_provenance(False)`` keeps the NAN_PANIC/INF_PANIC loss
    gate but skips the snapshots — the legacy attribution-free behavior
    (and the overhead probe's baseline for "what does provenance itself
    cost on top of the panic sync").  ``snapshot_every`` tunes the
    snapshot cadence (1 = copy state every dispatch: cheapest
    attribution, costliest steady state)."""
    global _PROVENANCE, _SNAPSHOT_EVERY
    _PROVENANCE = bool(enabled)
    if snapshot_every is not None:
        _SNAPSHOT_EVERY = max(1, int(snapshot_every))


def track_value_ranges(enable: bool = True, every: int = 1) -> None:
    """Opt-in absmax/value-range tracking: while a panic mode is active,
    every ``every``-th update step runs one eager per-layer forward on
    the live batch and records ``dl4j_tensor_absmax`` samples plus the
    overflow-proximity gauge.  A full extra forward per sampled step —
    a diagnostic dial, not a production default."""
    global _TRACK_RANGES, _TRACK_EVERY
    _TRACK_RANGES = bool(enable)
    _TRACK_EVERY = max(1, int(every))


class NonfiniteAttributionError(NumericsPanicError):
    """NAN_PANIC/INF_PANIC with provenance: carries the first-nonfinite
    (layer, op, step) the replay attributed."""

    def __init__(self, message: str, layer: str = "", op: str = "",
                 step: int = 0):
        super().__init__(message)
        self.layer = layer
        self.op = op
        self.step = step


def active() -> bool:
    """One enum read: True while a panic mode wants the sanitizer armed."""
    return get_profiling_mode() in (ProfilingMode.NAN_PANIC,
                                    ProfilingMode.INF_PANIC)


class _ModelSan:
    """Per-model provenance state: the last device-side snapshot plus
    the (kind, batch, start-iteration, steps) of every dispatch since —
    enough to roll the snapshot forward to ANY step in the window
    through the model's own compiled step programs."""

    __slots__ = ("params", "states", "opt_state", "scale_state",
                 "snap_step", "expected_next", "ring")

    def __init__(self, params, states, opt_state, snap_step,
                 scale_state=None):
        self.params = params
        self.states = states
        self.opt_state = opt_state
        self.scale_state = scale_state   # dynamic loss-scale carry (or None)
        self.snap_step = snap_step
        self.expected_next = snap_step
        self.ring: list = []      # (kind, batch dict, start_iter, steps)


class _Token:
    """One dispatch's provenance handle: the shared per-model state plus
    this dispatch's position in its ring."""

    __slots__ = ("state", "ring_index", "step0", "batch", "kind")

    def __init__(self, state, ring_index, step0, batch, kind):
        self.state = state
        self.ring_index = ring_index
        self.step0 = step0          # 0-based iteration count before dispatch
        self.batch = batch          # dict of arrays the step consumed
        self.kind = kind   # "single" | "mega" | "tbptt" | "graph" | "graph_mega"


_STATES: "weakref.WeakKeyDictionary" = None  # created on first use


def invalidate(model) -> None:
    """Void the provenance window after an OUT-OF-BAND model-state
    mutation the compiled-step replay cannot reproduce — fault-injected
    parameter poisons, checkpoint restores.  The next dispatch takes a
    fresh snapshot, so attribution stays exact across the mutation."""
    if _STATES is not None:
        _STATES.pop(model, None)


def _steps_of(kind: str, batch: dict) -> int:
    if kind == "mega":
        return int(batch["x"].shape[0])
    if kind == "graph_mega":
        return int(batch["labels"][0].shape[0])
    return 1


def snapshot(model, kind: str, **batch) -> Optional[_Token]:
    """Arm provenance for one dispatch.  Returns None (cost: one enum
    read) unless a panic mode is active.  The device-side state copy
    (ONE compiled dispatch for all three trees) is taken every
    ``snapshot_every`` dispatches — in between, only the batch refs are
    retained and attribution replays forward from the last snapshot.  A
    gap in the iteration sequence (an elastic rollback, an abandoned
    dispatch) voids the window and forces a fresh snapshot."""
    if not (_PROVENANCE and active()):
        return None
    global _STATES
    if _STATES is None:
        import weakref
        _STATES = weakref.WeakKeyDictionary()
    it = model._iteration
    st = _STATES.get(model)
    if st is None or st.expected_next != it \
            or it - st.snap_step >= _SNAPSHOT_EVERY:
        from deeplearning4j_tpu.train.resilience import _device_copy
        if getattr(model, "_dynamic_scaling", lambda: False)():
            # materialize the loss-scale carry BEFORE copying: the fit
            # paths snapshot first and ensure the carry just before
            # dispatch, so the first window would otherwise record None
            # and the replay would roll from the wrong (live) scale
            model._ensure_scale_state()
        params, states, opt, scale = _device_copy(
            (model._params, model._states, model._opt_state,
             getattr(model, "_scale_state", None)))
        st = _ModelSan(params, states, opt, it, scale_state=scale)
        _STATES[model] = st
    batch = dict(batch)
    st.ring.append((kind, batch, it, _steps_of(kind, batch)))
    st.expected_next = it + st.ring[-1][3]
    return _Token(st, len(st.ring) - 1, it, batch, kind)


def check(model, token: Optional[_Token], losses,
          context: str = "loss") -> None:
    """Post-dispatch numerics gate: under a panic mode, pull the loss
    (vector) and raise on NaN/Inf — with first-nonfinite attribution
    when a snapshot token is available.  Also drives the opt-in
    value-range walk.  No-op (zero device syncs) when no panic mode is
    active — call sites pay one enum read."""
    mode = get_profiling_mode()
    if mode not in (ProfilingMode.NAN_PANIC, ProfilingMode.INF_PANIC):
        return
    import numpy as np
    vals = np.asarray(losses).reshape(-1)
    # the loss gate keeps each mode's LEGACY scan (NaN-only / Inf-only,
    # matching environment.panic_check and the op-level _panic_scan);
    # once triggered, the attribution walk looks for the first
    # NONFINITE of any kind — an inf input that became a NaN loss is
    # attributed to the inf, which is the actual first bad site
    if mode is ProfilingMode.NAN_PANIC:
        bad = np.isnan(vals)
        label = "NAN_PANIC"
    else:
        bad = np.isinf(vals)
        label = "INF_PANIC"
    if not bad.any():
        if token is not None and _TRACK_RANGES \
                and (token.step0 % _TRACK_EVERY) == 0:
            _record_ranges(model, token)
        return
    _PANICS.inc()
    j = int(np.argmax(bad))                  # first bad step in the dispatch
    step = (token.step0 if token is not None else model._iteration) + j + 1
    site = None
    if token is not None:
        try:
            site = _attribute(model, token, j)
        except Exception as e:               # a diagnostic must not mask
            site = ("<replay-failed>", f"error:{type(e).__name__}")
    if site is None:
        raise NumericsPanicError(
            f"{label}: nonfinite detected in {context} "
            f"(step {step}; no provenance snapshot available)")
    layer, op = site
    _FIRST_SITE.labels(model=type(model).__name__, layer=layer,
                       op=op).set(step)
    raise NonfiniteAttributionError(
        f"{label}: nonfinite detected in {context} — first nonfinite "
        f"attributed to layer '{layer}', op '{op}', step {step}",
        layer=layer, op=op, step=step)


# ------------------------------------------------------------ attribution
def _bad_fn():
    import numpy as np

    def bad(a):
        v = np.asarray(a, dtype=np.float64) \
            if str(getattr(a, "dtype", "")) == "bfloat16" else np.asarray(a)
        return not np.isfinite(v).all()
    return bad


def _tree_bad(tree, bad) -> bool:
    import jax
    return any(bad(leaf) for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "dtype"))


def _roll_dispatch(model, kind: str, batch: dict, start_it: int,
                   n_steps: int, params, states, opt, scale=None):
    """Advance (params, states, opt[, dynamic loss-scale carry])
    ``n_steps`` update steps through the model's OWN compiled
    single-step program.  For megastep dispatches the scanned body is
    byte-identical to the single-step body, so j single steps over the
    K slices == j scanned steps."""
    import jax.numpy as jnp
    dyn = getattr(model, "_dynamic_scaling", lambda: False)()
    if dyn and scale is None:
        # snapshot predates the automaton's first materialization: roll
        # from a COPY of the live carry — the compiled step donates its
        # scale argument, and donating the training loop's own buffer
        # would delete it out from under the next real dispatch
        from deeplearning4j_tpu.train.resilience import _device_copy
        scale = _device_copy(model._ensure_scale_state())

    def run(step, *args):
        nonlocal params, states, opt, scale
        if dyn:
            params, states, opt, _, scale, _ = step(
                params, states, opt, args[0], scale, *args[1:])
        else:
            params, states, opt, _, _ = step(params, states, opt, *args)
    if n_steps <= 0:
        return params, states, opt, scale
    if kind == "tbptt":
        # segment step: donates (params, opt, t), threads the RECORDED
        # carried RNN state — each ring entry holds the seg_states it was
        # actually dispatched with, so entries never thread state between
        # replays. No dynamic-scale variant (fitTBPTT pre-dates it).
        b = batch
        sig = b.get("lmask") is not None
        if sig not in model._tbptt_step_cache:
            model._tbptt_step_cache[sig] = model._make_tbptt_step(sig)
        step = model._tbptt_step_cache[sig]
        dummy = jnp.zeros((1,))
        for i in range(n_steps):
            params, opt, _, _, _ = step(
                params, states, opt, jnp.asarray(start_it + i, jnp.int32),
                b["x"], b["y"],
                b["lmask"] if b.get("lmask") is not None else dummy,
                b["seg_states"])
        return params, states, opt, scale
    if kind in ("single", "mega"):
        mega = kind == "mega"
        b = batch
        sig = (b.get("fmask") is not None, b.get("lmask") is not None)
        if sig not in model._train_step_cache:
            model._train_step_cache[sig] = model._make_train_step(*sig)
        step = model._train_step_cache[sig]
        dummy = jnp.zeros((1,))
        for i in range(n_steps):
            sel = (lambda a: a[i]) if mega else (lambda a: a)
            run(step,
                jnp.asarray(start_it + i, jnp.int32),
                sel(b["x"]), sel(b["y"]),
                sel(b["fmask"]) if b.get("fmask") is not None else dummy,
                sel(b["lmask"]) if b.get("lmask") is not None else dummy)
    else:                                       # graph / graph_mega
        mega = kind == "graph_mega"
        b = batch
        sig = b.get("lmasks") is not None
        if sig not in model._train_step_cache:
            model._train_step_cache[sig] = model._make_train_step(sig)
        step = model._train_step_cache[sig]
        dummy = [jnp.zeros((1,))] * len(b["labels"])
        for i in range(n_steps):
            sel = (lambda a: a[i]) if mega else (lambda a: a)
            ins_i = {k: sel(v) for k, v in b["ins"].items()}
            labels_i = [sel(a) for a in b["labels"]]
            lm_i = [sel(m) for m in b["lmasks"]] \
                if b.get("lmasks") is not None else dummy
            run(step,
                jnp.asarray(start_it + i, jnp.int32),
                ins_i, labels_i, lm_i)
    return params, states, opt, scale


def _attribute(model, token: _Token, j: int) -> Tuple[str, str]:
    """Replay step ``token.step0 + j``: roll the last snapshot forward
    through every retained dispatch before this one (and j steps into
    this one), then walk the failing step eagerly.  The roll-forward
    DONATES the snapshot buffers into the compiled steps, so the
    per-model provenance window is consumed — dropped from the store
    either way, since the raise ends the fit."""
    st = token.state
    if _STATES is not None:
        _STATES.pop(model, None)
    params, states, opt = st.params, st.states, st.opt_state
    scale = st.scale_state
    for kind_i, batch_i, it_i, steps_i in st.ring[:token.ring_index]:
        params, states, opt, scale = _roll_dispatch(
            model, kind_i, batch_i, it_i, steps_i, params, states, opt,
            scale)
    params, states, opt, scale = _roll_dispatch(
        model, token.kind, token.batch, token.step0, j, params, states, opt,
        scale)
    t = token.step0 + j
    b = token.batch
    if token.kind == "tbptt":
        return _attribute_tbptt(
            model, params, states, opt, t, b["x"], b["y"],
            b.get("lmask"), b["seg_states"])
    if token.kind in ("single", "mega"):
        idx = (lambda a: a[j]) if token.kind == "mega" else (lambda a: a)
        return _attribute_multilayer(
            model, params, states, opt, t, idx(b["x"]), idx(b["y"]),
            idx(b["fmask"]) if b.get("fmask") is not None else None,
            idx(b["lmask"]) if b.get("lmask") is not None else None,
            scale_state=scale)
    idx = (lambda a: a[j]) if token.kind == "graph_mega" else (lambda a: a)
    return _attribute_graph(
        model, params, states, opt, t,
        {k: idx(v) for k, v in b["ins"].items()},
        [idx(a) for a in b["labels"]],
        [idx(m) for m in b["lmasks"]] if b.get("lmasks") is not None
        else None, scale_state=scale)


# ------------------------------------------------- shared eager walkers
def _walk_multilayer(model, params, states, x, fmask, t, train):
    """THE eager per-layer walk mirroring ``MultiLayerNetwork._forward``
    (same casts, same RNG stream) — the single copy both the
    attribution and the absmax recorder consume, so a ``_forward``
    change has exactly one mirror to keep in sync.  Yields
    ``(name, layer, cast_params, activation)`` per layer."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import layers as L
    from deeplearning4j_tpu.nn.multilayer import _MASK_AWARE
    cdt = model._compute_dtype()
    if cdt is None and getattr(x, "dtype", None) == jnp.uint8:
        x = x.astype(jnp.float32)
    key = jax.random.fold_in(jax.random.PRNGKey(model.conf.base.seed),
                             jnp.asarray(t, jnp.int32))
    nhwc = getattr(model, "_compute_layout", "NCHW") == "NHWC"
    plan = model._ensure_epilogue_plan() \
        if getattr(model, "_fuse_epilogues", False) else {}
    cur_nhwc = False
    i = 0
    while i < len(model.layers):
        layer = model.layers[i]
        if i in model.conf.preprocessors:
            if cur_nhwc:
                x, cur_nhwc = L.to_nchw(x), False
            x = model.conf.preprocessors[i](x)
        x, cur_nhwc = L.layout_step(layer, x, cur_nhwc, nhwc)
        fuse = plan.get(i)
        if fuse is not None:
            # mirror the fused-epilogue dispatch (same split count, same
            # bias fold) so replay reproduces the compiled step exactly
            n_used, conv_leads, alpha = fuse
            subs = []
            for _ in range(n_used):
                key, sub = jax.random.split(key)
                subs.append(sub)
            bn_idx = i
            bias = None
            if conv_leads:
                p = params[i]
                if cdt is not None:
                    p, x = L.policy_cast(layer, p, x, cdt)
                x, _ = layer.apply(p, states[i], x, train, subs[0],
                                   skip_bias=True)
                bias = p.get("b")
                yield f"{i}:{layer.name}", layer, p, x
                bn_idx = i + 1
            bn = model.layers[bn_idx]
            pbn = params[bn_idx]
            if cdt is not None:
                pbn, x = L.policy_cast(bn, pbn, x, cdt)
            x, _ = L.fused_bn_act(bn, pbn, states[bn_idx], x, train,
                                  alpha, bias=bias)
            yield f"{bn_idx}:{bn.name}", bn, pbn, x
            for j in range(bn_idx + 1, i + n_used):
                yield (f"{j}:{model.layers[j].name}", model.layers[j],
                       params[j], x)      # the folded activation
            i += n_used
            continue
        p = params[i]
        if cdt is not None:
            p, x = L.policy_cast(layer, p, x, cdt)
        key, sub = jax.random.split(key)
        if isinstance(layer, _MASK_AWARE):
            x, _ = layer.apply(p, states[i], x, train, sub, mask=fmask)
        else:
            x, _ = layer.apply(p, states[i], x, train, sub)
        cur_nhwc = cur_nhwc and getattr(x, "ndim", 0) == 4
        yield f"{i}:{layer.name}", layer, p, x
        i += 1


def _walk_graph(model, params, states, env, t, train):
    """THE eager per-node walk mirroring ``ComputationGraph._forward``
    (see ``_walk_multilayer``).  ``env`` maps input names to PREPARED
    arrays (cast/augmented by the caller) and is filled with every
    node's output as the walk progresses.  Yields
    ``(node, cast_params_or_None, output)``."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import layers as L
    from deeplearning4j_tpu.nn.graph import _MASK_AWARE
    cdt = model._compute_dtype()
    key = jax.random.fold_in(jax.random.PRNGKey(model.conf.base.seed),
                             jnp.asarray(t, jnp.int32))
    nhwc = getattr(model, "_compute_layout", "NCHW") == "NHWC"
    plan = model._ensure_epilogue_plan() \
        if getattr(model, "_fuse_epilogues", False) else {}
    fused_act = {act: bn for bn, (act, _c, _a) in plan.items()}
    fused_conv = {c for _a, c, _al in plan.values() if c}
    pending_bias = {}
    fmt = {k: False for k in env}
    for node in model.conf.topo:
        if node.name in fused_act:
            # folded into its BN's epilogue; keep the RNG stream aligned
            key, _ = jax.random.split(key)
            env[node.name] = env[fused_act[node.name]]
            fmt[node.name] = fmt[fused_act[node.name]]
            yield node, None, env[node.name]
            continue
        xs = [env[i] for i in node.inputs]
        if node.kind == "layer":
            xv = xs[0]
            cur_nhwc = fmt.get(node.inputs[0], False)
            if node.name in model.conf.preprocessors:
                if cur_nhwc:
                    xv, cur_nhwc = L.to_nchw(xv), False
                xv = model.conf.preprocessors[node.name](xv)
            xv, cur_nhwc = L.layout_step(node.obj, xv, cur_nhwc, nhwc)
            p = params[node.name]
            if cdt is not None:
                p, xv = L.policy_cast(node.obj, p, xv, cdt)
            key, sub = jax.random.split(key)
            if node.name in plan:          # BN anchoring a fusion
                _act, conv_name, alpha = plan[node.name]
                out, _ = L.fused_bn_act(
                    node.obj, p, states[node.name], xv, train, alpha,
                    bias=pending_bias.pop(conv_name, None))
            elif node.name in fused_conv:  # bias folds into the BN
                out, _ = node.obj.apply(p, states[node.name], xv, train,
                                        sub, skip_bias=True)
                pending_bias[node.name] = p.get("b")
            elif isinstance(node.obj, _MASK_AWARE):
                out, _ = node.obj.apply(p, states[node.name], xv, train,
                                        sub, mask=None)
            else:
                out, _ = node.obj.apply(p, states[node.name], xv, train,
                                        sub)
            fmt[node.name] = cur_nhwc and getattr(out, "ndim", 0) == 4
            if fmt[node.name]:
                out = L.to_nchw(out)     # env stays public-layout NCHW
                fmt[node.name] = False
        else:
            if cdt is not None and len(xs) > 1:
                if any(getattr(a, "dtype", None) == jnp.bfloat16
                       for a in xs):
                    xs = [a.astype(jnp.bfloat16)
                          if getattr(a, "dtype", None) == jnp.float32
                          else a for a in xs]
            p = None
            out = node.obj.apply(*xs)
        env[node.name] = out
        yield node, p, out


def _attribute_multilayer(model, params, states, opt, t, x, y, fmask,
                          lmask, scale_state=None) -> Tuple[str, str]:
    """First-nonfinite site over the shared multilayer walk."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import augment as _augment_mod
    bad = _bad_fn()
    x = jnp.asarray(x)
    if bad(x):
        return "<input>", "batch"
    x = _augment_mod.maybe_augment(model._augment, x,
                                   jnp.asarray(t, jnp.int32))
    if bad(x):
        return "<input>", "augment"
    x_step = x                  # the step body's input (post-augment):
    out = x                     # what the backward replay re-forwards
    for name, layer, p, out in _walk_multilayer(model, params, states, x,
                                                fmask, t, train=True):
        if _tree_bad(p, bad):
            return name, "params"
        if bad(out):
            return name, f"forward:{type(layer).__name__}"
    head = len(model.layers) - 1
    head_name = f"{head}:{model.layers[head].name}"
    loss = model.layers[-1].compute_loss(jnp.asarray(y), out, mask=lmask)
    if bad(loss):
        return head_name, f"loss:{getattr(model.layers[-1], 'loss_fn', '?')}"
    return _grad_site_mln(model, params, states, opt, t, x_step, y, fmask,
                          lmask, scale_state=scale_state)


def _attribute_tbptt(model, params, states, opt, t, x, y, lmask,
                     seg_states) -> Tuple[str, str]:
    """First-nonfinite site over an eager mirror of the compiled TBPTT
    segment body (``_make_tbptt_step.loss_fn``): same preprocessors,
    same RNG stream, same carried-state threading — so the attributed
    (layer, op, step) names the segment step that actually went bad,
    including a poisoned carried RNN state crossing a segment boundary."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.multilayer import _MASK_AWARE
    bad = _bad_fn()
    cur = jnp.asarray(x)
    if bad(cur):
        return "<input>", "batch"
    key = jax.random.fold_in(jax.random.PRNGKey(model.conf.base.seed),
                             jnp.asarray(t, jnp.int32))
    for i, layer in enumerate(model.layers):
        name = f"{i}:{layer.name}"
        if i in model.conf.preprocessors:
            cur = model.conf.preprocessors[i](cur)
        if _tree_bad(params[i], bad):
            return name, "params"
        if seg_states[i] is not None and _tree_bad(seg_states[i], bad):
            return name, "carried-state"
        key, sub = jax.random.split(key)
        if hasattr(layer, "apply_with_state"):
            cur, _ = layer.apply_with_state(params[i], cur, seg_states[i])
        elif isinstance(layer, _MASK_AWARE):
            cur, _ = layer.apply(params[i], states[i], cur, True, sub,
                                 mask=None)
        else:
            cur, _ = layer.apply(params[i], states[i], cur, True, sub)
        if bad(cur):
            return name, f"forward:{type(layer).__name__}"
    head = len(model.layers) - 1
    head_name = f"{head}:{model.layers[head].name}"
    loss = model.layers[-1].compute_loss(jnp.asarray(y), cur, mask=lmask)
    if bad(loss):
        return head_name, f"loss:{getattr(model.layers[-1], 'loss_fn', '?')}"
    return _grad_site_tbptt(model, params, states, opt, t, x, y, lmask,
                            seg_states)


def _grad_site_tbptt(model, params, states, opt, t, x, y, lmask,
                     seg_states) -> Tuple[str, str]:
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.multilayer import _MASK_AWARE
    bad = _bad_fn()
    seed = model.conf.base.seed
    x_j, y_j = jnp.asarray(x), jnp.asarray(y)

    def loss_fn(p):
        cur = x_j
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 jnp.asarray(t, jnp.int32))
        for i, layer in enumerate(model.layers):
            if i in model.conf.preprocessors:
                cur = model.conf.preprocessors[i](cur)
            key, sub = jax.random.split(key)
            if hasattr(layer, "apply_with_state"):
                cur, _ = layer.apply_with_state(p[i], cur, seg_states[i])
            elif isinstance(layer, _MASK_AWARE):
                cur, _ = layer.apply(p[i], states[i], cur, True, sub,
                                     mask=None)
            else:
                cur, _ = layer.apply(p[i], states[i], cur, True, sub)
        return model.layers[-1].compute_loss(y_j, cur, mask=lmask)
    grads = jax.grad(loss_fn)(params)
    names = [f"{i}:{l.name}" for i, l in enumerate(model.layers)]
    hit = _first_bad_leaf(grads, names, bad)
    if hit is not None:
        return hit, "backward"
    return _updater_site(model, params, grads, opt, t, names, bad)


def _attribute_graph(model, params, states, opt, t, ins, labels,
                     lmasks, scale_state=None) -> Tuple[str, str]:
    """First-nonfinite site over the shared graph walk."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import augment as _augment_mod
    bad = _bad_fn()
    cdt = model._compute_dtype()
    env = {}
    for k, v in ins.items():
        v = jnp.asarray(v)
        if bad(v):
            return f"<input:{k}>", "batch"
        if model._augment is not None:
            v = _augment_mod.maybe_augment(model._augment, v,
                                           jnp.asarray(t, jnp.int32))
        if cdt is None and getattr(v, "dtype", None) == jnp.uint8:
            v = v.astype(jnp.float32)
        env[k] = v
    for node, p, out in _walk_graph(model, params, states, env, t,
                                    train=True):
        if p is not None and _tree_bad(p, bad):
            return node.name, "params"
        if bad(out):
            kind = "forward" if node.kind == "layer" else "vertex"
            return node.name, f"{kind}:{type(node.obj).__name__}"
    for i, name in enumerate(model.conf.graph_outputs):
        node = model.conf.node_by_name[name]
        lm = lmasks[i] if lmasks is not None else None
        loss = node.obj.compute_loss(jnp.asarray(labels[i]), env[name],
                                     mask=lm)
        if bad(loss):
            return name, f"loss:{getattr(node.obj, 'loss_fn', '?')}"
    return _grad_site_graph(model, params, states, opt, t, ins, labels,
                            lmasks, scale_state=scale_state)


# ------------------------------------------------- backward/updater sites
def _first_bad_leaf(tree, names, bad) -> Optional[str]:
    """Name of the first layer whose grad/state subtree has a nonfinite."""
    import jax
    for name, sub in zip(names, tree):
        if any(bad(leaf) for leaf in jax.tree_util.tree_leaves(sub)
               if hasattr(leaf, "dtype")):
            return name
    return None


def _loss_scale_of(model, scale_state=None):
    """The scale the eager grad walk should apply: static policies use
    their constant; dynamic policies use ``scale_state`` — the carry
    the attribution replay rolled to, threaded explicitly from
    ``_attribute`` — falling back to the model's live automaton (and
    finally the policy's init value)."""
    pol = getattr(model, "_precision", None)
    if pol is None:
        return None
    if pol.is_dynamic:
        if scale_state is None:
            scale_state = getattr(model, "_scale_state", None)
        if scale_state is None:
            return float(pol.loss_scale_init)
        import jax
        import numpy as np
        return float(np.asarray(jax.device_get(scale_state))[0])
    return pol.loss_scale


def _grad_site_mln(model, params, states, opt, t, x, y, fmask,
                   lmask, scale_state=None) -> Tuple[str, str]:
    import jax
    import jax.numpy as jnp
    bad = _bad_fn()
    scale = _loss_scale_of(model, scale_state)
    key = jax.random.fold_in(jax.random.PRNGKey(model.conf.base.seed),
                             jnp.asarray(t, jnp.int32))

    def loss_fn(p):
        loss = model._loss_and_reg(p, states, jnp.asarray(x),
                                   jnp.asarray(y), True, key, fmask,
                                   lmask)[0]
        return loss * scale if scale else loss
    grads = jax.grad(loss_fn)(params)
    names = [f"{i}:{l.name}" for i, l in enumerate(model.layers)]
    # the compiled step checks/applies grads SCALED first (overflow in the
    # scaled grads is the classic fp16 failure), then unscales for the
    # updater — mirror both halves
    hit = _first_bad_leaf(grads, names, bad)
    if hit is not None:
        return hit, "backward"
    if scale:
        grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
    return _updater_site(model, params, grads, opt, t, names, bad)


def _grad_site_graph(model, params, states, opt, t, ins, labels,
                     lmasks, scale_state=None) -> Tuple[str, str]:
    import jax
    import jax.numpy as jnp
    bad = _bad_fn()
    key = jax.random.fold_in(jax.random.PRNGKey(model.conf.base.seed),
                             jnp.asarray(t, jnp.int32))
    ins_j = {k: jnp.asarray(v) for k, v in ins.items()}
    labels_j = [jnp.asarray(a) for a in labels]
    scale = _loss_scale_of(model, scale_state)

    def loss_fn(p):
        loss = model._loss_and_reg(p, states, ins_j, labels_j, True, key,
                                   None, lmasks)[0]
        return loss * scale if scale else loss
    grads = jax.grad(loss_fn)(params)
    names = sorted(grads)
    hit = _first_bad_leaf([grads[n] for n in names], names, bad)
    if hit is not None:
        return hit, "backward"
    if scale:
        grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
    return _updater_site(model, params, grads, opt, t, names, bad,
                         graph=True)


def _updater_site(model, params, grads, opt, t, names, bad,
                  graph: bool = False) -> Tuple[str, str]:
    """Apply one updater step eagerly and name the first layer whose new
    opt-state/params go nonfinite; falls back to the loss head."""
    from deeplearning4j_tpu.nn.multilayer import _process_and_apply_grads
    base = model.conf.base
    new_params, new_opt = _process_and_apply_grads(
        base, base.updater, params, grads, opt, float(t))
    upd_name = type(base.updater).__name__
    if graph:
        order = names
        new_p = [new_params[n] for n in order]
        new_o = [new_opt[n] for n in order]
    else:
        order = names
        new_p, new_o = new_params, new_opt
    hit = _first_bad_leaf(new_o, order, bad)
    if hit is not None:
        return hit, f"updater:{upd_name}"
    hit = _first_bad_leaf(new_p, order, bad)
    if hit is not None:
        return hit, f"updater:{upd_name}"
    return order[-1] if order else "<model>", "dispatch"


# --------------------------------------------------- value-range tracking
def _record_ranges(model, token: _Token) -> None:
    """One eager forward recording per-layer activation |max| — the
    opt-in dl4j_tensor_absmax / overflow-proximity walk."""
    import numpy as np
    try:
        sites = _collect_absmax(model, token)
    except Exception:
        return                              # diagnostics never break a fit
    if not sites:
        return
    mname = type(model).__name__
    peak = 0.0
    for layer, amax in sites:
        _ABSMAX.labels(model=mname, layer=layer).observe(amax)
        if np.isfinite(amax):
            peak = max(peak, amax)
    pol = getattr(model, "_precision", None)
    if pol is not None and pol.is_low_precision:
        _PROXIMITY.set(peak / pol.compute_max())


def _collect_absmax(model, token: _Token) -> List[Tuple[str, float]]:
    """Per-layer |max| over the SAME shared walkers attribution uses
    (live post-step params — a magnitude diagnostic, not a replay)."""
    import jax.numpy as jnp
    import numpy as np
    b = token.batch
    out: List[Tuple[str, float]] = []

    def amax(a):
        v = np.asarray(a, dtype=np.float64) \
            if str(getattr(a, "dtype", "")) == "bfloat16" else np.asarray(a)
        return float(np.max(np.abs(v))) if v.size else 0.0

    cdt = model._compute_dtype()
    if token.kind in ("single", "mega"):
        x = jnp.asarray(b["x"][0] if token.kind == "mega" else b["x"])
        fmask = b.get("fmask")
        if fmask is not None and token.kind == "mega":
            fmask = fmask[0]
        for name, _, _, act in _walk_multilayer(
                model, model._params, model._states, x, fmask,
                token.step0, train=False):
            out.append((name, amax(act)))
    else:
        idx = (lambda a: a[0]) if token.kind == "graph_mega" else (lambda a: a)
        env = {}
        for k, v in b["ins"].items():
            v = jnp.asarray(idx(v))
            if cdt is None and v.dtype == jnp.uint8:
                v = v.astype(jnp.float32)
            env[k] = v
        for node, _, o in _walk_graph(model, model._params, model._states,
                                      env, token.step0, train=False):
            out.append((node.name, amax(o)))
    return out
