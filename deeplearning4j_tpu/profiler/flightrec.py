"""Crash flight recorder: always-on event ring + debug bundle dump.

When a fit dies with :class:`NonfiniteAttributionError`, a serving
dispatch times out, or a coordination peer goes dead, the evidence an
operator needs — what was dispatching, which metrics were moving, what
the compile cache and device topology looked like — is gone by the time
anyone attaches a profiler. The flight recorder keeps it cheaply,
always:

- :meth:`FlightRecorder.record` appends a structured event (kind +
  fields + monotonic timestamp) to a bounded ring. It is **always on**
  (no tracing flag): one deque append per event, and the integration
  points are low-frequency seams (dispatch signatures, retries,
  failures, dead peers, fault injections, device-health probes), never
  per-op hot paths.
- :meth:`FlightRecorder.dump` writes a debug bundle directory on
  trigger: ``events.json`` (the ring), ``trace.json`` (the process
  tracer's recent spans — Perfetto-loadable), ``metrics.txt`` (full
  registry exposition), ``config.json`` (backend/device/topology,
  compile-cache status + stats + runtime fingerprint, pid/python), and
  ``reason.txt`` (trigger type, message, traceback). Dumps are
  rate-limited per reason and **never raise** — a recorder failure must
  not mask the crash it is documenting.

Triggers wired in this PR: ``fit_scope`` (any non-preemption crash),
the serving loop's death path and :class:`DispatchTimeoutError`
retries, coordinator dead-peer detection, and
:class:`NonfiniteAttributionError` via the resilience seam. The bundle
directory defaults to ``$DL4J_FLIGHTREC_DIR`` or
``<tempdir>/dl4j-flightrec``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Deque, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

_ENV_DIR = "DL4J_FLIGHTREC_DIR"


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_jsonable(x) for x in v]
    return repr(v)


class FlightRecorder:
    """Bounded ring of structured events plus the bundle dumper.

    ``capacity`` bounds memory (a deque of dicts); ``min_dump_interval``
    rate-limits dumps *per reason* so a retry storm produces one bundle,
    not hundreds; ``clock`` is injectable for tests.
    """

    def __init__(self, capacity: int = 4096,
                 directory: Optional[str] = None,
                 min_dump_interval: float = 5.0,
                 clock=time.monotonic):
        self.capacity = int(capacity)
        self.directory = directory
        self.min_dump_interval = float(min_dump_interval)
        self._clock = clock
        self._ring: Deque[dict] = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_dump: dict = {}
        self._seq = 0
        self.dumps: List[str] = []

    # ---------------------------------------------------------- record
    def record(self, kind: str, **fields) -> None:
        """Append one event to the ring (always-on; one lock + deque
        append). ``fields`` must be cheap — repr() is applied lazily
        only at dump time for non-JSON values."""
        ev = {"t": self._clock(), "kind": str(kind)}
        if fields:
            ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    def events(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self._ring)
        return evs if last is None else evs[-int(last):]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------ dump
    def _resolve_dir(self, directory: Optional[str]) -> str:
        return (directory or self.directory or os.environ.get(_ENV_DIR)
                or os.path.join(tempfile.gettempdir(), "dl4j-flightrec"))

    def _config(self) -> dict:
        cfg: dict = {"pid": os.getpid(), "python": sys.version,
                     "argv": list(sys.argv)}
        try:
            from deeplearning4j_tpu.nn import compilecache as _cc
            cfg["compile_cache"] = {
                "dir": _cc.cache_dir(),
                "status": _jsonable(_cc.cache_dir_status()),
                "stats": _jsonable(_cc.cache_stats()),
                "runtime_fingerprint": _cc.runtime_fingerprint(),
            }
        except Exception as e:                      # pragma: no cover
            cfg["compile_cache"] = {"error": repr(e)}
        try:
            # guarded: jax may be mid-crash or devices unreachable —
            # a bundle without topology beats no bundle
            import jax
            cfg["backend"] = jax.default_backend()
            cfg["devices"] = [str(d) for d in jax.devices()]
            cfg["process_index"] = jax.process_index()
        except Exception as e:
            cfg["jax"] = {"error": repr(e)}
        return cfg

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             directory: Optional[str] = None) -> Optional[str]:
        """Write a debug bundle; returns its path, or None when
        rate-limited or the write failed. NEVER raises."""
        try:
            now = self._clock()
            with self._lock:
                last = self._last_dump.get(reason)
                if last is not None \
                        and now - last < self.min_dump_interval:
                    return None
                self._last_dump[reason] = now
                self._seq += 1
                seq = self._seq
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(reason))[:64]
            root = self._resolve_dir(directory)
            path = os.path.join(root,
                                f"flightrec-{os.getpid()}-{seq}-{safe}")
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "events.json"), "w") as f:
                json.dump([_jsonable(ev) for ev in self.events()], f,
                          indent=1)
            try:
                from deeplearning4j_tpu.profiler import tracer as _tracer
                with open(os.path.join(path, "trace.json"), "w") as f:
                    json.dump(_tracer.get_tracer().to_chrome_trace(), f)
            except Exception as e:
                with open(os.path.join(path, "trace.json"), "w") as f:
                    json.dump({"error": repr(e)}, f)
            try:
                from deeplearning4j_tpu.profiler import metrics as _m
                with open(os.path.join(path, "metrics.txt"), "w") as f:
                    f.write(_m.get_registry().exposition())
            except Exception as e:
                with open(os.path.join(path, "metrics.txt"), "w") as f:
                    f.write(f"# exposition failed: {e!r}\n")
            with open(os.path.join(path, "config.json"), "w") as f:
                json.dump(_jsonable(self._config()), f, indent=1)
            with open(os.path.join(path, "reason.txt"), "w") as f:
                f.write(f"reason: {reason}\n")
                if exc is not None:
                    f.write(f"exception: {type(exc).__name__}: {exc}\n\n")
                    f.write("".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__)))
            with self._lock:
                self.dumps.append(path)
            logger.warning("flight recorder dumped %s bundle: %s",
                           reason, path)
            return path
        except Exception:                           # pragma: no cover
            logger.warning("flight recorder dump failed", exc_info=True)
            return None


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """Process-wide recorder singleton (what the serving loop,
    fit_scope, and the coordinator record into)."""
    return _RECORDER


def configure(directory: Optional[str] = None,
              capacity: Optional[int] = None,
              min_dump_interval: Optional[float] = None) -> FlightRecorder:
    """Adjust the singleton in place (events already recorded are kept
    unless capacity shrinks below the ring's length)."""
    r = _RECORDER
    if directory is not None:
        r.directory = directory
    if capacity is not None:
        r.capacity = int(capacity)
        with r._lock:
            r._ring = collections.deque(r._ring, maxlen=r.capacity)
    if min_dump_interval is not None:
        r.min_dump_interval = float(min_dump_interval)
    return r
