"""Declarative SLOs with multi-window burn-rate gates.

PR 12's rollout gate and PR 13's warmup verdicts are point-in-time
checks; an operator still has to eyeball ``/metrics`` to decide "is the
fleet healthy *enough*". This module turns the existing counter and
histogram series into declarative objectives evaluated the way SRE
burn-rate alerting does:

- :class:`SLOSpec` — one named objective over any subset of criteria:
  a latency bound at an objective quantile ("99% of requests under
  250ms"), a shed-rate ceiling, an availability target (non-``failed``
  terminal outcomes), and a step-time regression bound against a
  recorded baseline ("fit steps within 1.2x of the bench baseline").
- :class:`SLOEngine` — keeps a bounded ring of (timestamp, metric
  snapshot) pairs and, per spec, computes the **burn rate** over a
  fast and a slow window: ``burn = bad_fraction / allowed_fraction``
  (burn 1.0 = consuming error budget exactly at the rate that exhausts
  it by period end). A spec is *failing* only when burn exceeds the
  threshold in BOTH windows — the standard multi-window rule: the slow
  window filters blips, the fast window makes recovery visible
  immediately after a drain, so the gate flips back quickly.
  Each evaluation exports ``dl4j_slo_burn_rate{slo,window}``.
- :class:`SLOGate` — a callable verdict usable anywhere a canary judge
  fits: ``ModelRegistry.roll(..., judge=gate)`` style checks, CI
  thresholds, or the ingress ``GET /v1/slo`` endpoint.

Everything reads the process registry (or an injected one) — no new
instrumentation is required at the measured sites, and the injectable
``clock`` keeps window arithmetic deterministic under test.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.profiler import metrics as _metrics
from deeplearning4j_tpu.profiler.locks import InstrumentedLock

# terminal outcomes counted as load shedding (mirrors
# ModelServer._SHED_OUTCOMES; duplicated here so the SLO layer does not
# import the serving stack it judges)
SHED_OUTCOMES = ("shed_overload", "shed_deadline", "shed_draining",
                 "rejected_unhealthy")

DEFAULT_LATENCY_METRIC = "dl4j_serving_latency_seconds"
DEFAULT_REQUESTS_METRIC = "dl4j_serving_requests_total"
DEFAULT_STEP_METRIC = "dl4j_train_iteration_seconds"


class SLOSpec:
    """One named objective. Any subset of the criteria may be set; the
    spec's burn rate is the max over its active criteria.

    - ``latency_bound`` (seconds) at ``objective`` (e.g. 0.99): the
      fraction of requests slower than the bound, divided by the
      allowed fraction ``1 - objective``.
    - ``shed_rate``: ceiling on the shed fraction of terminal outcomes;
      burn = shed_fraction / ceiling.
    - ``availability``: target fraction of non-``failed`` outcomes;
      burn = failed_fraction / (1 - availability).
    - ``step_time_baseline`` (seconds) with ``step_time_regression``
      factor: burn = windowed_mean_step / (baseline * regression).

    ``windows`` is (fast, slow) in seconds.
    """

    __slots__ = ("name", "objective", "latency_bound", "latency_metric",
                 "shed_rate", "availability", "requests_metric",
                 "step_time_baseline", "step_time_regression",
                 "step_metric", "windows")

    def __init__(self, name: str, objective: float = 0.99,
                 latency_bound: Optional[float] = None,
                 latency_metric: str = DEFAULT_LATENCY_METRIC,
                 shed_rate: Optional[float] = None,
                 availability: Optional[float] = None,
                 requests_metric: str = DEFAULT_REQUESTS_METRIC,
                 step_time_baseline: Optional[float] = None,
                 step_time_regression: float = 1.2,
                 step_metric: str = DEFAULT_STEP_METRIC,
                 windows: Tuple[float, float] = (60.0, 600.0)):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if shed_rate is not None and not 0.0 < shed_rate <= 1.0:
            raise ValueError(f"shed_rate ceiling must be in (0, 1], "
                             f"got {shed_rate}")
        if availability is not None and not 0.0 < availability < 1.0:
            raise ValueError(f"availability must be in (0, 1), "
                             f"got {availability}")
        if len(windows) != 2 or windows[0] >= windows[1]:
            raise ValueError(f"windows must be (fast, slow) with "
                             f"fast < slow, got {windows}")
        self.name = name
        self.objective = float(objective)
        self.latency_bound = latency_bound
        self.latency_metric = latency_metric
        self.shed_rate = shed_rate
        self.availability = availability
        self.requests_metric = requests_metric
        self.step_time_baseline = step_time_baseline
        self.step_time_regression = float(step_time_regression)
        self.step_metric = step_metric
        self.windows = (float(windows[0]), float(windows[1]))

    def metric_names(self) -> List[str]:
        names = []
        if self.latency_bound is not None:
            names.append(self.latency_metric)
        if self.shed_rate is not None or self.availability is not None:
            names.append(self.requests_metric)
        if self.step_time_baseline is not None:
            names.append(self.step_metric)
        return names


def _snapshot_metric(metric) -> Optional[dict]:
    """Capture one family's windowable state: cumulative histogram
    counts (summed over children) or per-child counter values."""
    if isinstance(metric, _metrics.Histogram):
        children = list(metric.children().values()) or [metric]
        bounds = metric.buckets
        counts = [0.0] * (len(bounds) + 1)
        total, s = 0.0, 0.0
        for child in children:
            with child._lock:
                for i, c in enumerate(child._counts):
                    counts[i] += c
                total += child._count
                s += child._sum
        return {"type": "histogram", "bounds": bounds, "counts": counts,
                "count": total, "sum": s}
    if isinstance(metric, _metrics.Counter):
        if metric.labelnames:
            children = {lvals: child.value for lvals, child
                        in metric.children().items()}
        else:
            children = {(): metric.value}
        return {"type": "counter", "children": children}
    return None


class SLOEngine:
    """Evaluate :class:`SLOSpec` burn rates from registry snapshots.

    Every :meth:`evaluate` call appends one (now, snapshot) sample to a
    bounded ring and computes each spec's burn over its fast and slow
    windows by differencing against the newest sample at least
    window-seconds old (falling back to the oldest sample while the
    ring is still shorter than the window — conservative: early burn
    reflects all data seen so far). Results are exported as
    ``dl4j_slo_burn_rate{slo,window}`` on the same registry.
    """

    def __init__(self, specs: Sequence[SLOSpec],
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 clock=time.monotonic, max_samples: int = 512,
                 threshold: float = 1.0):
        self.specs = list(specs)
        self.registry = registry or _metrics.get_registry()
        self.threshold = float(threshold)
        self._clock = clock
        self._max_samples = int(max_samples)
        self._samples: List[Tuple[float, Dict[str, dict]]] = []
        self._lock = InstrumentedLock("slo:engine")
        self._burn = self.registry.gauge(
            "dl4j_slo_burn_rate",
            "Error-budget burn rate per SLO and evaluation window "
            "(1.0 = consuming budget exactly at the exhaustion rate; "
            "failing = above threshold in BOTH windows)",
            labelnames=("slo", "window"))
        self._names = sorted({n for s in self.specs
                              for n in s.metric_names()})

    # --------------------------------------------------------- sampling
    def _capture(self) -> Dict[str, dict]:
        snap = {}
        for name in self._names:
            metric = self.registry.get(name)
            if metric is None:
                continue
            data = _snapshot_metric(metric)
            if data is not None:
                snap[name] = data
        return snap

    @staticmethod
    def _reference(samples, now: float, window: float
                   ) -> Optional[Tuple[float, Dict[str, dict]]]:
        """Newest sample at least ``window`` old; else the oldest."""
        ref = None
        for t, snap in samples:
            if now - t >= window:
                ref = (t, snap)
            else:
                break
        if ref is None and samples:
            ref = samples[0]
        return ref

    # ------------------------------------------------------------ burns
    @staticmethod
    def _hist_delta(cur: Optional[dict], ref: Optional[dict]):
        if cur is None or cur.get("type") != "histogram":
            return None
        counts = list(cur["counts"])
        count, s = cur["count"], cur["sum"]
        if ref is not None and ref.get("type") == "histogram" \
                and len(ref["counts"]) == len(counts):
            counts = [c - r for c, r in zip(counts, ref["counts"])]
            count -= ref["count"]
            s -= ref["sum"]
        return {"bounds": cur["bounds"], "counts": counts,
                "count": count, "sum": s}

    @staticmethod
    def _counter_delta(cur: Optional[dict], ref: Optional[dict]
                       ) -> Dict[Tuple, float]:
        if cur is None or cur.get("type") != "counter":
            return {}
        refc = (ref or {}).get("children", {}) \
            if (ref or {}).get("type") == "counter" else {}
        return {k: v - refc.get(k, 0.0)
                for k, v in cur["children"].items()}

    def _spec_burn(self, spec: SLOSpec, cur: Dict[str, dict],
                   ref: Optional[Dict[str, dict]]) -> Dict[str, float]:
        ref = ref or {}
        burns: Dict[str, float] = {}
        if spec.latency_bound is not None:
            h = self._hist_delta(cur.get(spec.latency_metric),
                                 ref.get(spec.latency_metric))
            if h is not None and h["count"] > 0:
                # observations above the bound = total minus cumulative
                # count at the smallest bucket bound >= the SLO bound
                cum = 0.0
                covered = 0.0
                matched = False
                for bound, c in zip(h["bounds"], h["counts"]):
                    cum += c
                    if bound >= spec.latency_bound:
                        covered, matched = cum, True
                        break
                if not matched:
                    covered = cum   # bound above all buckets: +Inf bad
                bad_frac = max(h["count"] - covered, 0.0) / h["count"]
                burns["latency"] = bad_frac / (1.0 - spec.objective)
        outcomes = None
        if spec.shed_rate is not None or spec.availability is not None:
            deltas = self._counter_delta(cur.get(spec.requests_metric),
                                         ref.get(spec.requests_metric))
            outcomes = {(k[0] if k else ""): v for k, v in deltas.items()}
        if outcomes:
            total = sum(outcomes.values())
            if total > 0:
                if spec.shed_rate is not None:
                    shed = sum(outcomes.get(o, 0.0) for o in SHED_OUTCOMES)
                    burns["shed"] = (shed / total) / spec.shed_rate
                if spec.availability is not None:
                    failed = outcomes.get("failed", 0.0)
                    burns["availability"] = (failed / total) / \
                        (1.0 - spec.availability)
        if spec.step_time_baseline is not None:
            h = self._hist_delta(cur.get(spec.step_metric),
                                 ref.get(spec.step_metric))
            if h is not None and h["count"] > 0:
                mean = h["sum"] / h["count"]
                burns["step_time"] = mean / (spec.step_time_baseline *
                                             spec.step_time_regression)
        return burns

    def burn_over(self, seconds: float) -> Dict[str, float]:
        """One-off burn per spec over an arbitrary lookback window —
        what the lifecycle driver's canary judge asks ("how did the
        fleet burn over THIS observation window?", which rarely matches
        the spec's alerting windows). Snapshots now but does NOT append
        a sample or export gauges, so interleaved calls never perturb
        :meth:`evaluate`'s multi-window series. Returns
        ``{spec_name: burn}`` (0.0 while the ring is empty)."""
        now = self._clock()
        snap = self._capture()
        with self._lock:
            samples_view = list(self._samples)
        out: Dict[str, float] = {}
        for spec in self.specs:
            ref = self._reference(samples_view, now, float(seconds))
            criteria = self._spec_burn(spec, snap, ref[1] if ref else None)
            out[spec.name] = max(criteria.values()) if criteria else 0.0
        return out

    def evaluate(self) -> dict:
        """Take a fresh snapshot, compute every spec's fast/slow burn,
        export the gauges, and return the full detail dict::

            {"failing": [names], "specs": {name: {
                "failing": bool,
                "windows": {"fast": {"seconds", "burn", "criteria"},
                            "slow": {...}}}}}
        """
        now = self._clock()
        snap = self._capture()
        with self._lock:
            self._samples.append((now, snap))
            if len(self._samples) > self._max_samples:
                del self._samples[:len(self._samples) - self._max_samples]
            samples_view = list(self._samples)
        detail: dict = {"failing": [], "specs": {}, "threshold":
                        self.threshold}
        for spec in self.specs:
            windows = {}
            over = []
            for label, seconds in zip(("fast", "slow"), spec.windows):
                ref = self._reference(samples_view, now, seconds)
                criteria = self._spec_burn(spec, snap,
                                           ref[1] if ref else None)
                burn = max(criteria.values()) if criteria else 0.0
                self._burn.labels(slo=spec.name, window=label).set(burn)
                windows[label] = {"seconds": seconds, "burn": burn,
                                  "criteria": criteria}
                over.append(burn > self.threshold)
            failing = all(over)
            detail["specs"][spec.name] = {"failing": failing,
                                          "windows": windows}
            if failing:
                detail["failing"].append(spec.name)
        return detail


class SLOVerdict:
    """The gate's answer: truthy when every spec is within budget."""

    __slots__ = ("passing", "failures", "detail")

    def __init__(self, passing: bool, failures: List[str], detail: dict):
        self.passing = passing
        self.failures = list(failures)
        self.detail = detail

    def __bool__(self) -> bool:
        return self.passing

    def __repr__(self):
        state = "passing" if self.passing else \
            f"FAILING({', '.join(self.failures)})"
        return f"SLOVerdict({state})"


class SLOGate:
    """Callable canary judge over an :class:`SLOEngine`: evaluates on
    call and returns an :class:`SLOVerdict` (truthy = healthy). Use as
    the accept/reject check around ``ModelRegistry.roll`` /
    ``rollback``, in CI, or behind ``GET /v1/slo``."""

    def __init__(self, engine: SLOEngine):
        self.engine = engine

    def __call__(self) -> SLOVerdict:
        detail = self.engine.evaluate()
        failing = detail["failing"]
        return SLOVerdict(not failing, failing, detail)
