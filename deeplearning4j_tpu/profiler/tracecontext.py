"""Distributed request tracing: W3C-``traceparent``-style context.

PR 1 gave every subsystem spans (``profiler.tracer``) and PR 7/12 gave
serving per-hop *timings* — but nothing correlated them: a request's
admission span, its queue wait, the coalesced dispatch that served it,
and the ingress response write were four unrelated ring-buffer entries.
This module adds the correlation layer the TensorFlow-Serving
operational stack treats as table stakes (PAPERS.md):

- :class:`TraceContext` — a (trace_id, span_id, parent_id) triple with
  W3C Trace Context wire form (``00-<32 hex>-<16 hex>-01``). The
  ingress honors an incoming ``traceparent`` header or mints a fresh
  context; IDs are *always* minted (os.urandom, sub-microsecond) so
  every response can carry its ``trace_id`` even with tracing off,
  while span *recording* stays gated on
  :func:`~deeplearning4j_tpu.profiler.tracer.tracing_enabled` — the
  near-zero-disabled-cost contract is unchanged.
- :func:`record_span` — records one completed span under a context on
  the process tracer: ``args`` carry ``trace_id``/``span_id``/
  ``parent_span_id`` plus optional ``links`` (span links). One
  coalesced batch serving N requests emits ONE dispatch span whose
  ``links`` name each request's root span — the fan-in edge.
- an ambient *current context* (contextvar): :func:`use` installs one
  for a code region and every span recorded meanwhile — op dispatch,
  ``train:step``, barrier waits — is stamped with its ``trace_id``
  (via the :func:`tracer.set_context_args_fn` hook), so training
  dispatches correlate with the ``fit``/``fit_elastic`` ``run_id``
  root span without touching the fit loops.
- the context rides the CoordinationService JSON-line protocol
  (``"trace"`` field) so a multi-process barrier round's client and
  server spans share one trace_id, and :func:`merge_chrome_traces`
  folds per-process Chrome-trace documents into one Perfetto-loadable
  flow.

Span vocabulary (the hops ISSUE 16 names)::

    ingress:request   wire recv -> response written (root per request)
    serve:route       registry route resolution (version pin; re-route
                      across a hot-swap shows as a version change)
    serve:admission   submit() admission decision
    serve:queue       enqueued -> popped into a batch (per request)
    serve:coalesce    batch build wait (per batch)
    serve:dispatch    forward dispatch (per batch; links fan-in)
    serve:retry       one failed dispatch attempt (per retry)
    serve:terminal    exactly-once resolution (per request; outcome arg)
    ingress:respond   response serialization + write
    coord:barrier     client-side barrier round-trip
    coord:round       server-side barrier round
    train:run         fit root span (run_id = trace_id)
"""

from __future__ import annotations

import contextvars
import os
import re
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional

from deeplearning4j_tpu.profiler import tracer as _tracer

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def _hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class TraceContext:
    """One node of a distributed trace: ``trace_id`` names the whole
    request flow, ``span_id`` this hop, ``parent_id`` the hop that
    caused it (None at the root). Immutable by convention — derive with
    :meth:`child`."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def new(cls) -> "TraceContext":
        """Mint a fresh root context (a new trace)."""
        return cls(_hex(16), _hex(8))

    def child(self) -> "TraceContext":
        """A child hop: same trace, new span id, parented here."""
        return TraceContext(self.trace_id, _hex(8), self.span_id)

    # ------------------------------------------------------------- wire
    def to_traceparent(self) -> str:
        """W3C Trace Context header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header) -> Optional["TraceContext"]:
        """Parse a ``traceparent`` header; None when absent/malformed
        (a bad header must never fail the request — mint instead)."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(str(header).strip().lower())
        if m is None:
            return None
        version, trace_id, span_id = m.group(1), m.group(2), m.group(3)
        if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
            return None     # forbidden version / all-zero ids per spec
        return cls(trace_id, span_id)

    def args(self) -> Dict[str, str]:
        a = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            a["parent_span_id"] = self.parent_id
        return a

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…, span={self.span_id}"
                f"{', parent=' + self.parent_id if self.parent_id else ''})")


# ------------------------------------------------------ ambient context
_CURRENT: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("dl4j_trace_context", default=None)


def current() -> Optional[TraceContext]:
    """The ambient trace context of the calling thread/task (None when
    no request/run is in scope)."""
    return _CURRENT.get()


@contextmanager
def use(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the ambient context for the body — every span
    recorded meanwhile is stamped with its trace_id."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def _ambient_args() -> Optional[Dict[str, str]]:
    ctx = _CURRENT.get()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id}


# installed at import (profiler/__init__ imports this module): ordinary
# spans recorded under an ambient context inherit its trace_id
_tracer.set_context_args_fn(_ambient_args)


# ---------------------------------------------------------- recording
def record_span(name: str, ctx: Optional[TraceContext], ts_us: float,
                dur_us: float, args: Optional[dict] = None,
                links: Optional[Iterable] = None, tracer=None) -> None:
    """Record one completed span under ``ctx`` (no-op when tracing is
    off or ``ctx`` is None). ``links`` is an iterable of
    :class:`TraceContext` (or ready-made dicts) naming spans this one
    fans in from — the coalesced-batch edge."""
    if ctx is None or not _tracer.tracing_enabled():
        return
    a = dict(args) if args else {}
    a.update(ctx.args())
    if links:
        a["links"] = [l.args() if isinstance(l, TraceContext) else dict(l)
                      for l in links]
    (tracer if tracer is not None else _tracer.get_tracer()).add_event(
        name, ts_us, dur_us, a)


@contextmanager
def span(name: str, parent: Optional[TraceContext] = None,
         links: Optional[Iterable] = None, **args):
    """Context manager: open a child span of ``parent`` (default: the
    ambient context; a fresh root when neither exists), make it ambient
    for the body, record it on exit. Yields the span's own
    :class:`TraceContext`. Exceptions are recorded
    (``error=<TypeName>``) and re-raised."""
    base = parent if parent is not None else _CURRENT.get()
    ctx = base.child() if base is not None else TraceContext.new()
    t0 = _tracer.now_us()
    token = _CURRENT.set(ctx)
    err = None
    try:
        yield ctx
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _CURRENT.reset(token)
        a = dict(args)
        if err is not None:
            a["error"] = err
        record_span(name, ctx, t0, _tracer.now_us() - t0, args=a,
                    links=links)


@contextmanager
def run_span(name: str = "train:run", **args):
    """Root span for a training run: mints a fresh trace whose
    ``trace_id`` doubles as the ``run_id``, installs it as the ambient
    context (so every step/op span recorded during the fit carries it),
    and records the root span at exit. Yields the run's
    :class:`TraceContext`."""
    ctx = TraceContext.new()
    t0 = _tracer.now_us()
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)
        record_span(name, ctx, t0, _tracer.now_us() - t0,
                    args=dict(args, run_id=ctx.trace_id))


# ------------------------------------------------------------- merging
def merge_chrome_traces(docs: Iterable) -> dict:
    """Fold several Chrome-trace documents (dicts with ``traceEvents``,
    or bare event lists — e.g. one per process of a multi-host job)
    into ONE Perfetto-loadable document: spans sharing a ``trace_id``
    across processes render as a single stitched flow. Duplicate
    thread-name metadata collapses to one entry per (pid, tid)."""
    events: List[dict] = []
    seen_meta = set()
    for doc in docs:
        evs = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
        for ev in evs:
            if ev.get("ph") == "M":
                key = (ev.get("name"), ev.get("pid"), ev.get("tid"))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spans_for_trace(trace_id: str, events: Optional[Iterable[dict]] = None
                    ) -> List[dict]:
    """Every recorded span stamped with ``trace_id`` (from ``events``
    or the process tracer's ring) — what the chaos/e2e pins assert on."""
    if events is None:
        events = _tracer.get_tracer().events()
    return [ev for ev in events
            if ev.get("args", {}).get("trace_id") == trace_id]
