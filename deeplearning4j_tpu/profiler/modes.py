"""ProfilingMode — unified op-execution profiling levels.

Reference parity: ``org.nd4j.linalg.api.ops.executioner.OpExecutioner
.ProfilingMode`` (OFF / BASIC / NAN_PANIC / INF_PANIC — SURVEY.md §5).
The seed scattered this across two independent Environment booleans
(``nan_panic``/``inf_panic``) plus a ``profiling`` flag; this module is
the single source of truth the op dispatcher, the fit loops, and
``environment.panic_check`` all consult.

Resolution order: an explicit ``set_profiling_mode(...)`` override wins;
otherwise the mode is derived from the Environment knobs on every read
(so ``DL4J_TPU_NAN_PANIC=1`` + ``Environment.reset()`` in tests behaves
exactly as before this module existed).
"""

from __future__ import annotations

import enum
from typing import Optional


class ProfilingMode(enum.Enum):
    OFF = "off"            # no per-op instrumentation
    BASIC = "basic"        # per-op dispatch timing + counters
    NAN_PANIC = "nan_panic"  # BASIC + raise on NaN in op outputs/loss
    INF_PANIC = "inf_panic"  # BASIC + raise on Inf in op outputs/loss


_OVERRIDE: Optional[ProfilingMode] = None


def set_profiling_mode(mode: Optional[ProfilingMode]) -> None:
    """Set the process-wide mode; ``None`` reverts to Environment-derived."""
    global _OVERRIDE
    if mode is not None and not isinstance(mode, ProfilingMode):
        mode = ProfilingMode(str(mode).lower())
    _OVERRIDE = mode


def get_profiling_mode() -> ProfilingMode:
    if _OVERRIDE is not None:
        return _OVERRIDE
    from deeplearning4j_tpu.utils.environment import Environment
    # lock-free fast path: this sits on every eager dispatch, and the
    # singleton is immutable-in-place except via reset() (which swaps the
    # instance — worst case we read the old one for one call)
    env = Environment._instance
    if env is None:
        env = Environment.get()
    if env.nan_panic:
        return ProfilingMode.NAN_PANIC
    if env.inf_panic:
        return ProfilingMode.INF_PANIC
    if env.profiling:
        return ProfilingMode.BASIC
    return ProfilingMode.OFF
