"""Thread-safe span tracer with Chrome Trace Event Format export.

Reference parity: libnd4j ``OpProfiler`` timelines and the reference's
ProfilingListener trace writer (SURVEY.md §5 "Tracing/profiling") — but as
a first-class subsystem rather than a listener side effect: any layer of
the stack (op dispatch, native runtime, collectives, the fit loop) opens
spans through one API and they land in one timeline, the way TensorFlow's
tracing and TVM's time evaluators treat per-op timelines as load-bearing
infrastructure (Abadi et al. 2016; Chen et al. 2018).

Design:

- ``trace_span("op:conv2d", shape=(8, 256))`` is a context manager AND a
  decorator; spans nest naturally (begin/end timestamps carry the nesting
  — Perfetto/catapult reconstruct the flame graph from ts/dur + tid).
- Near-zero cost when disabled: a module-level ``_ENABLED`` flag is
  checked before ANY allocation; a disabled span is one attribute read.
- Completed spans go into a bounded ring buffer (oldest evicted first) so
  a long training run cannot grow host memory without bound.
- ``stream_to(path)`` additionally appends every span to a Chrome-trace
  JSON file AS IT COMPLETES — spans past the ring-buffer horizon live on
  disk instead of silently dropping, so a multi-hour fit's first epoch
  is still in the trace (``stop_stream()`` finalizes the file; a killed
  process leaves a truncated array Perfetto still loads).
- Export is Chrome Trace Event Format JSON ("X" complete events + "M"
  thread-name metadata), loadable in Perfetto (ui.perfetto.dev) and
  chrome://tracing.

The tracer is orthogonal to ``jax.profiler`` (ProfilingListener): jax
traces XLA device internals; this traces the *framework* — dispatch,
transfers, cache behaviour, data-wait vs compute — on hosts where the XLA
profiler plugin is unavailable (e.g. relayed TPU backends).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# module-level fast path: checked before span allocation (see trace_span)
_ENABLED = False

# one monotonic epoch per process so spans from every thread share a
# timebase (Chrome trace ts is in microseconds from an arbitrary origin)
_EPOCH_NS = time.perf_counter_ns()

# streamed-trace flush cadence: every N events (the file is also closed
# cleanly by stop_stream; a killed process loses at most one buffer)
_STREAM_FLUSH_EVERY = 256

# ambient trace-context stamp (set by profiler.tracecontext on import):
# returns a small dict of args (e.g. {"trace_id": ...}) merged into every
# recorded span that does not already carry them — how ordinary op/fit
# spans correlate with the distributed request/run trace they ran under
_CTX_ARGS_FN = None


def set_context_args_fn(fn) -> None:
    """Install the ambient-context stamper (``None`` uninstalls). The
    callable must be cheap (one contextvar read) and return a dict of
    span args or None."""
    global _CTX_ARGS_FN
    _CTX_ARGS_FN = fn


def enable_tracing() -> None:
    """Turn span recording on (module-level flag)."""
    global _ENABLED
    _ENABLED = True


def disable_tracing() -> None:
    global _ENABLED
    _ENABLED = False


def tracing_enabled() -> bool:
    return _ENABLED


def _now_us() -> float:
    return (time.perf_counter_ns() - _EPOCH_NS) / 1000.0


#: public alias — call sites that time a region themselves use this to
#: stamp after-the-fact events on the tracer's timebase
now_us = _now_us


class SpanTracer:
    """Bounded ring buffer of completed spans (thread-safe)."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()   # per-thread open-span stack
        self._stream = None             # open file: see stream_to()
        self._stream_path: Optional[str] = None
        self._stream_count = 0
        self._stream_flush_every = _STREAM_FLUSH_EVERY
        self._stream_tids: set = set()  # every (pid, tid) EVER streamed —
        # the ring may have evicted a thread's spans by stop_stream time,
        # but its thread_name metadata must still land in the file

    # ------------------------------------------------------------- recording
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, name: str, args: Optional[Dict[str, Any]] = None) -> tuple:
        token = (name, _now_us(), args)
        self._stack().append(token)
        return token

    def end(self, token: tuple) -> None:
        st = self._stack()
        if st and st[-1] is token:
            st.pop()
        name, ts, args = token
        self.add_event(name, ts, _now_us() - ts, args, depth=len(st))

    def add_event(self, name: str, ts_us: float, dur_us: float,
                  args: Optional[Dict[str, Any]] = None,
                  depth: int = 0) -> None:
        """Record one completed span directly (after-the-fact API for call
        sites that measured a region without holding a context manager)."""
        ev = {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        if depth:
            ev.setdefault("args", {})["depth"] = depth
        if _CTX_ARGS_FN is not None:
            extra = _CTX_ARGS_FN()
            if extra:
                a = ev.setdefault("args", {})
                for k, v in extra.items():
                    a.setdefault(k, v)
        with self._lock:
            self._events.append(ev)
            if self._stream is not None:
                # streamed BEFORE ring eviction can drop it: long fits
                # keep every span on disk while host memory stays bounded
                try:
                    prefix = ",\n" if self._stream_count else ""
                    self._stream.write(prefix + json.dumps(ev))
                    self._stream_count += 1
                    self._stream_tids.add((ev["pid"], ev["tid"]))
                    if self._stream_count % self._stream_flush_every == 0:
                        self._stream.flush()
                except OSError as e:
                    stream, self._stream = self._stream, None
                    try:
                        stream.close()
                    except OSError:
                        pass
                    import warnings
                    warnings.warn(
                        f"trace stream to {self._stream_path} failed "
                        f"({e}) — streaming disabled, ring buffer "
                        "retention continues", stacklevel=3)

    def current_depth(self) -> int:
        """Open-span nesting depth on the calling thread."""
        return len(self._stack())

    # --------------------------------------------------------------- reading
    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> List[dict]:
        """Snapshot of recorded spans (oldest first)."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # ---------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict:
        """Chrome Trace Event Format document (perfetto-loadable)."""
        evs = self.events()
        # thread-name metadata so Perfetto labels rows usefully
        seen = {}
        for ev in evs:
            seen.setdefault((ev["pid"], ev["tid"]), None)
        meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": _thread_name(tid)}}
                for pid, tid in seen]
        return {"traceEvents": meta + evs, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: Optional[str] = None) -> str:
        """Serialize to Chrome trace JSON; write to ``path`` if given."""
        doc = json.dumps(self.to_chrome_trace())
        if path:
            with open(path, "w") as f:
                f.write(doc)
        return doc

    # ------------------------------------------------------------- streaming
    def stream_to(self, path: str,
                  flush_every: int = _STREAM_FLUSH_EVERY) -> "SpanTracer":
        """Append every completed span to ``path`` as it is recorded —
        the disk-resident escape hatch from the ring buffer's horizon: a
        long fit's early spans survive on disk after the ring evicted
        them. The file is the Chrome Trace Event JSON-array format
        (Perfetto loads a truncated array from a killed process too);
        :meth:`stop_stream` terminates it properly with the thread-name
        metadata. Idempotent per path; a second call with a different
        path closes the first stream. ``flush_every`` tunes the flush
        cadence — a crash-forensics stream (the flight recorder's) sets
        1 so a killed process loses nothing buffered."""
        with self._lock:
            if self._stream is not None:
                if self._stream_path == path:
                    return self
                self._close_stream_locked()
            f = open(path, "w", buffering=1 << 16)
            f.write("[\n")
            self._stream = f
            self._stream_path = path
            self._stream_count = 0
            self._stream_flush_every = max(int(flush_every), 1)
            self._stream_tids = set()
        return self

    def stop_stream(self) -> Optional[str]:
        """Finish the streamed trace (thread-name metadata + closing
        bracket) and close the file. Returns the path, or None when no
        stream was active."""
        with self._lock:
            return self._close_stream_locked()

    def _close_stream_locked(self) -> Optional[str]:
        # contract: caller holds self._lock (the _locked suffix) — the
        # static linter cannot see a caller-held lock, hence the noqas
        if self._stream is None:
            return None
        path, stream = self._stream_path, self._stream
        self._stream = None               # dl4j: noqa=E201
        self._stream_path = None          # dl4j: noqa=E201
        try:
            # every (pid, tid) that EVER streamed — not just the ring's
            # survivors: early-epoch threads whose spans aged out of the
            # ring still get their Perfetto row labelled
            seen = set(self._stream_tids)
            self._stream_tids = set()     # dl4j: noqa=E201 (lock held)
            for ev in self._events:
                seen.add((ev["pid"], ev["tid"]))
            for pid, tid in sorted(seen):
                prefix = ",\n" if self._stream_count else ""
                stream.write(prefix + json.dumps(
                    {"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": _thread_name(tid)}}))
                self._stream_count += 1   # dl4j: noqa=E202
            stream.write("\n]\n")
        except OSError as e:
            # same contract as the recording path: a full disk at
            # teardown warns — a truncated array is Perfetto-loadable,
            # and stop_stream must never crash the end-of-fit path
            import warnings
            warnings.warn(
                f"trace stream finalize to {path} failed ({e}) — the "
                "streamed file is a truncated (still loadable) array",
                stacklevel=3)
        finally:
            try:
                stream.close()
            except OSError:
                pass
        self._stream_count = 0            # dl4j: noqa=E201
        return path


def _thread_name(tid: int) -> str:
    for t in threading.enumerate():
        if t.ident == tid:
            return t.name
    return f"thread-{tid}"


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    """Process-wide tracer singleton (what ``GET /trace`` serves)."""
    return _TRACER


class trace_span:
    """Context manager / decorator recording one span on the global tracer.

    ::

        with trace_span("op:conv2d", args_shape=(8, 1, 16, 16)):
            ...
        @trace_span("data:augment")
        def augment(batch): ...

    When tracing is disabled the context manager is a no-op (one flag
    read, no allocation beyond the object itself) and the decorated
    function adds a single flag check per call.
    """

    __slots__ = ("name", "args", "_token", "_tracer")

    def __init__(self, name: str, tracer: Optional[SpanTracer] = None,
                 **args):
        self.name = name
        self.args = args or None
        self._token = None
        self._tracer = tracer

    def _t(self) -> SpanTracer:
        # explicit None check: SpanTracer.__len__ makes an empty tracer
        # falsy, so `self._tracer or _TRACER` would silently misroute
        return self._tracer if self._tracer is not None else _TRACER

    def __enter__(self):
        if _ENABLED:
            self._token = self._t().begin(self.name, self.args)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            self._t().end(self._token)
            self._token = None
        return False

    def __call__(self, fn):
        name, args = self.name, self.args

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _ENABLED:
                return fn(*a, **kw)
            t = self._t()
            token = t.begin(name, args)
            try:
                return fn(*a, **kw)
            finally:
                t.end(token)
        return wrapper
