"""Cross-host metric federation: merge fleet members' /metrics.

The ROADMAP's fleet router needs fleet-wide load/latency signals;
today every ``/metrics`` and ``/v1/load`` is one host's view. This
module federates them without a Prometheus server in the pod (the
environment is egress-free — same constraint that made
``profiler.metrics`` speak the text format natively):

- :func:`parse_exposition` — a small parser for the Prometheus text
  exposition (0.0.4 *and* the OpenMetrics dialect our registry renders:
  exemplar annotations after ``#`` are stripped, ``# EOF`` ignored).
- :class:`MetricsAggregator` — ingests per-host snapshots and merges
  by family with per-type rules:

  * **counters** sum across hosts (a fleet total),
  * **gauges** keep a ``host`` label (a gauge is a per-host instant;
    summing queue depths is meaningful only for some gauges, so the
    merged exposition preserves per-host values and lets the reader
    aggregate),
  * **histograms** bucket-merge: per-``le`` counts sum over the union
    of bucket layouts, so a *fleet* p99 is computable from
    :meth:`HistogramSnapshot.quantile` with exactly the
    ``histogram_quantile`` interpolation ``Histogram.quantile`` uses
    locally.

  Snapshots age out (``max_age``) so a dead host stops shaping fleet
  quantiles a bounded time after its last scrape.
- :class:`FleetScraper` — drives scrape targets from CoordinationService
  membership: participants advertise a ``metrics_url`` in their
  ``hello`` meta, the coordinator server exposes
  :meth:`~deeplearning4j_tpu.distributed.coordinator.
  SocketCoordinatorServer.members`, and the scraper pulls each fresh
  member's ``/metrics`` (and ``/v1/load``) over stdlib urllib. Dead
  hosts (stale heartbeat) are skipped and age out of the merge.

The ingress exposes the result at ``GET /v1/fleet/metrics`` (merged
exposition) and ``GET /v1/fleet/load`` (merged autoscaling hints).
Fleet-meta series rendered into the merged exposition:
``dl4j_fleet_members``, ``dl4j_fleet_snapshot_age_seconds{host=}``,
``dl4j_fleet_scrapes_total``, ``dl4j_fleet_scrape_errors_total``.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.profiler import metrics as _metrics
from deeplearning4j_tpu.profiler.locks import InstrumentedLock

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape(v: str) -> str:
    return v.replace(r"\"", '"').replace(r"\n", "\n").replace("\\\\", "\\")


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


class Family:
    """One parsed metric family: ``samples`` maps
    ``(suffix, labels_tuple)`` -> value, where ``labels_tuple`` is a
    sorted tuple of (name, value) pairs."""

    __slots__ = ("name", "typ", "help", "samples")

    def __init__(self, name: str, typ: str = "untyped", help: str = ""):
        self.name = name
        self.typ = typ
        self.help = help
        self.samples: Dict[Tuple[str, Tuple], float] = {}


def parse_exposition(text: str) -> Dict[str, Family]:
    """Prometheus/OpenMetrics text -> {family name: :class:`Family`}.
    Histogram ``_bucket``/``_sum``/``_count`` series fold into their
    base family; exemplar annotations and ``# EOF`` are ignored."""
    families: Dict[str, Family] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                name = parts[2]
                fam = families.setdefault(name, Family(name))
                if parts[1] == "TYPE" and len(parts) >= 4:
                    fam.typ = parts[3].strip()
                elif parts[1] == "HELP":
                    fam.help = parts[3] if len(parts) >= 4 else ""
            continue
        # strip an OpenMetrics exemplar annotation (" # {...} v")
        if " # " in line:
            line = line.split(" # ", 1)[0].rstrip()
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        sample_name, label_blob, raw = m.group(1), m.group(2), m.group(3)
        try:
            value = _parse_value(raw)
        except ValueError:
            continue
        # a suffix only folds into a base family that was declared a
        # histogram — a counter legitimately named *_count keeps its own
        base, suffix = sample_name, ""
        for sfx in _HIST_SUFFIXES:
            if sample_name.endswith(sfx) \
                    and sample_name[:-len(sfx)] in families \
                    and families[sample_name[:-len(sfx)]].typ == "histogram":
                base, suffix = sample_name[:-len(sfx)], sfx
                break
        labels = tuple(sorted((n, _unescape(v)) for n, v in
                              _LABEL_RE.findall(label_blob or "")))
        fam = families.setdefault(base, Family(base))
        fam.samples[(suffix, labels)] = value
    return families


class HistogramSnapshot:
    """A merged (or single-host) cumulative histogram:
    ``bounds`` are finite upper bounds, ``cumulative`` the cumulative
    counts per bound, ``count``/``sum`` the totals. :meth:`quantile`
    matches :meth:`deeplearning4j_tpu.profiler.metrics.Histogram.
    quantile` (linear interpolation within the owning bucket) so a
    fleet p99 is the same computation as a local one."""

    __slots__ = ("bounds", "cumulative", "count", "sum")

    def __init__(self, bounds: List[float], cumulative: List[float],
                 count: float, sum: float):
        self.bounds = list(bounds)
        self.cumulative = list(cumulative)
        self.count = count
        self.sum = sum

    def quantile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        total = self.count
        if total <= 0:
            return None
        rank = q * total
        cum_prev = 0.0
        lo = 0.0
        for bound, cum in zip(self.bounds, self.cumulative):
            c = cum - cum_prev
            if c > 0 and cum >= rank:
                frac = (rank - cum_prev) / c
                return lo + (bound - lo) * max(min(frac, 1.0), 0.0)
            cum_prev = cum
            lo = bound
        return self.bounds[-1] if self.bounds else None


def _merge_histogram(per_host: List[Dict[Tuple[str, Tuple], float]],
                     labels: Tuple) -> Optional[HistogramSnapshot]:
    """Merge one labelset's cumulative buckets across hosts: convert
    each host's cumulative counts to per-bucket deltas keyed by ``le``,
    sum over the union grid, re-cumulate. Identical layouts merge
    exactly; differing layouts merge on the union of bounds (each
    host's mass stays at its own bound — the merged histogram is the
    histogram of the union of observations at each host's resolution)."""
    deltas: Dict[float, float] = {}
    total_count = 0.0
    total_sum = 0.0
    any_data = False
    for samples in per_host:
        bounds = []
        for (suffix, lbls), value in samples.items():
            if suffix != "_bucket":
                continue
            le = dict(lbls).get("le")
            rest = tuple(p for p in lbls if p[0] != "le")
            if le is None or rest != labels:
                continue
            bounds.append((_parse_value(le), value))
        if not bounds:
            continue
        any_data = True
        bounds.sort(key=lambda bv: bv[0])
        prev = 0.0
        for bound, cum in bounds:
            deltas[bound] = deltas.get(bound, 0.0) + (cum - prev)
            prev = cum
        total_count += samples.get(("_count", labels), bounds[-1][1])
        total_sum += samples.get(("_sum", labels), 0.0)
    if not any_data:
        return None
    finite = sorted(b for b in deltas if b != float("inf"))
    cumulative = []
    cum = 0.0
    for b in finite:
        cum += deltas[b]
        cumulative.append(cum)
    return HistogramSnapshot(finite, cumulative, total_count, total_sum)


class MetricsAggregator:
    """Merge per-host Prometheus snapshots into a fleet view (module
    doc for the per-type rules). ``max_age`` seconds after its last
    ingest a host's snapshot stops contributing (dead-host age-out);
    ``clock`` is injectable for deterministic tests."""

    def __init__(self, max_age: float = 30.0, clock=time.monotonic):
        self.max_age = float(max_age)
        self._clock = clock
        self._lock = InstrumentedLock("fleet:aggregator")
        self._snapshots: Dict[str, Tuple[float, Dict[str, Family]]] = {}
        self._loads: Dict[str, Tuple[float, dict]] = {}
        self._scrapes = 0
        self._scrape_errors = 0

    # ------------------------------------------------------------ ingest
    def ingest(self, host: str, text: str) -> None:
        """Store one host's exposition snapshot (parsed immediately so
        a malformed body fails the ingest, not a later read)."""
        families = parse_exposition(text)
        with self._lock:
            self._snapshots[str(host)] = (self._clock(), families)
            self._scrapes += 1

    def ingest_load(self, host: str, hints: dict) -> None:
        """Store one host's ``/v1/load`` payload for :meth:`fleet_load`."""
        with self._lock:
            self._loads[str(host)] = (self._clock(), dict(hints))

    def note_scrape_error(self) -> None:
        with self._lock:
            self._scrape_errors += 1

    def drop(self, host: str) -> None:
        with self._lock:
            self._snapshots.pop(str(host), None)
            self._loads.pop(str(host), None)

    def _fresh(self) -> Dict[str, Tuple[float, Dict[str, Family]]]:
        # caller holds the lock
        now = self._clock()
        return {h: (t, fams) for h, (t, fams) in self._snapshots.items()
                if now - t <= self.max_age}

    def hosts(self) -> List[str]:
        """Hosts currently contributing (ingested within ``max_age``)."""
        with self._lock:
            return sorted(self._fresh())

    # ------------------------------------------------------------- merge
    def fleet_histogram(self, name: str, labels: Optional[dict] = None
                        ) -> Optional[HistogramSnapshot]:
        """The merged fleet histogram for ``name`` (None when no fresh
        host exposes it). ``labels`` filters to one labelset (ignoring
        ``le``); default: the unlabelled series."""
        want = tuple(sorted((str(k), str(v))
                            for k, v in (labels or {}).items()))
        with self._lock:
            fresh = self._fresh()
        per_host = [fams[name].samples for _, fams in fresh.values()
                    if name in fams]
        return _merge_histogram(per_host, want)

    def quantile(self, name: str, q: float,
                 labels: Optional[dict] = None) -> Optional[float]:
        """Fleet quantile from the merged buckets (the number the
        fleet router thresholds on)."""
        snap = self.fleet_histogram(name, labels)
        return None if snap is None else snap.quantile(q)

    def counter_total(self, name: str,
                      labels: Optional[dict] = None) -> float:
        """Summed counter value across fresh hosts."""
        want = tuple(sorted((str(k), str(v))
                            for k, v in (labels or {}).items()))
        with self._lock:
            fresh = self._fresh()
        total = 0.0
        for _, fams in fresh.values():
            fam = fams.get(name)
            if fam is None:
                continue
            for (suffix, lbls), value in fam.samples.items():
                if suffix == "" and lbls == want:
                    total += value
        return total

    def exposition(self) -> str:
        """The merged fleet exposition (what ``GET /v1/fleet/metrics``
        serves): counters summed, gauges per-host under a ``host``
        label, histograms bucket-merged, plus the ``dl4j_fleet_*``
        meta-series."""
        with self._lock:
            fresh = self._fresh()
            scrapes, errors = self._scrapes, self._scrape_errors
            now = self._clock()
            ages = {h: now - t for h, (t, _) in self._snapshots.items()}
        names: Dict[str, Family] = {}
        for _, fams in fresh.values():
            for name, fam in fams.items():
                if name not in names:
                    names[name] = Family(name, fam.typ, fam.help)
        lines: List[str] = []
        for name in sorted(names):
            proto = names[name]
            lines.append(f"# HELP {name} {proto.help}")
            lines.append(f"# TYPE {name} {proto.typ}")
            if proto.typ == "histogram":
                lines.extend(self._render_histogram(name, fresh))
            elif proto.typ == "gauge":
                lines.extend(self._render_gauge(name, fresh))
            else:               # counter / untyped: sum across hosts
                lines.extend(self._render_counter(name, fresh))
        lines.append("# HELP dl4j_fleet_members Hosts contributing to "
                     "the merged fleet view (fresh within max_age)")
        lines.append("# TYPE dl4j_fleet_members gauge")
        lines.append(f"dl4j_fleet_members {len(fresh)}")
        lines.append("# HELP dl4j_fleet_snapshot_age_seconds Seconds "
                     "since each member's snapshot was ingested")
        lines.append("# TYPE dl4j_fleet_snapshot_age_seconds gauge")
        for h in sorted(ages):
            lines.append(f'dl4j_fleet_snapshot_age_seconds'
                         f'{{host="{_metrics._escape_label(h)}"}} '
                         f"{_metrics._format_value(ages[h])}")
        lines.append("# HELP dl4j_fleet_scrapes_total Snapshots "
                     "ingested into the aggregator")
        lines.append("# TYPE dl4j_fleet_scrapes_total counter")
        lines.append(f"dl4j_fleet_scrapes_total {scrapes}")
        lines.append("# HELP dl4j_fleet_scrape_errors_total Failed "
                     "member scrapes (host skipped that round)")
        lines.append("# TYPE dl4j_fleet_scrape_errors_total counter")
        lines.append(f"dl4j_fleet_scrape_errors_total {errors}")
        return "\n".join(lines) + "\n"

    def _labelsets(self, name: str, fresh, suffix: str = "") -> List[Tuple]:
        seen = []
        for _, fams in fresh.values():
            fam = fams.get(name)
            if fam is None:
                continue
            for (sfx, lbls) in fam.samples:
                if sfx != suffix:
                    continue
                key = tuple(p for p in lbls if p[0] != "le")
                if key not in seen:
                    seen.append(key)
        return sorted(seen)

    @staticmethod
    def _fmt(name: str, labels: Tuple, value: float,
             suffix: str = "", extra: Optional[Tuple] = None) -> str:
        pairs = list(labels) + list(extra or ())
        blob = ""
        if pairs:
            inner = ",".join(
                f'{n}="{_metrics._escape_label(v)}"' for n, v in pairs)
            blob = "{" + inner + "}"
        return f"{name}{suffix}{blob} {_metrics._format_value(value)}"

    def _render_counter(self, name: str, fresh) -> List[str]:
        out = []
        for labels in self._labelsets(name, fresh):
            total = 0.0
            for _, fams in fresh.values():
                fam = fams.get(name)
                if fam is not None:
                    total += fam.samples.get(("", labels), 0.0)
            out.append(self._fmt(name, labels, total))
        return out

    def _render_gauge(self, name: str, fresh) -> List[str]:
        out = []
        for labels in self._labelsets(name, fresh):
            for host in sorted(fresh):
                fam = fresh[host][1].get(name)
                if fam is None or ("", labels) not in fam.samples:
                    continue
                out.append(self._fmt(name, labels,
                                     fam.samples[("", labels)],
                                     extra=(("host", host),)))
        return out

    def _render_histogram(self, name: str, fresh) -> List[str]:
        out = []
        for labels in self._labelsets(name, fresh, suffix="_bucket"):
            per_host = [fams[name].samples for _, fams in fresh.values()
                        if name in fams]
            snap = _merge_histogram(per_host, labels)
            if snap is None:
                continue
            for bound, cum in zip(snap.bounds, snap.cumulative):
                out.append(self._fmt(
                    name, labels, cum, suffix="_bucket",
                    extra=(("le", _metrics._format_value(bound)),)))
            out.append(self._fmt(name, labels, snap.count,
                                 suffix="_bucket", extra=(("le", "+Inf"),)))
            out.append(self._fmt(name, labels, snap.sum, suffix="_sum"))
            out.append(self._fmt(name, labels, snap.count,
                                 suffix="_count"))
        return out

    # -------------------------------------------------------------- load
    def fleet_load(self) -> dict:
        """Merged autoscaling hints (``GET /v1/fleet/load``): per-host
        payloads under ``hosts`` plus fleet totals a router can
        threshold on — the fleet-wide twin of the per-host
        ``/v1/load``."""
        with self._lock:
            now = self._clock()
            loads = {h: hints for h, (t, hints) in self._loads.items()
                     if now - t <= self.max_age}
        totals = {"queue_depth": 0, "max_queue": 0, "breakers_open": 0,
                  "shed_rate": 0.0, "ready": bool(loads), "hosts": len(loads)}
        for hints in loads.values():
            t = hints.get("totals", hints)
            totals["queue_depth"] += int(t.get("queue_depth", 0))
            totals["max_queue"] += int(t.get("max_queue", 0))
            totals["breakers_open"] += int(t.get("breakers_open", 0))
            totals["shed_rate"] += float(t.get("shed_rate", 0.0))
            totals["ready"] = totals["ready"] and bool(t.get("ready", False))
        if loads:
            totals["shed_rate"] = round(totals["shed_rate"] / len(loads), 6)
        return {"hosts": loads, "totals": totals}


# ------------------------------------------------------------- scraping
def members_from_coordinator(server, fresh_within: Optional[float] = None
                             ) -> Dict[str, str]:
    """Scrape targets from CoordinationService membership: every fresh
    participant that advertised a ``metrics_url`` in its hello meta.
    Returns {participant: base_url}."""
    out = {}
    for name, info in server.members(fresh_within=fresh_within).items():
        url = (info.get("meta") or {}).get("metrics_url")
        if url:
            out[name] = str(url)
    return out


class FleetScraper:
    """Pull each member's ``/metrics`` (and ``/v1/load`` when present)
    into a :class:`MetricsAggregator`. ``members`` is a callable
    returning {host: base_url} — typically
    ``lambda: members_from_coordinator(coord_server)`` so scrape
    targets track heartbeat-fresh membership and dead hosts fall out.
    ``start()`` runs a background thread at ``interval``;
    :meth:`scrape_once` is the synchronous form tests drive."""

    def __init__(self, aggregator: MetricsAggregator,
                 members: Callable[[], Dict[str, str]],
                 interval: float = 5.0, timeout: float = 2.0):
        self.aggregator = aggregator
        self.members = members
        self.interval = float(interval)
        self.timeout = float(timeout)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = InstrumentedLock("fleet:scraper")

    def scrape_once(self) -> Dict[str, bool]:
        """One synchronous sweep; returns {host: succeeded}."""
        results = {}
        try:
            targets = dict(self.members())
        except Exception:
            return results
        for host, base in targets.items():
            base = base.rstrip("/")
            try:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=self.timeout) as r:
                    self.aggregator.ingest(host,
                                           r.read().decode("utf-8"))
                results[host] = True
            except Exception:
                self.aggregator.note_scrape_error()
                results[host] = False
                continue
            try:
                with urllib.request.urlopen(base + "/v1/load",
                                            timeout=self.timeout) as r:
                    self.aggregator.ingest_load(
                        host, json.loads(r.read().decode("utf-8")))
            except Exception:
                pass    # load hints are optional (e.g. a bare UIServer)
        return results

    def start(self) -> "FleetScraper":
        with self._lifecycle:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="dl4j-fleet-scraper")
                self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.scrape_once()

    def stop(self) -> None:
        self._stop.set()
        with self._lifecycle:
            if self._thread is not None:
                self._thread.join(timeout=self.timeout + 1.0)
                self._thread = None

    def __enter__(self) -> "FleetScraper":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
