"""Instrumented locks — the dynamic half of the concurrency tooling.

:class:`InstrumentedLock` / :class:`InstrumentedRLock` /
:class:`InstrumentedCondition` are drop-in replacements for the
``threading`` primitives that, when :func:`~deeplearning4j_tpu.profiler.
instrumentation_active` (ProfilingMode != OFF or tracing on), record:

- ``dl4j_lock_wait_seconds{lock=...}`` — time spent *waiting* to
  acquire (contention latency),
- ``dl4j_lock_hold_seconds{lock=...}`` — time the lock was *held*
  (critical-section length — long holds are the contention cause),
- ``dl4j_lock_contention_total{lock=...}`` — acquisitions that could
  not take the lock uncontended (had to block at all).

With instrumentation off the overhead is one module-flag check per
acquire/release on top of the raw primitive (measured by
``benchmarks/probe_lock_overhead.py``; the <5% fit-overhead bound is
asserted there).

Independently of ProfilingMode, a process-wide **lock-order witness**
(:func:`enable_lock_order_witness`) records the per-thread held-lock
stack and the observed acquisition edges: the first time two
instrumented locks are taken in both orders — the runtime signature of
the static ``DL4J-E203`` deadlock lint — it raises
:class:`LockOrderInversionError` (tests) or warns once (production),
and counts ``dl4j_lock_order_inversions_total``. The witness is the
dynamic confirmation channel for E203: the static pass proves the
cycle exists in the code, the witness proves a real schedule walked it.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.profiler import metrics as _metrics
from deeplearning4j_tpu.profiler.modes import ProfilingMode, \
    get_profiling_mode
from deeplearning4j_tpu.profiler.tracer import tracing_enabled

_REG = _metrics.get_registry()
#: bucket layout tuned for lock latencies (1us .. 1s)
_LOCK_BUCKETS = (1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2,
                 5e-2, 0.1, 0.5, 1.0)
LOCK_WAIT = _REG.histogram(
    "dl4j_lock_wait_seconds",
    "Time spent blocked acquiring an instrumented lock",
    labelnames=("lock",), buckets=_LOCK_BUCKETS)
LOCK_HOLD = _REG.histogram(
    "dl4j_lock_hold_seconds",
    "Time an instrumented lock was held (critical-section length)",
    labelnames=("lock",), buckets=_LOCK_BUCKETS)
LOCK_CONTENTION = _REG.counter(
    "dl4j_lock_contention_total",
    "Acquisitions of an instrumented lock that had to block",
    labelnames=("lock",))
LOCK_INVERSIONS = _REG.counter(
    "dl4j_lock_order_inversions_total",
    "Lock-order inversions observed by the runtime witness (each is a "
    "potential deadlock — the dynamic confirmation of DL4J-E203)")


def _active() -> bool:
    return tracing_enabled() or get_profiling_mode() is not ProfilingMode.OFF


class LockOrderInversionError(RuntimeError):
    """Two instrumented locks were acquired in both orders (A->B on one
    code path, B->A on another) — the runtime signature of a potential
    deadlock. Raised only while the witness runs in raising mode
    (tests); production mode warns once per edge pair instead."""


class _LockOrderWitness:
    """Process-wide acquisition-order recorder (module singleton)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.raise_on_inversion = True
        # (first, then) -> first site observed, for the error message
        self._edges: Dict[Tuple[str, str], str] = {}
        self._warned: set = set()
        self._tls = threading.local()

    def _held(self) -> List[str]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._warned.clear()

    def on_acquired(self, name: str) -> None:
        held = self._held()
        if held:
            me = threading.current_thread().name
            inversion = None
            with self._lock:
                for outer in held:
                    if outer == name:
                        continue        # re-entrant acquire, not ordering
                    self._edges.setdefault((outer, name),
                                           f"thread {me}")
                    rev = self._edges.get((name, outer))
                    if rev is not None and inversion is None:
                        inversion = (outer, name, rev)
            if inversion is not None:   # raise/warn outside our own lock
                self._inversion(*inversion)
        held.append(name)

    def _inversion(self, outer: str, inner: str, rev_site: str) -> None:
        LOCK_INVERSIONS.inc()
        msg = (f"lock-order inversion: this thread acquired "
               f"'{inner}' while holding '{outer}', but the opposite "
               f"order '{inner}' -> '{outer}' was already observed "
               f"({rev_site}) — two such threads interleaved deadlock "
               f"(DL4J-E203 at runtime)")
        if self.raise_on_inversion:
            raise LockOrderInversionError(msg)
        key = tuple(sorted((outer, inner)))
        with self._lock:
            if key in self._warned:
                return
            self._warned.add(key)
        warnings.warn(msg, RuntimeWarning, stacklevel=4)

    def on_released(self, name: str) -> None:
        # called unconditionally from release paths: bail before the
        # list construction when this thread never pushed anything (the
        # overwhelmingly common disabled case)
        held = getattr(self._tls, "held", None)
        if not held:
            return
        # remove the most recent occurrence (re-entrant locks release in
        # LIFO order; out-of-order releases still clean up correctly)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._lock:
            return dict(self._edges)


_WITNESS = _LockOrderWitness()


def enable_lock_order_witness(raise_on_inversion: bool = True) -> None:
    """Start recording acquisition order across every instrumented lock
    (independent of ProfilingMode). With ``raise_on_inversion`` (the
    test default) the first A->B/B->A pair raises
    :class:`LockOrderInversionError` on the acquiring thread; otherwise
    it warns once per pair and counts
    ``dl4j_lock_order_inversions_total``."""
    _WITNESS.reset()
    _WITNESS.raise_on_inversion = bool(raise_on_inversion)
    _WITNESS.enabled = True


def disable_lock_order_witness() -> None:
    _WITNESS.enabled = False


def lock_order_edges() -> Dict[Tuple[str, str], str]:
    """Snapshot of the observed (outer, inner) acquisition edges."""
    return _WITNESS.edges()


class InstrumentedLock:
    """``threading.Lock`` with wait/hold histograms, a contention
    counter, and lock-order witnessing. Context manager and
    ``acquire``/``release`` compatible; ``name`` is the metrics label
    (keep the cardinality low — name the *role*, not the instance)."""

    _raw_factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = str(name)
        self._raw = self._raw_factory()
        self._tls = threading.local()

    # -- hold bookkeeping (per-thread stack: RLocks nest) ---------------
    def _holds(self) -> list:
        st = getattr(self._tls, "holds", None)
        if st is None:
            st = self._tls.holds = []
        return st

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _active() and not _WITNESS.enabled:
            return self._raw.acquire(blocking, timeout)
        instrument = _active()
        got = self._raw.acquire(False)
        if not got:
            if not blocking:
                return False
            if instrument:
                LOCK_CONTENTION.labels(lock=self.name).inc()
                t0 = time.perf_counter()
            got = self._raw.acquire(True, timeout)
            if instrument and got:
                LOCK_WAIT.labels(lock=self.name).observe(
                    time.perf_counter() - t0)
        if got:
            if instrument:
                self._holds().append(time.perf_counter())
            else:
                self._holds().append(None)
            if _WITNESS.enabled:
                try:
                    _WITNESS.on_acquired(self.name)
                except BaseException:
                    # witness raised (inversion): the lock IS held —
                    # release it so the failure does not strand waiters
                    self._holds().pop()
                    self._raw.release()
                    raise
        return got

    def release(self) -> None:
        holds = self._holds()
        t0 = holds.pop() if holds else None
        # unconditional (cheap no-op when nothing is on the stack):
        # releasing while the witness is disabled must still pop the
        # entry an enabled-time acquire pushed, or the stale name fakes
        # inversions after the next enable
        _WITNESS.on_released(self.name)
        self._raw.release()
        if t0 is not None:
            LOCK_HOLD.labels(lock=self.name).observe(
                time.perf_counter() - t0)

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r})"


class InstrumentedRLock(InstrumentedLock):
    """Re-entrant variant. Also delegates the private
    ``_is_owned``/``_release_save``/``_acquire_restore`` protocol so a
    ``threading.Condition`` can be built on top of it (see
    :class:`InstrumentedCondition`)."""

    _raw_factory = staticmethod(threading.RLock)

    def locked(self) -> bool:
        # _thread.RLock.locked() only exists on newer CPython — emulate
        # it with an uninstrumented non-blocking probe
        if self._raw._is_owned():
            return True
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    # Condition protocol: wait() releases the lock fully and re-acquires
    # it after — close/reopen the hold window so hold-time excludes the
    # blocked wait (a wait IS a release for contention purposes).
    def _is_owned(self) -> bool:
        return self._raw._is_owned()

    def _release_save(self):
        holds = self._holds()
        t0s = list(holds)
        holds.clear()
        _WITNESS.on_released(self.name)     # unconditional, see release()
        state = self._raw._release_save()
        now = time.perf_counter()
        for t0 in t0s:
            if t0 is not None:
                LOCK_HOLD.labels(lock=self.name).observe(now - t0)
        return state, len(t0s)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._raw._acquire_restore(state)
        if _WITNESS.enabled:
            _WITNESS.on_acquired(self.name)
        now = time.perf_counter() if _active() else None
        self._holds().extend([now] * max(depth, 1))


class InstrumentedCondition(threading.Condition):
    """``threading.Condition`` over an :class:`InstrumentedRLock`: every
    ``with cond:`` / ``acquire`` / ``wait`` reports the same wait/hold/
    contention series, so a condition-guarded subsystem (the model
    server's request queue) is observable like any other lock."""

    def __init__(self, name: str, lock: Optional[InstrumentedRLock] = None):
        self.name = str(name)
        super().__init__(lock if lock is not None
                         else InstrumentedRLock(name))


class WitnessedLock:
    """Witness-only ``threading.Lock`` shim for hot or short-lived
    locks (e.g. one per :class:`~deeplearning4j_tpu.serving.server.
    ServingRequest`): participates in the lock-order witness under its
    role name but records NO wait/hold metrics and allocates no
    per-instance thread-local — construction is a raw Lock plus two
    slots, and the disabled-witness fast path is one flag read. Use
    :class:`InstrumentedLock` wherever the wait/hold series matter;
    use this where only deadlock ordering does."""

    __slots__ = ("name", "_raw")

    def __init__(self, name: str):
        self.name = str(name)
        self._raw = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._raw.acquire(blocking, timeout)
        if got and _WITNESS.enabled:
            try:
                _WITNESS.on_acquired(self.name)
            except BaseException:
                # witness raised (inversion): the lock IS held — release
                # so the failure does not strand waiters
                self._raw.release()
                raise
        return got

    def release(self) -> None:
        # unconditional pop (cheap no-op when nothing was pushed): see
        # InstrumentedLock.release for why
        _WITNESS.on_released(self.name)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> "WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self):
        return f"WitnessedLock({self.name!r})"


class InstrumentedQueue(_queue.Queue):
    """``queue.Queue`` whose internal mutex (and the three conditions
    built on it) is an :class:`InstrumentedRLock` — every put/get
    reports wait/hold/contention under the queue's role name. The
    input-pipeline hot-path queues (DevicePrefetcher,
    AsyncDataSetIterator) use this so queue contention shows up in
    ``dl4j_lock_*{lock=...}`` like any other lock; overhead with
    instrumentation OFF is one module-flag check per op
    (benchmarks/probe_lock_overhead.py pins it)."""

    def __init__(self, maxsize: int = 0, name: str = "queue"):
        super().__init__(maxsize)
        # replace the plain primitives queue.Queue.__init__ installed;
        # Condition drives the lock through the _release_save/
        # _acquire_restore/_is_owned protocol InstrumentedRLock delegates
        lock = InstrumentedRLock(name)
        self.mutex = lock
        self.not_empty = threading.Condition(lock)
        self.not_full = threading.Condition(lock)
        self.all_tasks_done = threading.Condition(lock)


# PR-8 carried follow-up: the metrics registry's get-or-create lock is a
# hot path (observe_region resolves its histogram through it every train
# step) — swap it for an instrumented lock. Safe against recursion: the
# dl4j_lock_* families above were registered BEFORE the swap, so a
# lock-metric record only takes per-family/child locks (plain
# threading.Lock), never the registry lock.
_REG._lock = InstrumentedLock("metrics_registry")
