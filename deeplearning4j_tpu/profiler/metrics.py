"""Metrics registry: counters, gauges, fixed-bucket histograms with label
support and Prometheus text-format exposition.

Reference parity: the counter registries that TensorFlow and TVM treat as
load-bearing runtime infrastructure (per-op counts, cache hit rates,
transfer volumes) — the reference framework has no equivalent; its
observability stops at the listener bus. Here every subsystem (op
dispatch, native runtime, parallel, the fit loop) reports into ONE
process-wide registry, and ``UIServer`` exposes it at ``GET /metrics`` in
Prometheus text exposition format (v0.0.4) so the dashboard, the bench
harness, and any external scraper agree on a single source of truth.

Semantics follow prometheus_client (not imported — the environment is
egress-free and the dependency is unnecessary):

- ``Counter``: monotonically increasing; ``inc(v)`` with v >= 0.
- ``Gauge``: ``set``/``inc``/``dec``.
- ``Histogram``: fixed cumulative buckets chosen at creation, plus
  ``_sum``/``_count`` series; ``observe(v)``.
- Labels: declare ``labelnames`` at creation, then ``m.labels(op="add")``
  returns (creating on first use) the child to operate on. A metric with
  labelnames cannot be operated on directly; one without them can.

All operations are thread-safe; hot-path cost is one lock + dict/float
update.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# latency-shaped default: 100us .. 10s (seconds)
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                              and abs(v) < 1e15):
        return str(int(v))
    return repr(float(v))


def _labels_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Base: a named family, optionally labelled (children per label set)."""

    typ = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}
        if not self.labelnames:
            self._init_value()

    def _init_value(self):
        raise NotImplementedError

    def _child(self) -> "_Metric":
        c = type(self)(self.name, self.help)
        return c

    def children(self) -> Dict[Tuple[str, ...], "_Metric"]:
        """Snapshot of the per-label-set children (empty for an
        unlabelled family). Lets readers — the analysis CLI's churn
        report, tests — enumerate which label sets exist without parsing
        the text exposition."""
        with self._lock:
            return dict(self._children)

    def labels(self, *values, **kv) -> "_Metric":
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    f"{self.name}: expected labels {self.labelnames}, "
                    f"got {tuple(kv)}")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}")
        with self._lock:
            c = self._children.get(values)
            if c is None:
                c = self._children[values] = self._child()
            return c

    def _check_unlabelled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                "use .labels(...) first")

    def _samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """[(suffix, extra_labels, value)] for one (child) metric — a
        consistent snapshot taken under the metric's own lock (a scrape
        racing observe() must never emit non-monotone histogram buckets).
        Histograms may append a 4th element: an ``(exemplar_id, value)``
        pair rendered as an OpenMetrics exemplar when negotiated."""
        raise NotImplementedError

    def expose(self, openmetrics: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            items = (list(self._children.items()) if self.labelnames
                     else [((), self)])
        # child _samples() acquire their own lock — called OUTSIDE the
        # family lock above (for an unlabelled family, child IS self)
        for lvals, child in items:
            for sample in child._samples():
                suffix, extra, value = sample[0], sample[1], sample[2]
                names = list(self.labelnames) + list(extra)
                vals = list(lvals) + [extra[k] for k in extra]
                line = (f"{self.name}{suffix}"
                        f"{_labels_str(names, vals)} "
                        f"{_format_value(value)}")
                if openmetrics and len(sample) > 3 and sample[3] is not None:
                    # OpenMetrics exemplar: ties this bucket back to one
                    # concrete trace (tier-1 <-> tier-2 correlation)
                    ex_id, ex_val = sample[3]
                    line += (f' # {{trace_id="{_escape_label(ex_id)}"}} '
                             f"{_format_value(ex_val)}")
                lines.append(line)
        return "\n".join(lines)


class Counter(_Metric):
    """Monotonic counter (ref: prometheus counter semantics)."""

    typ = "counter"

    def _init_value(self):
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self._check_unlabelled()
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        self._check_unlabelled()
        with self._lock:
            return self._value

    def _samples(self):
        with self._lock:
            return [("", {}, self._value)]


class Gauge(_Metric):
    """Settable instantaneous value."""

    typ = "gauge"

    def _init_value(self):
        self._value = 0.0

    def set(self, value: float) -> None:
        self._check_unlabelled()
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._check_unlabelled()
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        self._check_unlabelled()
        with self._lock:
            return self._value

    def _samples(self):
        with self._lock:
            return [("", {}, self._value)]


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (+Inf bucket implicit)."""

    typ = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        super().__init__(name, help, labelnames)

    def _init_value(self):
        self._counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        # one exemplar per bucket (the latest observation that carried
        # one) — bounded by construction: len(buckets)+1 slots, ever
        self._exemplars: List[Optional[Tuple[str, float]]] = \
            [None] * (len(self.buckets) + 1)

    def _child(self):
        return Histogram(self.name, self.help, (), self.buckets)

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        """Record one observation. ``exemplar`` (typically a trace_id)
        is retained per owning bucket — latest wins, so retention is
        bounded at one exemplar per bucket — and rendered as an
        OpenMetrics ``# {trace_id="..."}`` annotation when the scrape
        negotiates the OpenMetrics exposition."""
        self._check_unlabelled()
        value = float(value)
        idx = bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if exemplar is not None:
                self._exemplars[idx] = (str(exemplar)[:128], value)

    @property
    def count(self) -> int:
        self._check_unlabelled()
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        self._check_unlabelled()
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (0 <= q <= 1) from the cumulative
        buckets, linearly interpolated within the owning bucket —
        prometheus ``histogram_quantile`` semantics, computed locally
        so the serving stats / bench probes need no PromQL engine.
        Returns None for an empty histogram; observations landing in
        the +Inf bucket clamp to the highest finite bound."""
        self._check_unlabelled()
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return None
        rank = q * total
        cum = 0
        lo = 0.0
        for bound, c in zip(self.buckets, counts):
            if c and cum + c >= rank:
                frac = (rank - cum) / c
                return lo + (bound - lo) * max(min(frac, 1.0), 0.0)
            cum += c
            lo = bound
        return self.buckets[-1]

    def _samples(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            exemplars = list(self._exemplars)
        out = []
        cum = 0
        for i, (bound, c) in enumerate(zip(self.buckets, counts)):
            cum += c
            out.append(("_bucket", {"le": _format_value(bound)}, cum,
                        exemplars[i]))
        out.append(("_bucket", {"le": "+Inf"}, total, exemplars[-1]))
        out.append(("_sum", {}, s))
        out.append(("_count", {}, total))
        return out


class MetricsRegistry:
    """Named metric families with get-or-create semantics.

    ``registry.counter(name, ...)`` returns the existing family when the
    name is already registered (validating the type matches), so every
    call site can declare the metrics it needs without coordination —
    the same pattern as prometheus_client's default REGISTRY.
    """

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name} already registered as {m.typ}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def exposition(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format 0.0.4 (what /metrics
        serves). ``openmetrics=True`` renders the OpenMetrics dialect
        instead — histogram bucket lines carry their retained
        ``# {trace_id="..."}`` exemplars and the body ends with
        ``# EOF`` — for scrapers that negotiate it via ``Accept:
        application/openmetrics-text``."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        if not metrics:
            return "# EOF\n" if openmetrics else ""
        body = "\n".join(m.expose(openmetrics=openmetrics) for m in metrics)
        return body + ("\n# EOF\n" if openmetrics else "\n")


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """Process-wide registry singleton (what ``GET /metrics`` serves)."""
    return _REGISTRY
