"""Unified tracing + metrics subsystem.

Two halves (SURVEY.md §5 "Tracing/profiling", "Metrics/logging"; the
per-op timeline + counter-registry discipline of TensorFlow (Abadi et
al., 2016) and TVM (Chen et al., 2018)):

- :mod:`tracer` — a thread-safe span tracer: ``with trace_span("op:x")``
  (also usable as a decorator), nestable, ring-buffer retention, Chrome
  Trace Event Format export (Perfetto-loadable). Serves ``GET /trace``.
- :mod:`metrics` — named counters / gauges / fixed-bucket histograms
  with label support and Prometheus text exposition. Serves
  ``GET /metrics``.

Plus :mod:`modes` — the OpExecutioner-style :class:`ProfilingMode`
(OFF/BASIC/NAN_PANIC/INF_PANIC) that gates per-op instrumentation and
unifies the Environment numerics-panic knobs — and :mod:`locks` —
instrumented Lock/RLock/Condition wrappers (``dl4j_lock_{wait,hold}_
seconds`` + ``dl4j_lock_contention_total`` per lock name, gated on the
same ProfilingMode) with a runtime lock-order witness that raises on
A->B/B->A inversions under tests (the dynamic half of the DL4J-E203
static deadlock lint).

Instrumented seams: ``ops.registry`` dispatch, ``native.runtime``
(compile cache, H2D/D2H), ``parallel.{wrapper,data}`` (replication /
shard transfers), the ``nn.{multilayer,graph}`` fit loops (step time,
data-wait vs compute + the ``dl4j_train_overlap_ratio`` gauge /
:func:`data_overlap_ratio`, ``train:megastep`` spans +
``dl4j_steps_per_dispatch`` for multi-step dispatch), the input
pipeline (``dl4j_{async_iterator,prefetch}_queue_depth``,
``dl4j_prefetch_h2d_bytes_total``, and the staged pipeline's per-stage
``dl4j_pipeline_{stage_seconds,stall_seconds,queue_depth,
h2d_bytes_total}``), and the listener bus (``MetricsListener``,
``PerformanceListener``).

The fleet observability plane (ISSUE 16) adds four more modules:
:mod:`tracecontext` (W3C-traceparent distributed tracing — request
flows stitch across ingress, coalesced dispatch, and the coordination
wire), :mod:`aggregate` (cross-host metric federation behind
``GET /v1/fleet/metrics``), :mod:`slo` (declarative SLOs with
multi-window burn-rate gates, ``dl4j_slo_burn_rate``), and
:mod:`flightrec` (always-on crash flight recorder dumping debug
bundles on NonfiniteAttributionError / dispatch timeout / dead peer).

Everything is near-zero-cost when disabled: one module-level flag / enum
read before any span or sample is allocated.
"""

import time as _time

from deeplearning4j_tpu.profiler.aggregate import (FleetScraper,
                                                   HistogramSnapshot,
                                                   MetricsAggregator,
                                                   members_from_coordinator,
                                                   parse_exposition)
from deeplearning4j_tpu.profiler.flightrec import (FlightRecorder,
                                                   get_flight_recorder)
from deeplearning4j_tpu.profiler.locks import (InstrumentedCondition,
                                               InstrumentedLock,
                                               InstrumentedQueue,
                                               InstrumentedRLock,
                                               LockOrderInversionError,
                                               WitnessedLock,
                                               disable_lock_order_witness,
                                               enable_lock_order_witness,
                                               lock_order_edges)
from deeplearning4j_tpu.profiler.metrics import (Counter, Gauge, Histogram,
                                                 MetricsRegistry,
                                                 get_registry)
from deeplearning4j_tpu.profiler.modes import (ProfilingMode,
                                               get_profiling_mode,
                                               set_profiling_mode)
from deeplearning4j_tpu.profiler.slo import (SLOEngine, SLOGate, SLOSpec,
                                             SLOVerdict)
from deeplearning4j_tpu.profiler.tracecontext import (TraceContext,
                                                      current as
                                                      current_trace,
                                                      merge_chrome_traces,
                                                      record_span, run_span,
                                                      span,
                                                      spans_for_trace)
from deeplearning4j_tpu.profiler.tracer import (SpanTracer, disable_tracing,
                                                enable_tracing, get_tracer,
                                                now_us, trace_span,
                                                tracing_enabled)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "ProfilingMode", "get_profiling_mode", "set_profiling_mode",
    "SpanTracer", "trace_span", "get_tracer", "enable_tracing",
    "disable_tracing", "tracing_enabled", "instrumentation_active",
    "now_us", "observe_region", "timed_region", "iter_with_data_wait",
    "data_overlap_ratio",
    "TraceContext", "current_trace", "record_span", "span", "run_span",
    "merge_chrome_traces", "spans_for_trace",
    "MetricsAggregator", "HistogramSnapshot", "FleetScraper",
    "parse_exposition", "members_from_coordinator",
    "SLOSpec", "SLOEngine", "SLOGate", "SLOVerdict",
    "FlightRecorder", "get_flight_recorder",
    "InstrumentedLock", "InstrumentedRLock", "InstrumentedCondition",
    "InstrumentedQueue", "WitnessedLock", "LockOrderInversionError",
    "enable_lock_order_witness", "disable_lock_order_witness",
    "lock_order_edges",
]


def instrumentation_active() -> bool:
    """True when any framework instrumentation should record: tracing is
    on or the profiling mode is not OFF. The fit loops check this once
    per iteration so a disabled profiler costs one boolean + enum read."""
    return tracing_enabled() or get_profiling_mode() is not ProfilingMode.OFF


def observe_region(span_name: str, metric_name: str, help_text: str,
                   started_us: float, seconds: float, **args) -> None:
    """Record one already-measured region: a histogram sample in the
    registry plus (when tracing) a span on the tracer timeline. The fit
    loops use this for regions they time with a bare perf_counter so the
    un-instrumented path stays allocation-free."""
    get_registry().histogram(metric_name, help_text).observe(seconds)
    if tracing_enabled():
        get_tracer().add_event(span_name, started_us, seconds * 1e6,
                               args or None)


class timed_region:
    """Context manager: time a region and feed it to :func:`observe_region`
    (histogram sample + optional span). No-ops entirely when
    instrumentation is inactive — the shared shape of the fit loops'
    step-timing blocks."""

    __slots__ = ("span_name", "metric_name", "help_text", "args", "_t0",
                 "_t0u")

    def __init__(self, span_name: str, metric_name: str, help_text: str,
                 **args):
        self.span_name = span_name
        self.metric_name = metric_name
        self.help_text = help_text
        self.args = args
        self._t0 = None

    def __enter__(self):
        if instrumentation_active():
            self._t0u, self._t0 = now_us(), _time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is not None:
            observe_region(self.span_name, self.metric_name, self.help_text,
                           self._t0u, _time.perf_counter() - self._t0,
                           **self.args)
            self._t0 = None
        return False


_SENTINEL = object()

# data-wait-vs-compute overlap: 1.0 = the input pipeline is fully hidden
# behind dispatched compute, 0.5 = the host spends as long waiting for
# batches as dispatching them (data-starved). Updated by
# iter_with_data_wait; dl4j_train_data_wait_seconds / _step_seconds hold
# the raw halves.
_OVERLAP_RATIO = get_registry().gauge(
    "dl4j_train_overlap_ratio",
    "Compiled-dispatch time as a fraction of dispatch + data-wait time "
    "(1.0 = input pipeline fully overlapped with compute; low values = "
    "the chip is starving for data)")


def data_overlap_ratio():
    """Cumulative dispatch/(dispatch + data_wait) from the two fit-loop
    histograms — the overlap number the data-pipeline bench reports.
    None before any instrumented fit ran."""
    reg = get_registry()
    step = reg.get("dl4j_train_step_seconds")
    wait = reg.get("dl4j_train_data_wait_seconds")
    s = step.sum if step is not None else 0.0
    w = wait.sum if wait is not None else 0.0
    total = s + w
    return None if total == 0 else s / total


def iter_with_data_wait(batches):
    """Yield from ``batches`` measuring each pull as ``train:data_wait``
    (histogram + span) — the data-wait half of the data-wait-vs-compute
    split both fit loops report (``dl4j_train_overlap_ratio`` tracks the
    running ratio). The terminal pull (StopIteration) is not recorded: it
    measures exhaustion, not a batch wait."""
    it = iter(batches)
    while True:
        active = instrumentation_active()
        if active:
            t0u, t0 = now_us(), _time.perf_counter()
        ds = next(it, _SENTINEL)
        if ds is _SENTINEL:
            return
        if active:
            observe_region("train:data_wait", "dl4j_train_data_wait_seconds",
                           "Host wait for the next training batch", t0u,
                           _time.perf_counter() - t0)
            ratio = data_overlap_ratio()
            if ratio is not None:
                _OVERLAP_RATIO.set(ratio)
        yield ds
