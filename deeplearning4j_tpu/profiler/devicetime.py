"""Per-op DEVICE timing — the bridge from wall-clock to chip time.

The op histograms PR 1 added (``dl4j_op_dispatch_seconds``) measure host
dispatch: on an async backend they time the enqueue, not the chip. This
module closes that gap (the PR-1 carried follow-up) with two capture
paths and ONE attribution model:

- **trace** — wrap a run in ``jax.profiler`` trace capture and parse the
  XLA ``*.xplane.pb`` device planes directly (a ~100-line protobuf
  wire-format reader; no tensorboard/tensorflow dependency). Fused-op
  events map back to config layers through the ``dl4j_L<i>_<name>``
  ``jax.named_scope`` both network forwards now emit — XLA carries the
  scope in the op metadata, so a fusion that swallowed three layers is
  attributed to the first layer whose scope it names.
- **sync** — the everywhere fallback (CPU tests, backends whose profiler
  exports nothing): re-dispatch each layer's ``apply`` as its own jitted
  program with a hard ``block_until_ready`` fence around it, min-of-reps.
  Each per-layer dispatch is synced, so the measured seconds are device
  seconds (plus one dispatch overhead, which min-of-reps keeps honest);
  what it cannot see is cross-layer fusion — it measures each layer *as
  if dispatched alone*, which is exactly the per-layer cost model the
  MFU attribution needs.

Attribution: per-layer forward FLOPs come from the SAME jax-free
declared-shape model the analyzer's W105 stage-balance lint uses
(``analysis.distribution._approx_flops`` over the config's propagated
InputTypes), times batch, times the bench's train factor (backward = 2x
forward, so train = 3x). ``DeviceTimeTable`` rows carry (layer, op,
seconds, flops, mfu, share); ``top_offenders`` names the layers burning
the most device time at the worst MFU — the list ``bench.py`` prints so
a bench run names the bottleneck instead of one aggregate number.

Metrics: :meth:`DeviceTimeTable.export_metrics` publishes
``dl4j_op_device_seconds{model,layer,op}``. Export is gated on
:func:`profiler.instrumentation_active` — OFF-mode records nothing
(pinned), and plain fits never touch this module at all (the bridge is
pull-based: only an explicit ``measure()`` call dispatches anything).
"""

from __future__ import annotations

import glob
import os
import re
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu import profiler as _prof

#: scope-name prefix both network forwards emit per layer; the trace
#: path greps XLA op metadata for it
SCOPE_PREFIX = "dl4j_L"
_SCOPE_RE = re.compile(r"dl4j_L(\d+)_([A-Za-z0-9_.\-]+)")

#: public v5e per-chip peak (BASELINE.md) — callers override for other parts
DEFAULT_PEAK_FLOPS = 197e12


def scope_name(index: int, name: str) -> str:
    """The per-layer named_scope string: ``dl4j_L<i>_<sanitized-name>``."""
    safe = re.sub(r"[^A-Za-z0-9_.\-]", "-", str(name))
    return f"{SCOPE_PREFIX}{index}_{safe}"


# ------------------------------------------------------------ FLOP model
def op_kind(layer) -> str:
    """Coarse op family for the metric label / table row."""
    cls = type(layer).__name__
    kinds = (("Separable", "conv2d"), ("Depthwise", "conv2d"),
             ("Deconvolution", "conv2d"), ("Convolution3D", "conv3d"),
             ("Convolution1D", "conv1d"), ("Convolution", "conv2d"),
             ("Subsampling", "pool"), ("GlobalPooling", "pool"),
             ("BatchNormalization", "batch_norm"),
             ("LocalResponseNormalization", "lrn"),
             ("LayerNorm", "layer_norm"), ("GroupNorm", "group_norm"),
             ("Embedding", "gather"), ("LSTM", "rnn"), ("GRU", "rnn"),
             ("Rnn", "rnn"), ("Attention", "attention"),
             ("Activation", "activation"), ("Dropout", "dropout"),
             ("Output", "loss_head"), ("Loss", "loss_head"),
             ("Yolo2", "loss_head"), ("Dense", "matmul"))
    for frag, kind in kinds:
        if frag in cls:
            return kind
    return cls.lower()


def layer_flop_model(conf) -> List[Tuple[str, str, int]]:
    """Per-example forward FLOPs per layer from declared config shapes —
    the analyzer's W105 model (jax-free) applied to a sequential config
    OR a graph config. Returns ``[(layer_name, op_kind, flops), ...]``
    in forward order; layers whose InputType propagation failed report
    0 FLOPs rather than raising (attribution degrades, never breaks)."""
    from deeplearning4j_tpu.analysis.distribution import _approx_flops
    rows: List[Tuple[str, str, int]] = []
    if hasattr(conf, "graph_inputs"):            # ComputationGraph config
        types = getattr(conf, "types", {}) or {}
        for node in conf.topo:
            if node.kind != "layer":
                continue
            it = types.get(node.inputs[0]) if node.inputs else None
            out = types.get(node.name)
            try:
                f = _approx_flops(node.obj, it, out)
            except Exception:
                f = 0
            rows.append((node.name, op_kind(node.obj), int(f)))
        return rows
    in_types = list(getattr(conf, "layer_input_types", []) or [])
    for i, layer in enumerate(conf.layers):
        it = in_types[i] if i < len(in_types) else None
        out = None
        try:
            out = layer.output_type(it) if it is not None else None
        except Exception:
            out = None
        try:
            f = _approx_flops(layer, it, out)
        except Exception:
            f = 0
        name = getattr(layer, "name", None) or type(layer).__name__
        if name == type(layer).__name__:
            name = f"{name.lower()}_{i}"
        rows.append((name, op_kind(layer), int(f)))
    return rows


# --------------------------------------------------------------- results
class LayerTime:
    """One attribution row: device seconds + FLOP-model MFU for a layer."""

    __slots__ = ("layer", "op", "seconds", "flops", "mfu", "share")

    def __init__(self, layer: str, op: str, seconds: float, flops: float,
                 mfu: Optional[float], share: float):
        self.layer = layer
        self.op = op
        self.seconds = seconds
        self.flops = flops
        self.mfu = mfu
        self.share = share

    def as_dict(self) -> dict:
        return {"layer": self.layer, "op": self.op,
                "device_ms": round(self.seconds * 1e3, 4),
                "gflops": round(self.flops / 1e9, 3),
                "mfu": None if self.mfu is None else round(self.mfu, 4),
                "time_share": round(self.share, 4)}

    def __repr__(self):
        return (f"LayerTime({self.layer}, {self.op}, "
                f"{self.seconds * 1e3:.3f}ms, mfu={self.mfu})")


class DeviceTimeTable:
    """Per-layer device-time MFU attribution for one model + batch."""

    def __init__(self, rows: List[LayerTime], source: str,
                 batch: int, peak_flops: float, train_factor: float):
        self.rows = rows
        self.source = source          # "trace" | "sync"
        self.batch = batch
        self.peak_flops = peak_flops
        self.train_factor = train_factor

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.rows)

    def top_offenders(self, n: int = 3) -> List[dict]:
        """The layers burning the most device time, worst first — what a
        bench run should name instead of one aggregate MFU number."""
        ranked = sorted(self.rows, key=lambda r: -r.seconds)
        return [r.as_dict() for r in ranked[:n]]

    def as_rows(self, n: Optional[int] = None) -> List[dict]:
        ranked = sorted(self.rows, key=lambda r: -r.seconds)
        if n is not None:
            ranked = ranked[:n]
        return [r.as_dict() for r in ranked]

    def export_metrics(self, model_name: str) -> bool:
        """Publish ``dl4j_op_device_seconds{model,layer,op}``. Gated on
        the profiling mode: OFF records nothing (the bridge is an
        explicit measurement tool, not ambient overhead)."""
        if not _prof.instrumentation_active():
            return False
        c = _prof.get_registry().counter(
            "dl4j_op_device_seconds",
            "Per-layer DEVICE seconds attributed by the devicetime "
            "bridge (trace-parsed XLA events, or sync-timed per-layer "
            "dispatch on backends without a trace)",
            labelnames=("model", "layer", "op"))
        for r in self.rows:
            c.labels(model=model_name, layer=r.layer, op=r.op).inc(r.seconds)
        return True


# -------------------------------------------------- xplane wire parser
# Minimal protobuf wire-format reader for the XSpace/XPlane schema
# (tsl/profiler/protobuf/xplane.proto) — enough to pull (plane name,
# line name, event name/display/duration) out of a jax.profiler capture
# without importing tensorflow. Unknown fields are skipped by wire type,
# so schema drift degrades to missing data, never a crash.

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes;
    value is an int for varint/fixed types and a bytes slice for
    length-delimited fields."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:                      # varint
            val, i = _read_varint(buf, i)
        elif wt == 2:                    # length-delimited
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # 32-bit
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wt == 1:                    # 64-bit
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:                            # groups: unsupported, stop
            return
        yield fno, wt, val


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    """XEventMetadata: id=1, name=2, metadata=3, display_name=4."""
    mid, name, display = 0, "", ""
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 0:
            mid = val
        elif fno == 2 and wt == 2:
            name = val.decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            display = val.decode("utf-8", "replace")
    return mid, (f"{name} {display}".strip() if display else name)


def _parse_event(buf: bytes) -> Tuple[int, int]:
    """XEvent: metadata_id=1, offset_ps=2, duration_ps=3."""
    mid = dur = 0
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 0:
            mid = val
        elif fno == 3 and wt == 0:
            dur = val
    return mid, dur


def _parse_line(buf: bytes) -> Tuple[str, List[Tuple[int, int]]]:
    """XLine: name=2, events=4."""
    name, events = "", []
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            name = val.decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            events.append(_parse_event(val))
    return name, events


def _parse_plane(buf: bytes) -> dict:
    """XPlane: name=2, lines=3, event_metadata=4 (map<int64, meta>)."""
    plane = {"name": "", "lines": [], "event_names": {}}
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            plane["name"] = val.decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            plane["lines"].append(_parse_line(val))
        elif fno == 4 and wt == 2:
            key, meta_name = 0, ""
            for kfno, kwt, kval in _fields(val):   # map entry {key=1, value=2}
                if kfno == 1 and kwt == 0:
                    key = kval
                elif kfno == 2 and kwt == 2:
                    mid, meta_name = _parse_event_metadata(kval)
                    key = mid or key
            plane["event_names"][key] = meta_name
    return plane


def parse_xspace(data) -> List[dict]:
    """Parse an XSpace (path or bytes) into
    ``[{name, lines: [(line_name, [(metadata_id, duration_ps)])],
    event_names: {id: name}}]``."""
    if isinstance(data, (str, os.PathLike)):
        with open(data, "rb") as f:
            data = f.read()
    planes = []
    for fno, wt, val in _fields(data):
        if fno == 1 and wt == 2:         # XSpace.planes
            planes.append(_parse_plane(val))
    return planes


def _is_device_plane(name: str) -> bool:
    n = name.lower()
    return ("/device:tpu" in n or "gpu:" in n.replace("/device:", "")
            or n.startswith("/device:gpu"))


def scope_seconds_from_xspace(planes: List[dict]) -> Dict[int, float]:
    """Aggregate device-plane event durations per ``dl4j_L<i>`` scope:
    {layer_index: seconds}. An event naming several scopes (a fusion
    that swallowed multiple layers) is attributed to the FIRST scope it
    names — deterministic, and the fused block's cost lands on the layer
    the fusion is rooted at."""
    out: Dict[int, float] = {}
    for plane in planes:
        if not _is_device_plane(plane["name"]):
            continue
        names = plane["event_names"]
        for _line_name, events in plane["lines"]:
            for mid, dur_ps in events:
                m = _SCOPE_RE.search(names.get(mid, ""))
                if m is None:
                    continue
                idx = int(m.group(1))
                out[idx] = out.get(idx, 0.0) + dur_ps * 1e-12
    return out


def _trace_layer_seconds(run_fn, trace_dir: Optional[str] = None
                         ) -> Optional[Dict[int, float]]:
    """Capture ``run_fn()`` under ``jax.profiler`` and return per-layer
    device seconds, or None when the backend exported no parsable device
    plane (callers fall back to sync timing)."""
    import jax
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="dl4j_devicetime_")
    try:
        jax.profiler.start_trace(d)
        try:
            run_fn()
        finally:
            jax.profiler.stop_trace()
        seconds: Dict[int, float] = {}
        for path in glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                              recursive=True):
            try:
                per = scope_seconds_from_xspace(parse_xspace(path))
            except Exception:
                continue
            for k, v in per.items():
                seconds[k] = seconds.get(k, 0.0) + v
        return seconds or None
    except Exception:
        return None
    finally:
        if own:
            import shutil
            shutil.rmtree(d, ignore_errors=True)


# ------------------------------------------------------- sync fallback
def _walk_layers(model, x):
    """Yield ``(index, name, layer, input_array, extra)`` in forward
    order with eagerly materialized inputs — shared by the sync timer.
    Handles both network classes; preprocessors/vertices run untimed
    between layers. Inputs are presented in the layout the layer is
    configured to compute in (the NHWC seam's ``data_format`` stamp)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import layers as L

    cdt = model._compute_dtype()
    nhwc = getattr(model, "_compute_layout", "NCHW") == "NHWC"
    key = jax.random.PRNGKey(0)

    def run(layer, p, s, a, sub):
        if cdt is not None:
            p, a = L.policy_cast(layer, p, a, cdt)
        return layer.apply(p, s, a, False, sub)[0]

    if hasattr(model.conf, "graph_inputs"):      # ComputationGraph
        env = {model.conf.graph_inputs[0]: jnp.asarray(x)} \
            if not isinstance(x, dict) else {k: jnp.asarray(v)
                                             for k, v in x.items()}
        fmt = {k: False for k in env}
        for i, node in enumerate(model.conf.topo):
            if node.kind != "layer":
                xs = [L.to_nchw(env[n]) if fmt[n] else env[n]
                      for n in node.inputs]
                env[node.name] = node.obj.apply(*xs)
                fmt[node.name] = False
                continue
            a = env[node.inputs[0]]
            cur_nhwc = fmt[node.inputs[0]]
            if node.name in model.conf.preprocessors:
                if cur_nhwc:
                    a, cur_nhwc = L.to_nchw(a), False
                a = model.conf.preprocessors[node.name](a)
            a, cur_nhwc = L.layout_step(node.obj, a, cur_nhwc, nhwc)
            key, sub = jax.random.split(key)
            yield i, node.name, node.obj, a, sub
            out = run(node.obj, model._params[node.name],
                      model._states[node.name], a, sub)
            env[node.name] = out
            fmt[node.name] = cur_nhwc and getattr(out, "ndim", 0) == 4
        return

    cur = jnp.asarray(x)
    cur_nhwc = False
    for i, layer in enumerate(model.layers):
        if i in model.conf.preprocessors:
            if cur_nhwc:
                cur, cur_nhwc = L.to_nchw(cur), False
            cur = model.conf.preprocessors[i](cur)
        cur, cur_nhwc = L.layout_step(layer, cur, cur_nhwc, nhwc)
        name = getattr(layer, "name", None) or type(layer).__name__
        if name == type(layer).__name__:
            name = f"{name.lower()}_{i}"
        key, sub = jax.random.split(key)
        yield i, name, layer, cur, sub
        cur = run(layer, model._params[i], model._states[i], cur, sub)
        cur_nhwc = cur_nhwc and getattr(cur, "ndim", 0) == 4


def _sync_layer_seconds(model, x, reps: int = 3) -> Dict[int, float]:
    """Per-layer forward device seconds by dispatching each layer's apply
    as its own jitted program with a block_until_ready fence, min of
    ``reps`` (first call compiles, then timed reps)."""
    import jax
    from deeplearning4j_tpu.nn import layers as L

    cdt = model._compute_dtype()
    out: Dict[int, float] = {}
    for i, _name, layer, a, sub in _walk_layers(model, x):
        p = model._params[i] if isinstance(model._params, list) \
            else model._params[_name]
        s = model._states[i] if isinstance(model._states, list) \
            else model._states[_name]

        def fn(p, s, a, sub, _layer=layer):
            if cdt is not None:
                p, a = L.policy_cast(_layer, p, a, cdt)
            r = _layer.apply(p, s, a, False, sub)
            return r[0]
        jf = jax.jit(fn)
        jax.block_until_ready(jf(p, s, a, sub))      # compile
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            jax.block_until_ready(jf(p, s, a, sub))
            best = min(best, time.perf_counter() - t0)
        out[i] = best
    return out


# --------------------------------------------------------------- measure
def measure(model, features, *, reps: int = 3, mode: str = "auto",
            peak_flops: float = DEFAULT_PEAK_FLOPS,
            train_factor: float = 3.0,
            trace_run=None) -> DeviceTimeTable:
    """Measure per-layer device time for one forward batch and attribute
    MFU per layer against the analyzer's FLOP model.

    ``mode``: ``"trace"`` parses a ``jax.profiler`` capture of
    ``trace_run()`` (default: the model's jitted forward on
    ``features``), ``"sync"`` times each layer's own dispatch, and
    ``"auto"`` tries trace on TPU backends and falls back to sync —
    so the same call works on the CPU test backend.

    ``train_factor`` converts forward seconds/FLOPs into the training
    MFU convention the bench uses (backward = 2x forward → 3.0); pass
    1.0 for inference attribution."""
    import jax
    import jax.numpy as jnp

    x = features if isinstance(features, dict) else jnp.asarray(features)
    batch = (next(iter(x.values())) if isinstance(x, dict) else x).shape[0]
    flops_rows = layer_flop_model(model.conf)

    per_layer: Optional[Dict[int, float]] = None
    source = "sync"
    if mode in ("trace", "auto") and (mode == "trace"
                                      or jax.default_backend() == "tpu"):
        # graph forwards take a name->array dict; coerce a bare array
        xin = model._as_input_dict(x) \
            if not isinstance(x, dict) and hasattr(model, "_as_input_dict") \
            else x
        n_runs = max(1, reps)

        def default_run():
            for _ in range(n_runs):
                jax.block_until_ready(
                    model._jit_forward()(model._params, model._states,
                                         xin, jax.random.PRNGKey(0)))
        per_layer = _trace_layer_seconds(trace_run or default_run)
        if per_layer is not None:
            source = "trace"
            if trace_run is None:
                # only default_run repeats n_runs times; a caller-supplied
                # trace_run owns its own iteration count
                per_layer = {k: v / n_runs for k, v in per_layer.items()}
        elif mode == "trace":
            raise RuntimeError(
                "trace capture produced no parsable device plane on this "
                "backend — use mode='sync' (or 'auto')")
    if per_layer is None:
        per_layer = _sync_layer_seconds(model, x, reps=reps)

    # layer index -> (name, op, flops): sequential configs index by
    # position; graphs index by topo position of layer nodes
    if hasattr(model.conf, "graph_inputs"):
        keyed = {}
        li = 0
        for i, node in enumerate(model.conf.topo):
            if node.kind == "layer":
                keyed[i] = flops_rows[li]
                keyed[node.name] = flops_rows[li]
                li += 1
    else:
        keyed = dict(enumerate(flops_rows))

    total = sum(per_layer.values()) or 1.0
    rows = []
    for idx, secs in sorted(per_layer.items()):
        name, op, fl = keyed.get(idx, (f"layer_{idx}", "unknown", 0))
        fl_total = float(fl) * batch * train_factor
        # per-layer MFU: this layer's forward FLOPs over its own forward
        # device seconds (the train-convention 3x cancels out of the
        # ratio, so forward-only measurement attributes train MFU)
        mfu = (float(fl) * batch) / (secs * peak_flops) \
            if secs > 0 and fl else None
        rows.append(LayerTime(str(name), op, secs, fl_total,
                              None if mfu is None else min(mfu, 1.0),
                              secs / total))
    return DeviceTimeTable(rows, source, batch, peak_flops, train_factor)


def attribution_detail(model, features, *, model_name: str,
                       peak_flops: float = DEFAULT_PEAK_FLOPS,
                       reps: int = 3, top: int = 8,
                       mode: str = "auto") -> dict:
    """The bench-row payload: per-layer table (top-N by device time) +
    top_offenders + capture source. Also exports the
    ``dl4j_op_device_seconds`` series when instrumentation is active."""
    table = measure(model, features, reps=reps, mode=mode,
                    peak_flops=peak_flops)
    table.export_metrics(model_name)
    return {"source": table.source,
            "device_ms_total": round(table.total_seconds * 1e3, 3),
            "per_layer": table.as_rows(top),
            "top_offenders": table.top_offenders(3)}
