"""GSPMD-native sharded training engine (ISSUE 15).

Three tiers over the same :class:`~deeplearning4j_tpu.parallel.mesh.
DeviceMesh`:

- **Tier 1 — GSPMD fit path** (:mod:`.gspmd`): a
  :class:`ShardedTrainingPlan` maps a mesh + per-parameter
  :class:`~deeplearning4j_tpu.parallel.mesh.ShardingRule`\\ s to
  ``NamedSharding`` annotations on params, updater state, and the batch,
  and runs the networks' existing compiled step/megastep under ONE
  ``jax.jit`` with those shardings — data, model, and (where declared)
  pipeline axes become one code path instead of the
  ``ParallelWrapper`` replicate-and-psum loop.
- **Tier 2 — ZeRO-style sharded updater state** (:mod:`.zero`): a
  :class:`ZeroPlan` partitions the first/second-moment updater tensors
  across the data axis (the cross-replica weight-update sharding paper),
  cutting per-device optimizer HBM ~``n_data``x, with an
  all-gather-on-demand seam for checkpointing and a measured
  ``dl4j_updater_hbm_bytes{device}`` gauge.
- **Tier 3 — real multi-host coordination** (:mod:`.coordinator`): a
  socket- and file-backed :class:`~deeplearning4j_tpu.parallel.elastic.
  CoordinationService` implementing the PR-6 resume-barrier protocol
  across OS processes (min-step agreement, reusable, timeout, heartbeats
  with dead-peer detection).
"""

from deeplearning4j_tpu.distributed.gspmd import (GSPMDTrainer,
                                                  ShardedTrainingPlan,
                                                  hlo_collective_bytes)
from deeplearning4j_tpu.distributed.zero import (ZeroPlan,
                                                 gather_opt_state,
                                                 updater_hbm_bytes)
from deeplearning4j_tpu.distributed.coordinator import (DeadPeerError,
                                                        FileCoordinator,
                                                        SocketCoordinator,
                                                        SocketCoordinatorServer)

__all__ = [
    "ShardedTrainingPlan", "GSPMDTrainer", "hlo_collective_bytes",
    "ZeroPlan", "gather_opt_state", "updater_hbm_bytes",
    "SocketCoordinator", "SocketCoordinatorServer", "FileCoordinator",
    "DeadPeerError",
]
