"""Real multi-host coordination (tier 3): socket/file barrier service.

PR 6's elastic resume barrier shipped with an in-process stand-in
(:class:`~deeplearning4j_tpu.parallel.elastic.InProcessCoordinator`)
behind a two-method contract. This module makes the contract real
across OS processes and hosts, so ``fit_elastic`` (and any other
consumer of :class:`~deeplearning4j_tpu.parallel.elastic.
CoordinationService`) coordinates genuinely multi-host jobs:

- :class:`SocketCoordinatorServer` — a tiny TCP rendezvous (one
  JSON-line request/response per connection, no long-lived framing to
  get wrong) run by any one process (typically rank 0 or a sidecar).
  It implements the SAME barrier protocol the in-process coordinator
  pins: every participant reports its last locally completed step, the
  agreed step is the MINIMUM, barriers are reusable (generation
  counter), and a participant that stops heartbeating while a round is
  pending fails the round for everyone with a structured
  :class:`DeadPeerError` instead of letting the survivors block until
  their own timeouts.
- :class:`SocketCoordinator` — the client-side
  ``CoordinationService``: background heartbeat thread + one blocking
  barrier request. Plugs straight into ``ElasticConfig(coordinator=)``.
- :class:`FileCoordinator` — the shared-filesystem fallback for
  clusters where an extra port is harder than an NFS mount: barrier
  arrival files + heartbeat mtimes under one directory, same
  agreement/dead-peer semantics.

Wire protocol (one JSON object per line, UTF-8, one request per
connection)::

    -> {"op": "hello",     "participant": "p0"}
    <- {"ok": true, "generation": 0}
    -> {"op": "heartbeat", "participant": "p0"}
    <- {"ok": true}
    -> {"op": "barrier",   "participant": "p0", "step": 12, "timeout": 30}
    <- {"ok": true, "step": 7, "generation": 0}            # agreed min
    <- {"ok": false, "error": "dead_peer", "peer": "p1"}   # peer died
    <- {"ok": false, "error": "timeout", "arrived": 1, "expected": 2}

Metrics: ``dl4j_coord_barrier_seconds`` (barrier wall time, labelled by
implementation), ``dl4j_coord_dead_peers_total``.

Fault injection: the server accepts a
:class:`~deeplearning4j_tpu.faults.FaultPlan` whose
``coord_peer_death`` kind freezes a planned participant's heartbeats
from a planned barrier generation on — every dead-peer path is a
seeded deterministic chaos test, like the rest of the resilience
stack.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, Tuple

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.parallel.elastic import CoordinationService
from deeplearning4j_tpu.profiler import flightrec as _flightrec
from deeplearning4j_tpu.profiler import tracecontext as _tracectx

BARRIER_SECONDS = _prof.get_registry().histogram(
    "dl4j_coord_barrier_seconds",
    "Resume-barrier wall time per participant (arrival to agreement)",
    labelnames=("impl",))
DEAD_PEERS = _prof.get_registry().counter(
    "dl4j_coord_dead_peers_total",
    "Barrier rounds failed because a participant stopped heartbeating")


class DeadPeerError(RuntimeError):
    """A barrier round failed because a participant stopped
    heartbeating. ``peer`` is the dead participant, ``generation`` the
    failed barrier round — the structured error the elastic layer (or
    an operator) acts on, instead of N independent timeouts."""

    def __init__(self, peer: str, generation: int):
        self.peer = str(peer)
        self.generation = int(generation)
        super().__init__(
            f"coordination barrier generation {generation} failed: "
            f"participant {peer!r} stopped heartbeating (dead peer)")


class BarrierProtocolError(RuntimeError):
    """Malformed/unexpected coordinator reply (wire-level failure)."""


# --------------------------------------------------------------- server
class SocketCoordinatorServer:
    """TCP rendezvous for ``participants`` processes (see module doc).

    ``heartbeat_timeout``: a participant that has contacted the server
    at least once and then goes silent longer than this while a barrier
    round is pending is declared dead — the round fails for every
    waiter with a structured ``dead_peer`` reply. ``plan`` injects the
    ``coord_peer_death`` fault kind deterministically.
    """

    def __init__(self, participants: int, host: str = "127.0.0.1",
                 port: int = 0, heartbeat_timeout: float = 5.0, plan=None):
        self.participants = int(participants)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.plan = plan
        self._cond = _prof.InstrumentedCondition("coord:server")
        self._generation = 0
        self._round: Dict[str, int] = {}
        self._results: Dict[int, int] = {}
        self._failures: Dict[int, Dict] = {}
        self._last_seen: Dict[str, float] = {}
        self._meta: Dict[str, Dict] = {}    # hello-advertised, per peer
        self._closed = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dl4j-coord-accept")
        self._accept_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True, name="dl4j-coord-monitor")
        self._monitor_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _is_closed(self) -> bool:
        with self._cond:
            return self._closed

    # ------------------------------------------------------------- wire
    def _accept_loop(self):
        while not self._is_closed():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return          # socket closed
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                f = conn.makefile("rwb")
                line = f.readline()
                if not line:
                    return
                try:
                    msg = json.loads(line.decode("utf-8"))
                except json.JSONDecodeError:
                    self._reply(f, {"ok": False, "error": "bad_request"})
                    return
                op = msg.get("op")
                participant = str(msg.get("participant", ""))
                if op == "hello":
                    self._touch(participant)
                    meta = msg.get("meta")
                    with self._cond:
                        gen = self._generation
                        if isinstance(meta, dict):
                            # hello-advertised metadata (e.g. a
                            # metrics_url) — what FleetScraper reads
                            # off members() to build scrape targets
                            self._meta[participant] = dict(meta)
                    self._reply(f, {"ok": True, "generation": gen})
                elif op == "heartbeat":
                    self._touch(participant)
                    self._reply(f, {"ok": True})
                elif op == "barrier":
                    self._reply(f, self._barrier(
                        participant, int(msg.get("step", 0)),
                        float(msg.get("timeout", 60.0)),
                        trace=msg.get("trace")))
                else:
                    self._reply(f, {"ok": False, "error": "bad_op",
                                    "op": op})
        except (OSError, ValueError):
            pass                # client went away mid-reply

    @staticmethod
    def _reply(f, payload: Dict):
        f.write((json.dumps(payload) + "\n").encode("utf-8"))
        f.flush()

    def _touch(self, participant: str):
        if not participant:
            return
        with self._cond:
            if participant not in self._last_seen:
                # first contact always registers (the dead-peer detector
                # can only suspect peers it has seen); a planned-dead
                # peer's REFRESHES are what stop counting
                self._last_seen[participant] = time.monotonic()
            elif not self._peer_planned_dead(participant):
                self._last_seen[participant] = time.monotonic()

    def _prune(self, gen: int, keep: int = 8):
        """Drop result/failure entries no waiter can read anymore — a
        long-lived coordinator sidecar must not leak one entry per
        barrier generation. ``keep`` generations of history cover any
        waiter still draining out of an old round. Caller holds the
        lock."""
        for stale in [g for g in self._results if g <= gen - keep]:
            del self._results[stale]
        for stale in [g for g in self._failures if g <= gen - keep]:
            del self._failures[stale]

    def _peer_planned_dead(self, participant: str) -> bool:
        """The coord_peer_death fault seam: a planned-dead peer's
        heartbeats stop counting from its planned generation on."""
        plan = self.plan
        if plan is None:
            return False
        dead = getattr(plan, "coord_peer_dead", None)
        return bool(dead and dead(participant, self._generation))

    def members(self, fresh_within: float = None) -> Dict[str, Dict]:
        """Membership snapshot: participant -> {"age": seconds since
        last contact, "meta": hello-advertised dict}. ``fresh_within``
        filters to peers heard from that recently (default: the
        heartbeat timeout) — dead hosts fall out of the view, and so
        out of any scrape-target list built from it."""
        bound = (self.heartbeat_timeout if fresh_within is None
                 else float(fresh_within))
        now = time.monotonic()
        with self._cond:
            return {p: {"age": now - seen,
                        "meta": dict(self._meta.get(p, {}))}
                    for p, seen in self._last_seen.items()
                    if now - seen <= bound}

    # ---------------------------------------------------------- barrier
    def _barrier(self, participant: str, step: int, timeout: float,
                 trace=None) -> Dict:
        """One participant's barrier arrival. ``trace`` is the client's
        traceparent riding the wire: the server-side round span becomes
        its child, so a multi-process barrier stitches into one trace."""
        ctx = _tracectx.TraceContext.from_traceparent(trace)
        t0_us = _prof.now_us()
        reply = self._barrier_inner(participant, step, timeout)
        _tracectx.record_span(
            "coord:round", ctx.child() if ctx is not None else None,
            t0_us, _prof.now_us() - t0_us,
            args={"participant": participant, "step": int(step),
                  "ok": bool(reply.get("ok")),
                  "generation": reply.get("generation")})
        return reply

    def _barrier_inner(self, participant: str, step: int,
                       timeout: float) -> Dict:
        t0 = time.perf_counter()
        with self._cond:
            if not self._peer_planned_dead(participant):
                self._last_seen[participant] = time.monotonic()
            gen = self._generation
            self._round[participant] = int(step)
            if len(self._round) >= self.participants:
                self._results[gen] = min(self._round.values())
                self._round = {}
                self._generation += 1
                self._prune(gen)
                self._cond.notify_all()
            else:
                deadline = time.monotonic() + timeout
                while (gen not in self._results
                       and gen not in self._failures):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        arrived = len(self._round)
                        self._round.pop(participant, None)
                        return {"ok": False, "error": "timeout",
                                "arrived": arrived,
                                "expected": self.participants,
                                "generation": gen}
                    self._cond.wait(min(remaining, 0.25))
            if gen in self._failures:
                return dict(self._failures[gen], ok=False)
            BARRIER_SECONDS.labels(impl="socket").observe(
                time.perf_counter() - t0)
            return {"ok": True, "step": self._results[gen],
                    "generation": gen}

    def _monitor_loop(self):
        """Dead-peer detection: while a round is pending, any participant
        the server has EVER seen whose heartbeat is stale fails the
        round for all waiters."""
        while not self._is_closed():
            time.sleep(min(self.heartbeat_timeout / 4.0, 0.25))
            died = None
            with self._cond:
                if not self._round:
                    continue
                gen = self._generation
                now = time.monotonic()
                for peer, seen in list(self._last_seen.items()):
                    if peer in self._round:
                        continue        # already arrived: not a suspect
                    stale = now - seen > self.heartbeat_timeout
                    if stale or self._peer_planned_dead(peer):
                        self._failures[gen] = {"error": "dead_peer",
                                               "peer": peer,
                                               "generation": gen}
                        self._round = {}
                        self._generation += 1
                        self._prune(gen)
                        DEAD_PEERS.inc()
                        self._cond.notify_all()
                        died = (peer, gen, now - seen)
                        break
            if died is not None:
                # outside the lock: the dump walks the metrics registry
                # and writes files — never under the barrier condvar
                rec = _flightrec.get_flight_recorder()
                rec.record("coord:dead_peer", peer=died[0],
                           generation=died[1], stale_seconds=died[2])
                rec.dump("dead_peer")

    def close(self):
        with self._cond:
            self._closed = True
            # fail any still-pending round so waiters unblock
            if self._round:
                self._failures[self._generation] = {
                    "error": "server_closed",
                    "generation": self._generation}
                self._round = {}
                self._generation += 1
            self._cond.notify_all()
        try:
            self._sock.close()      # unblocks the accept loop
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        self._monitor_thread.join(timeout=2.0)

    def __enter__(self) -> "SocketCoordinatorServer":
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------------------- client
def _parse_address(address) -> Tuple[str, int]:
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    host, _, port = str(address).rpartition(":")
    return host or "127.0.0.1", int(port)


class SocketCoordinator(CoordinationService):
    """Client-side ``CoordinationService`` over the socket protocol.

    ``participant`` is this process's identity; a background thread
    heartbeats every ``heartbeat_interval`` seconds so the server's
    dead-peer detector can tell a slow participant from a dead one.
    Plugs into ``ElasticConfig(coordinator=...)`` unchanged — the
    resume-barrier contract is the in-process coordinator's.
    """

    def __init__(self, address, participant: str = None,
                 heartbeat_interval: float = 1.0, connect_timeout: float = 5.0):
        self.host, self.port = _parse_address(address)
        # hostname + pid: bare pids collide routinely ACROSS hosts, and
        # colliding participant names silently merge two workers into
        # one barrier slot
        self.participant = participant if participant is not None \
            else f"{socket.gethostname()}-{os.getpid()}"
        self.heartbeat_interval = float(heartbeat_interval)
        self.connect_timeout = float(connect_timeout)
        self._closed = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"dl4j-coord-hb-{self.participant}")
        self._hb_thread.start()

    # ------------------------------------------------------------- wire
    def _request(self, payload: Dict, timeout: float) -> Dict:
        with socket.create_connection((self.host, self.port),
                                      timeout=self.connect_timeout) as conn:
            conn.settimeout(timeout)
            f = conn.makefile("rwb")
            f.write((json.dumps(payload) + "\n").encode("utf-8"))
            f.flush()
            line = f.readline()
        if not line:
            raise BarrierProtocolError(
                f"coordinator {self.host}:{self.port} closed the "
                "connection without replying")
        try:
            return json.loads(line.decode("utf-8"))
        except json.JSONDecodeError as e:
            raise BarrierProtocolError(
                f"unparseable coordinator reply: {line[:200]!r}") from e

    def _heartbeat_loop(self):
        while not self._closed.wait(self.heartbeat_interval):
            try:
                self._request({"op": "heartbeat",
                               "participant": self.participant},
                              timeout=self.connect_timeout)
            except OSError:
                continue        # transient: the next beat retries
            except BarrierProtocolError:
                continue

    def hello(self, timeout: float = 5.0, meta: Dict = None) -> int:
        """Register with the server (so dead-peer detection covers this
        participant even before its first barrier); returns the
        server's current barrier generation. ``meta`` advertises
        participant metadata — e.g. ``{"metrics_url": "http://..."}``
        — that the server exposes through ``members()`` (what
        ``FleetScraper`` builds scrape targets from)."""
        payload = {"op": "hello", "participant": self.participant}
        if meta:
            payload["meta"] = dict(meta)
        reply = self._request(payload, timeout)
        return int(reply.get("generation", 0))

    # ---------------------------------------------------------- contract
    def resume_barrier(self, participant: str, step: int,
                       timeout: float = 60.0) -> int:
        t0 = time.perf_counter()
        t0_us = _prof.now_us()
        name = str(participant or self.participant)
        # the barrier rides the ambient trace when one is in scope
        # (e.g. a fit_elastic run span) so the server's coord:round span
        # stitches into the same flow; otherwise mint only if tracing —
        # an untraced barrier should not grow the wire payload
        ambient = _tracectx.current()
        wire_ctx = (ambient.child() if ambient is not None
                    else (_tracectx.TraceContext.new()
                          if _prof.tracing_enabled() else None))
        payload = {"op": "barrier", "participant": name,
                   "step": int(step), "timeout": float(timeout)}
        if wire_ctx is not None:
            payload["trace"] = wire_ctx.to_traceparent()

        def _span(**args):
            _tracectx.record_span(
                "coord:barrier", wire_ctx, t0_us,
                _prof.now_us() - t0_us,
                args=dict(args, participant=name, step=int(step)))

        try:
            reply = self._request(payload,
                                  timeout=timeout + self.connect_timeout)
        except socket.timeout as e:
            _span(error="TimeoutError")
            raise TimeoutError(
                f"resume barrier: no reply from coordinator "
                f"{self.host}:{self.port} within {timeout}s") from e
        if reply.get("ok"):
            BARRIER_SECONDS.labels(impl="socket").observe(
                time.perf_counter() - t0)
            _span(ok=True, generation=reply.get("generation"))
            return int(reply["step"])
        err = reply.get("error")
        if err == "dead_peer":
            _span(error="DeadPeerError", peer=reply.get("peer"))
            rec = _flightrec.get_flight_recorder()
            rec.record("coord:dead_peer", peer=reply.get("peer", "?"),
                       generation=reply.get("generation", -1),
                       participant=name)
            rec.dump("dead_peer")
            raise DeadPeerError(reply.get("peer", "?"),
                                reply.get("generation", -1))
        if err == "timeout":
            _span(error="TimeoutError", arrived=reply.get("arrived"))
            raise TimeoutError(
                f"resume barrier: only {reply.get('arrived')}/"
                f"{reply.get('expected')} participants arrived within "
                f"{timeout}s")
        _span(error="BarrierProtocolError")
        raise BarrierProtocolError(f"coordinator error: {reply}")

    def close(self):
        self._closed.set()
        self._hb_thread.join(timeout=self.connect_timeout + 1.0)

    def __enter__(self) -> "SocketCoordinator":
        return self

    def __exit__(self, *exc):
        self.close()


# ----------------------------------------------------------------- file
class FileCoordinator(CoordinationService):
    """Shared-filesystem ``CoordinationService``: barrier arrival files
    + heartbeat mtimes under ``directory``. Every participant runs the
    same code — there is no server process; the filesystem is the
    rendezvous (same trade as ``parallel/checkpoint.py``'s manifest
    merge). Suited to clusters where every host mounts one filesystem
    and opening a port is the harder thing.

    Layout::

        <dir>/hb_<participant>            (touched every heartbeat)
        <dir>/gen<k>_<participant>.json   ({"step": n})

    Each participant tracks its own generation counter (barriers are
    called in lockstep by construction — the elastic layer's contract);
    the agreed step is the min over the generation's arrival files.
    """

    def __init__(self, directory: str, participants: int,
                 participant: str = None, heartbeat_timeout: float = 5.0,
                 heartbeat_interval: float = 1.0):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.participants = int(participants)
        self.participant = participant if participant is not None \
            else f"{socket.gethostname()}-{os.getpid()}"  # see SocketCoordinator
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.heartbeat_interval = float(heartbeat_interval)
        self._generation = 0
        # freshness floor: arrival/heartbeat files older than this
        # coordinator's construction belong to a PREVIOUS run in a
        # reused directory — counting them would agree on a stale step
        # (gen files) or fail every barrier forever (dead hb files).
        # Wall clock by necessity: file mtimes are wall-clock.
        self._t0 = time.time() - 1.0  # dl4j: noqa=W210
        self._closed = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"dl4j-coord-fhb-{self.participant}")
        self._hb_thread.start()

    def _hb_path(self, participant: str) -> str:
        return os.path.join(self.directory, f"hb_{participant}")

    def _touch_hb(self):
        path = self._hb_path(self.participant)
        with open(path, "a"):
            os.utime(path, None)

    def _heartbeat_loop(self):
        self._touch_hb()
        while not self._closed.wait(self.heartbeat_interval):
            try:
                self._touch_hb()
            except OSError:
                continue

    def resume_barrier(self, participant: str, step: int,
                       timeout: float = 60.0) -> int:
        import glob as _glob
        t0 = time.perf_counter()
        name = str(participant or self.participant)
        gen = self._generation
        own = os.path.join(self.directory, f"gen{gen}_{name}.json")
        tmp = own + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": int(step)}, f)
        os.replace(tmp, own)
        # result-acceptance floor: OUR round's result is written after
        # every arrival, including this one — a previous run's result
        # file strictly predates it, however quickly a supervisor
        # restarted us into the reused directory (the construction-time
        # floor alone leaves a <slack hole there)
        try:
            result_floor = os.path.getmtime(own)
        except OSError:
            result_floor = self._t0
        deadline = time.monotonic() + timeout
        pattern = os.path.join(self.directory, f"gen{gen}_*.json")
        result_path = os.path.join(self.directory, f"result_gen{gen}.json")
        while True:
            # a durable agreement first: whoever completed the round
            # wrote the result (and may have cleanly closed since,
            # retiring its heartbeat — its arrival must still bind us).
            # Floored on our own arrival's mtime: OUR round's result is
            # always written after every arrival, so anything older is
            # a previous run's leftover in a reused directory.
            try:
                if os.path.getmtime(result_path) >= result_floor:
                    with open(result_path) as f:
                        agreed = int(json.load(f)["step"])
                    self._generation += 1
                    BARRIER_SECONDS.labels(impl="file").observe(
                        time.perf_counter() - t0)
                    return agreed
            except (OSError, json.JSONDecodeError, KeyError, ValueError):
                pass        # absent or mid-rename: fall through to census
            # liveness census first: an arrival only counts when its
            # peer's heartbeat is FRESH — this is what separates a
            # same-run peer that arrived before we even constructed
            # (still heartbeating: counted) from a previous run's ghost
            # files in a reused directory (stale heartbeat: ignored).
            # Heartbeat ages compare against file MTIMES, which are
            # wall-clock by nature — monotonic time is meaningless
            # across processes.
            now = time.time()   # dl4j: noqa=W210
            fresh = {self.participant}
            registered: Dict[str, float] = {}
            for hb in _glob.glob(os.path.join(self.directory, "hb_*")):
                peer = os.path.basename(hb)[len("hb_"):]
                try:
                    mtime = os.path.getmtime(hb)
                except OSError:
                    continue
                registered[peer] = mtime
                if now - mtime <= self.heartbeat_timeout:  # dl4j: noqa=W210
                    fresh.add(peer)
            arrivals = {}
            for path in _glob.glob(pattern):
                peer = os.path.basename(path)[len(f"gen{gen}_"):-len(".json")]
                if peer not in fresh:
                    continue
                try:
                    with open(path) as f:
                        arrivals[peer] = int(json.load(f)["step"])
                except (json.JSONDecodeError, OSError, KeyError, ValueError):
                    continue    # mid-rename on a non-atomic filesystem
            if len(arrivals) >= self.participants:
                agreed = min(arrivals.values())
                # persist the agreement before returning: peers that
                # poll after we (or others) close must still converge
                rtmp = result_path + ".tmp"
                try:
                    with open(rtmp, "w") as f:
                        json.dump({"step": agreed}, f)
                    os.replace(rtmp, result_path)
                except OSError:
                    pass    # best-effort: live peers agree via census
                self._generation += 1
                BARRIER_SECONDS.labels(impl="file").observe(
                    time.perf_counter() - t0)
                return agreed
            # dead-peer detection: a peer that registered during THIS
            # session (mtime past our construction floor) and stopped
            # heartbeating is dead, not slow — previous-run ghosts
            # (mtime < _t0) are ignored, they were never our peers
            for peer, mtime in registered.items():
                if peer in fresh or peer == self.participant:
                    continue
                if mtime >= self._t0:
                    self._generation += 1
                    DEAD_PEERS.inc()
                    rec = _flightrec.get_flight_recorder()
                    rec.record("coord:dead_peer", peer=peer,
                               generation=gen, impl="file")
                    rec.dump("dead_peer")
                    raise DeadPeerError(peer, gen)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"resume barrier: only {len(arrivals)}/"
                    f"{self.participants} participants arrived within "
                    f"{timeout}s (generation {gen})")
            time.sleep(0.05)

    def close(self):
        self._closed.set()
        self._hb_thread.join(timeout=self.heartbeat_interval + 1.0)
        # a clean exit retires this participant: its heartbeat file must
        # not read as a dead peer to anyone still (or later) waiting
        try:
            os.remove(self._hb_path(self.participant))
        except OSError:
            pass

    def __enter__(self) -> "FileCoordinator":
        return self

    def __exit__(self, *exc):
        self.close()
