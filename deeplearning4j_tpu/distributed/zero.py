"""ZeRO-style sharded updater state (tier 2 of the GSPMD engine).

In plain data parallelism every device carries a full replica of the
updater state — for Adam that is 2x the parameter bytes of pure waste
per extra replica, and it is what blows the E104 HBM budget first on
big models. "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md) observes that the weight update is
element-wise in the gradient and the state, so the state (and the
update computation) can be sharded across the data axis and only the
resulting parameter delta all-gathered — the math is unchanged,
per-device optimizer HBM drops ~``n_data``x, and XLA inserts the
all-gather where the replicated parameters consume the sharded update.

:class:`ZeroPlan` is the declaration: which mesh axis to partition
over, and the minimum tensor size worth sharding. It composes with the
parameter's own sharding (a tensor already model-sharded on dim 0
shards its state over ``data`` on the next free divisible dim).
Checkpointing needs no gather: ``parallel/checkpoint.py`` writes the
addressable shards as-is and ``load_sharded`` re-stitches them under
any target topology; :func:`gather_opt_state` is the explicit
all-gather-on-demand seam for writers that want full host arrays.

Measured accounting: ``dl4j_updater_hbm_bytes{device}`` gauges the
bytes of updater state physically resident on each device (from
``addressable_shards``), so the ~1/``n_data`` claim is a number, not a
formula.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import profiler as _prof

#: below this many bytes a state tensor stays with its param's sharding —
#: sharding tiny tensors buys nothing and costs collective latency
DEFAULT_MIN_BYTES = 65536

UPDATER_HBM = _prof.get_registry().gauge(
    "dl4j_updater_hbm_bytes",
    "Updater (optimizer) state bytes physically resident per device, "
    "measured from the arrays' addressable shards",
    labelnames=("device",))


class ZeroPlan:
    """Declaration of cross-replica updater-state sharding.

    ``axis``: the mesh axis to partition state tensors over (the data
    axis — each data replica keeps 1/n of every moment tensor).
    ``min_bytes``: tensors smaller than this keep their parameter's
    sharding (default 64 KiB).
    """

    def __init__(self, axis: str = "data", min_bytes: int = DEFAULT_MIN_BYTES):
        self.axis = str(axis)
        self.min_bytes = int(min_bytes)

    @staticmethod
    def coerce(obj) -> Optional["ZeroPlan"]:
        """ZeroPlan | True (defaults) | {"axis": ..., "min_bytes": ...}"""
        if obj is None or isinstance(obj, ZeroPlan):
            return obj
        if obj is True:
            return ZeroPlan()
        if obj is False:
            return None
        if isinstance(obj, str):
            return ZeroPlan(axis=obj)
        if isinstance(obj, dict):
            return ZeroPlan(**obj)
        raise TypeError(f"cannot interpret {obj!r} as a ZeRO plan "
                        "(use ZeroPlan, True, an axis name, or a dict)")

    def signature(self):
        return ("zero", self.axis, self.min_bytes)

    def declare(self) -> Dict:
        """The jax-free mirror for the static analyzer
        (:class:`~deeplearning4j_tpu.analysis.distribution.MeshSpec`'s
        ``zero=`` declaration)."""
        return {"axis": self.axis, "min_bytes": self.min_bytes}

    def state_spec(self, param_spec, shape, itemsize: int, n_axis: int) -> P:
        """PartitionSpec for one param-shaped state tensor: the param's
        own spec with ``self.axis`` inserted at the first unsharded dim
        the axis divides. Tensors below ``min_bytes``, or with no
        divisible free dim, keep the param spec (replicated state there
        — correctness never depends on the partitioning)."""
        shape = tuple(int(d) for d in shape)
        entries = list(tuple(param_spec) if param_spec is not None else ())
        entries += [None] * (len(shape) - len(entries))
        nbytes = int(np.prod(shape)) * itemsize if shape else itemsize
        if n_axis <= 1 or nbytes < self.min_bytes:
            return P(*entries)
        used = {a for e in entries if e is not None
                for a in (e if isinstance(e, (tuple, list)) else (e,))}
        if self.axis in used:
            # FSDP-style param sharding already partitions over this
            # axis: the state inherits it (inserting it again would be
            # a duplicate-axis PartitionSpec, which NamedSharding
            # rejects — and the state is already 1/n per device)
            return P(*entries)
        for d, e in enumerate(entries):
            if e is None and shape[d] >= n_axis and shape[d] % n_axis == 0:
                entries[d] = self.axis
                return P(*entries)
        return P(*tuple(param_spec) if param_spec is not None else ())

    def __repr__(self):
        return f"ZeroPlan(axis={self.axis!r}, min_bytes={self.min_bytes})"


def updater_hbm_bytes(opt_state, record: bool = True) -> Dict[str, int]:
    """Measured per-device updater-state residency: {device: bytes} from
    every array leaf's ``addressable_shards`` (replicated leaves count
    their full size on EVERY device — that is the point of the gauge).
    ``record=True`` also publishes ``dl4j_updater_hbm_bytes{device}``."""
    per_device: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(opt_state):
        if not isinstance(leaf, jax.Array):
            continue
        try:
            shards = leaf.addressable_shards
        except Exception:       # uncommitted/host leaf: bill the default
            per_device["host"] = per_device.get("host", 0) + leaf.nbytes
            continue
        for sh in shards:
            key = str(sh.device)
            per_device[key] = per_device.get(key, 0) + int(sh.data.nbytes)
    if record:
        for dev, nbytes in per_device.items():
            UPDATER_HBM.labels(device=dev).set(float(nbytes))
    return per_device


def gather_opt_state(opt_state):
    """The all-gather-on-demand seam: full host (numpy) copies of every
    state tensor, whatever its sharding — what a non-shard-aware
    checkpoint writer (the PR-5 serializer path) consumes. Sharded
    checkpoints should prefer ``parallel.checkpoint.save_sharded``,
    which writes the addressable shards without any gather."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a))
        if isinstance(a, jax.Array) else a, opt_state)
