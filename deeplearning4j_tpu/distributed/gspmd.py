"""GSPMD-native fit path (tier 1): NamedSharding end-to-end.

``ParallelWrapper`` made multi-chip training a *wrapper* — replicate
params, shard the batch, let XLA allreduce — and anything beyond pure
data parallelism (tensor/sequence axes, sharded updater state) lived in
separate code paths or static lints. This module makes sharding a
*declaration* instead: a :class:`ShardedTrainingPlan` maps a
:class:`~deeplearning4j_tpu.parallel.mesh.DeviceMesh` plus per-parameter
:class:`~deeplearning4j_tpu.parallel.mesh.ShardingRule`\\ s to
``NamedSharding`` placements on params, updater state, and the batch,
and the networks' EXISTING compiled step/megastep runs under ONE
``jax.jit`` with those shardings (SNIPPETS.md [2]/[3]: mesh +
PartitionSpec annotations, let XLA insert the collectives). Data,
model, and sequence axes are one code path; the CachedDispatch/compile-
cache seam, precision policy, device augmentation, and churn detector
all carry through unchanged because the step body IS unchanged — the
only additions are committed input shardings and (when a
:class:`~deeplearning4j_tpu.distributed.zero.ZeroPlan` or model-axis
rules are declared) ``with_sharding_constraint`` on the step outputs so
XLA cannot silently gather the sharded state back to replicated.

Replication semantics: a plan with no rules and no ZeRO compiles the
byte-identical program the ``ParallelWrapper`` path compiles (same
replicated params, same batch sharding), which is what the bit-exact
parity pins in ``tests/test_distributed.py`` rely on.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.data.dataset import DataSetIterator as _DSIterator
from deeplearning4j_tpu.distributed.zero import ZeroPlan, updater_hbm_bytes
from deeplearning4j_tpu.parallel.mesh import DeviceMesh, ShardingRule


def _coerce_rules(rules) -> Optional[ShardingRule]:
    if rules is None or isinstance(rules, ShardingRule):
        return rules
    if isinstance(rules, dict):
        return ShardingRule(rules)
    raise TypeError(f"cannot interpret {rules!r} as sharding rules "
                    "(use ShardingRule or a {regex: spec-tuple} dict)")


class ShardedTrainingPlan:
    """Declarative mapping from a mesh to end-to-end shardings.

    - ``rules``: {param-name-regex: partition-spec-tuple} (or a
      :class:`ShardingRule`) matched against ``"<layer-or-node-name>/
      <param>"`` — the same naming the static distribution lints use.
      Unmatched params replicate.
    - ``batch_axes``: mesh axes the batch dim shards over (default
      ``("data",)``). On a model/seq-axis mesh the batch PartitionSpec
      replicates over the non-batch axes automatically — this is what
      the DevicePrefetcher placement derives from (the PR-2 carried
      follow-up: no more hard-coded ``(None, 'data')`` layout).
    - ``zero``: a :class:`~deeplearning4j_tpu.distributed.zero.
      ZeroPlan` (or ``True``) sharding updater state across the data
      axis.
    """

    def __init__(self, mesh: DeviceMesh, rules=None,
                 batch_axes: Tuple[str, ...] = ("data",), zero=None):
        self.mesh = mesh
        self.rules = _coerce_rules(rules)
        self.batch_axes = tuple(batch_axes)
        for a in self.batch_axes:
            if a not in mesh.mesh.axis_names:
                raise ValueError(f"batch axis {a!r} is not a mesh axis "
                                 f"{tuple(mesh.mesh.axis_names)}")
        self.zero = ZeroPlan.coerce(zero)

    # ------------------------------------------------------------ identity
    def signature(self):
        """Hashable identity for the compiled-step cache keys: mesh
        shape AND device ids (an equal-shaped mesh over different
        devices must bust the caches — the step's sharding-constraint
        closures are mesh-bound), rule patterns, batch axes, and the
        ZeRO declaration."""
        rules = None
        if self.rules is not None:
            rules = tuple((pat.pattern, tuple(spec))
                          for pat, spec in self.rules.rules)
        return ("gspmd", tuple(dict(self.mesh.mesh.shape).items()),
                tuple(d.id for d in self.mesh.devices), rules,
                self.batch_axes,
                self.zero.signature() if self.zero is not None else None)

    def data_shards(self) -> int:
        """How many ways the batch dim splits (the pad-to multiple)."""
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.size(a)
        return n

    def mesh_spec(self, **kw):
        """Jax-free declaration for the static analyzer: the mesh with
        this plan's sharding rules AND ZeRO declaration attached, so
        E104 accounts sharded updater state and W109 stays quiet."""
        kw.setdefault("sharding", self.rules)
        if self.zero is not None:
            kw.setdefault("zero", self.zero.declare())
        return self.mesh.spec(**kw)

    # ------------------------------------------------------- param naming
    def _leaf_param_name(self, model, path) -> str:
        """``"<layer-or-node-name>/<param>"`` for a params/opt-state leaf
        path — SequenceKey index (MultiLayerNetwork list) resolves to the
        layer's name, DictKey (ComputationGraph dict) is the node name."""
        first = path[0]
        pname = str(getattr(path[1], "key", path[1]))
        idx = getattr(first, "idx", None)
        layers = getattr(model, "layers", None)
        if idx is not None and layers is not None:
            layer = layers[idx]
            lname = getattr(layer, "name", None) or type(layer).__name__
        else:
            lname = str(getattr(first, "key", first))
        return f"{lname}/{pname}"

    def _param_spec(self, model, path, leaf) -> P:
        if self.rules is None:
            return P()
        name = self._leaf_param_name(model, path)
        return self.rules.spec_for(name, np.ndim(leaf))

    # ------------------------------------------------------- sharding trees
    def param_shardings(self, model):
        """NamedSharding pytree matching ``model._params``."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(
                self.mesh.mesh, self._param_spec(model, path, leaf)),
            model._params)

    def opt_shardings(self, model):
        """NamedSharding pytree matching ``model._opt_state``: each
        param-shaped state tensor composes the param's spec with the
        ZeRO data-axis partitioning (when declared)."""
        n_axis = self.mesh.size(self.zero.axis) \
            if self.zero is not None and self.zero.axis in self.mesh.mesh.axis_names \
            else 1

        def spec_of(path, leaf):
            pspec = self._param_spec(model, path, leaf)
            if self.zero is not None:
                itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
                spec = self.zero.state_spec(tuple(pspec),
                                            getattr(leaf, "shape", ()),
                                            itemsize, n_axis)
            else:
                spec = pspec
            return NamedSharding(self.mesh.mesh, spec)
        return jax.tree_util.tree_map_with_path(spec_of, model._opt_state)

    def step_constraints(self, model):
        """(param shardings, opt-state shardings) for
        ``with_sharding_constraint`` on the compiled step's outputs —
        or ``(None, None)`` for a pure-replication plan, where no
        constraint is needed and the compiled program stays
        byte-identical to the ParallelWrapper path (the bit-exact
        parity pins)."""
        if self.rules is None and self.zero is None:
            return None, None
        model._ensure_opt_state()
        return self.param_shardings(model), self.opt_shardings(model)

    # ----------------------------------------------------- batch placement
    def batch_spec(self, ndim: int, mega: bool = False) -> P:
        """The batch PartitionSpec: dim 0 (dim 1 under a ``[K, B, ...]``
        megabatch) shards over ``batch_axes``; everything else — and
        every other mesh axis — replicates."""
        if ndim == 0:
            return P()
        axes = self.batch_axes if len(self.batch_axes) > 1 \
            else self.batch_axes[0]
        if mega:
            if ndim == 1:
                return P(None)
            return P(None, axes, *([None] * (ndim - 2)))
        return P(axes, *([None] * (ndim - 1)))

    def batch_sharding(self, ndim: int, mega: bool = False) -> NamedSharding:
        return NamedSharding(self.mesh.mesh, self.batch_spec(ndim, mega))

    def place(self, a, mega: bool = False):
        """Stage one batch array onto the mesh per :meth:`batch_spec` —
        the DevicePrefetcher ``placement`` hook and the fit functions'
        staging call. A no-op copy-wise when ``a`` is already placed
        with this sharding."""
        if a is None:
            return None
        return jax.device_put(a, self.batch_sharding(np.ndim(a), mega))

    # ------------------------------------------------------------ lifecycle
    def apply(self, model):
        """Place params, layer states, and updater state onto the mesh
        per this plan, and refresh the ``dl4j_updater_hbm_bytes``
        gauge. Idempotent (device_put with an unchanged sharding is a
        no-op)."""
        if not model._initialized:
            model.init()
        model._ensure_opt_state()
        with _prof.trace_span("collective:place_params",
                              devices=self.mesh.size()):
            self.place_params(model)
            model._opt_state = jax.tree_util.tree_map(
                jax.device_put, model._opt_state, self.opt_shardings(model))
        model._t_dev = None     # rebuild the device clock on this mesh
        updater_hbm_bytes(model._opt_state)
        return model

    def place_params(self, model):
        """Place params + layer states (NOT updater state) per this
        plan — the serving-staging entry: an inference-only load must
        not allocate 2-3x its parameter bytes of never-used optimizer
        moments on the serving mesh. A live dynamic loss-scale carry
        moves with the params (its signature must match the mesh)."""
        model._params = jax.tree_util.tree_map(
            jax.device_put, model._params, self.param_shardings(model))
        model._states = self.mesh.replicate(model._states)
        if getattr(model, "_scale_state", None) is not None:
            model._scale_state = jax.device_put(model._scale_state,
                                                self.mesh.replicated())
        return model

    def ensure_placed(self, model) -> None:
        """Cheap per-dispatch guard: re-place the model when its arrays
        are not on this plan's mesh (fresh init, a resilience restore
        that swapped in host arrays, or a plan change)."""
        if model._opt_state is None:
            self.apply(model)
            return
        for tree in (model._params, model._opt_state):
            leaves = jax.tree_util.tree_leaves(tree)
            if not leaves:
                continue
            sh = getattr(leaves[0], "sharding", None)
            if getattr(sh, "mesh", None) != self.mesh.mesh:
                self.apply(model)
                return

    def __repr__(self):
        return (f"ShardedTrainingPlan(mesh={dict(self.mesh.mesh.shape)}, "
                f"rules={'yes' if self.rules else None}, "
                f"batch_axes={self.batch_axes}, zero={self.zero})")


# --------------------------------------------------------------- trainer
class GSPMDTrainer:
    """The one-``jit``-with-shardings fit driver.

    Where :class:`~deeplearning4j_tpu.parallel.wrapper.ParallelWrapper`
    is replicate-and-shard-the-batch only, this trainer applies a full
    :class:`ShardedTrainingPlan` — so the same ``fit()`` call covers
    pure DP, tensor-parallel rules, ZeRO updater-state sharding, and
    combinations, with resilience (``checkpoint=``/``nan_policy=``/
    ``faults=``) and megasteps composing unchanged (they ride the
    network's own fit loop).
    """

    def __init__(self, model, plan: ShardedTrainingPlan,
                 prefetch_buffer: int = 2):
        self.model = model
        self.plan = plan
        self.prefetch = prefetch_buffer

    @property
    def mesh(self) -> DeviceMesh:
        return self.plan.mesh

    def validate(self, batch_size: int = None, **kw):
        """Static lint against this plan's mesh + sharding + ZeRO
        declaration (E1xx/W10x incl. the ZeRO-aware E104 and W109)."""
        kw.setdefault("mesh", self.plan.mesh_spec())
        return self.model.validate(batch_size=batch_size, **kw)

    def warmup(self, shapes, *, steps_per_dispatch: int = 1, dtype=None,
               label_dtype=None, policy=None):
        """AOT-warm the model's programs under this plan's placements
        through the PR-13 compile-cache seam — same contract as
        ``ParallelWrapper.warmup`` (batch dims pad up to the plan's
        data-shard multiple exactly like ``fit`` pads real batches)."""
        from deeplearning4j_tpu.nn import compilecache as _cc
        model = self.model
        model.setShardingPlan(self.plan)
        if not model._initialized:
            model.init()
        self.plan.apply(model)
        n = self.plan.data_shards()

        def pad_shape(shape):
            shape = tuple(int(d) for d in shape)
            b = shape[0]
            if b % n:
                b += n - b % n
            return (b,) + shape[1:]

        padded = []
        for spec in shapes:
            if (isinstance(spec, (tuple, list)) and len(spec) == 2
                    and isinstance(spec[0], (tuple, list))):
                padded.append((pad_shape(spec[0]), pad_shape(spec[1])))
            else:
                padded.append(pad_shape(spec))
        k = max(int(steps_per_dispatch), 1)
        if k > 1 and any(not (isinstance(s, (tuple, list)) and len(s) == 2
                              and isinstance(s[0], (tuple, list)))
                         for s in padded):
            # same guard as ParallelWrapper.warmup: the placement hook
            # stages per the megabatch layout when k>1, which would
            # shard a bare forward shape's FEATURE dim over the data axis
            raise ValueError(
                "steps_per_dispatch>1 warms the megastep from "
                "(features, labels) pairs; bare forward shapes cannot "
                "be megabatched — warm them in a separate call")
        _cc.warmup(model, padded, policy=policy, steps_per_dispatch=k,
                   dtype=dtype, label_dtype=label_dtype,
                   placement=lambda a: self.plan.place(a, k > 1))
        return model

    def fit(self, data, epochs: int = 1, steps_per_dispatch: int = 1,
            checkpoint=None, nan_policy=None, faults=None,
            prefetch: int = None):
        """Fit through the network's own loop with this plan attached:
        batches pad up to the data-shard multiple with zero-weight
        examples (gradients exactly match the unpadded batch), stage
        onto the mesh per the plan's batch PartitionSpec, and every
        dispatch runs the ONE compiled step with the plan's shardings."""
        from deeplearning4j_tpu.data.dataset import (DataSet,
                                                     DataSetIterator,
                                                     MultiDataSet)
        model = self.model
        model.setShardingPlan(self.plan)
        if not model._initialized:
            model.init()
        self.plan.apply(model)
        n = self.plan.data_shards()
        if n > 1:
            from deeplearning4j_tpu.parallel.data import pad_to_data_axis
            if isinstance(data, DataSetIterator):
                data = _PaddingIterator(data, n)
            elif isinstance(data, (DataSet, MultiDataSet)):
                data = pad_to_data_axis(data, n)
            elif isinstance(data, (list, tuple)) and data \
                    and isinstance(data[0], (DataSet, MultiDataSet)):
                data = [pad_to_data_axis(ds, n) for ds in data]
        return model.fit(
            data, epochs=epochs, steps_per_dispatch=steps_per_dispatch,
            prefetch=self.prefetch if prefetch is None else prefetch,
            checkpoint=checkpoint, nan_policy=nan_policy, faults=faults)


class _PaddingIterator(_DSIterator):
    """DataSetIterator proxy padding every batch up to the plan's
    data-shard multiple (zero-weight tail examples — see
    ``parallel.data.pad_to_data_axis``). Forwards the checkpoint
    cursor protocol so resilience sessions compose."""

    def __init__(self, base: _DSIterator, n: int):
        self.base = base
        self.n = int(n)

    def next(self):
        from deeplearning4j_tpu.parallel.data import pad_to_data_axis
        return pad_to_data_axis(self.base.next(), self.n)

    def hasNext(self):
        return self.base.hasNext()

    def reset(self):
        self.base.reset()

    def batch(self):
        return self.base.batch()

    def cursor(self):
        return self.base.cursor()

    def seek(self, cursor):
        self.base.seek(cursor)


# ------------------------------------------------------- HLO accounting
_SHAPE_RE = re.compile(r"([a-z]+[0-9]+)\[([0-9,]*)\]")
_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z]+[0-9]+\[[0-9,]*\][^ ]*)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(?:-start)?\(")
_DTYPE_BYTES = {"f64": 8, "u64": 8, "s64": 8,
                "f32": 4, "u32": 4, "s32": 4,
                "bf16": 2, "f16": 2, "u16": 2, "s16": 2,
                "u8": 1, "s8": 1, "pred": 1}


def hlo_collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Per-kind output-tensor byte counts of the collective ops in a
    compiled (post-SPMD-partitioning) HLO module — the measured side of
    the W107 collective-volume characterization. Keys: ``all-reduce``,
    ``all-gather``, ``reduce-scatter``, ``collective-permute`` (absent
    kinds omitted); values are the summed per-device output bytes of
    each op's shape."""
    out: Dict[str, int] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shape_str, kind = m.group(1), m.group(2)
        total = 0
        for dt, dims in _SHAPE_RE.findall(shape_str):
            size = _DTYPE_BYTES.get(dt, 4)
            for d in dims.split(","):
                if d:
                    size *= int(d)
            total += size
        if total:
            out[kind] = out.get(kind, 0) + total
    return out


def compiled_train_step_hlo(model, features, labels, steps: int = 1) -> str:
    """Compiled HLO text of the model's train step for this batch
    signature under the attached sharding plan (MultiLayerNetwork;
    ``steps>1`` lowers the megastep over ``[K, B, ...]`` stacks).
    Nothing executes — the program is lowered and compiled only, which
    is exactly what ``benchmarks/probe_collectives.py`` and the
    ``--virtual-mesh`` scaling bench need for collective accounting."""
    model._ensure_opt_state()
    plan = getattr(model, "_sharding_plan", None)
    x = np.asarray(features)
    y = np.asarray(labels)
    if plan is not None:
        plan.ensure_placed(model)
        x = plan.place(x, steps > 1)
        y = plan.place(y, steps > 1)
    step, dummy = model._step_for((False, False), steps)
    clock = jnp.asarray(model._iteration, jnp.int32)
    args = [model._params, model._states, model._opt_state, clock]
    if model._dynamic_scaling():
        args.append(model._ensure_scale_state())
    args += [x, y, dummy, dummy]
    return step._jit.lower(*args).compile().as_text()
