"""Early stopping: validation-driven training termination + best-model save.

Reference parity: ``org.deeplearning4j.earlystopping.*`` —
``EarlyStoppingConfiguration``, ``EarlyStoppingTrainer``, score calculators
(``DataSetLossCalculator``), termination conditions
(``MaxEpochsTerminationCondition``, ``ScoreImprovementEpochTerminationCondition``,
``MaxScoreIterationTerminationCondition``, ``MaxTimeIterationTerminationCondition``),
``EarlyStoppingResult``, ``LocalFileModelSaver`` / ``InMemoryModelSaver``
(SURVEY.md §2.2 "Early stopping").
"""

from __future__ import annotations

import copy
import os
import time
from typing import List

import numpy as np


class DataSetLossCalculator:
    """Average loss over a validation iterator (ref: DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculateScore(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        while self.iterator.hasNext():
            ds = self.iterator.next()
            total += model.score(ds) * ds.numExamples()
            n += ds.numExamples()
        return total / max(n, 1) if self.average else total


class ClassificationScoreCalculator:
    """Negative accuracy so 'lower is better' holds (ref:
    ClassificationScoreCalculator uses the Evaluation metric)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculateScore(self, model) -> float:
        ev = model.evaluate(self.iterator)
        return -ev.accuracy()


class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float, best_epoch: int) -> bool:
        return epoch >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without score improvement (ref class of the
    same name)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement

    def terminate(self, epoch: int, score: float, best_epoch: int) -> bool:
        return (epoch - best_epoch) > self.patience


class MaxScoreIterationTerminationCondition:
    """Abort if score explodes (ref class of the same name)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate_iteration(self, score: float) -> bool:
        return score > self.max_score or not np.isfinite(score)


class MaxTimeIterationTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def terminate_iteration(self, score: float) -> bool:
        # monotonic: an NTP wall-clock step must not end (or extend)
        # the training budget spuriously (W210)
        if self._start is None:
            self._start = time.monotonic()
            return False
        return (time.monotonic() - self._start) > self.max_seconds


class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self._model_ref = None

    def saveBestModel(self, model, score):
        self.best = (copy.deepcopy(model._params), copy.deepcopy(model._states))
        self._model_ref = model

    def getBestModel(self):
        if self.best is None:
            return None      # nothing saved (e.g. a resumed run that never
        model = self._model_ref  # improved on the restored best score)
        model._params, model._states = self.best
        return model


class LocalFileModelSaver:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.best_path = os.path.join(directory, "bestModel.zip")
        self._model_cls = None

    def saveBestModel(self, model, score):
        model.save(self.best_path)
        self._model_cls = type(model)

    def getBestModel(self):
        # None when nothing was ever saved (or the zip is gone) — e.g. a
        # resumed run whose restored best was never beaten; the trainer
        # falls back to the final model instead of crashing
        if self._model_cls is None or not os.path.exists(self.best_path):
            return None
        return self._model_cls.load(self.best_path)


class EarlyStoppingConfiguration:
    """ref: EarlyStoppingConfiguration.Builder."""

    def __init__(self, score_calculator, epoch_termination_conditions: List,
                 iteration_termination_conditions: List = None,
                 model_saver=None, evaluate_every_n_epochs: int = 1):
        self.score_calculator = score_calculator
        self.epoch_conditions = epoch_termination_conditions
        self.iter_conditions = iteration_termination_conditions or []
        self.saver = model_saver or InMemoryModelSaver()
        self.eval_every = evaluate_every_n_epochs

    class Builder:
        def __init__(self):
            self._score = None
            self._epoch_conds = []
            self._iter_conds = []
            self._saver = None
            self._every = 1

        def scoreCalculator(self, sc):
            self._score = sc
            return self

        def epochTerminationConditions(self, *conds):
            self._epoch_conds.extend(conds)
            return self

        def iterationTerminationConditions(self, *conds):
            self._iter_conds.extend(conds)
            return self

        def modelSaver(self, saver):
            self._saver = saver
            return self

        def evaluateEveryNEpochs(self, n):
            self._every = n
            return self

        def build(self):
            return EarlyStoppingConfiguration(self._score, self._epoch_conds,
                                              self._iter_conds, self._saver,
                                              self._every)


class EarlyStoppingResult:
    """ref: EarlyStoppingResult."""

    def __init__(self, termination_reason: str, termination_details: str,
                 score_vs_epoch: dict, best_epoch: int, best_score: float,
                 total_epochs: int, best_model):
        self.termination_reason = termination_reason
        self.termination_details = termination_details
        self.score_vs_epoch = score_vs_epoch
        self.best_epoch = best_epoch
        self.best_score = best_score
        self.total_epochs = total_epochs
        self.best_model = best_model

    def getBestModel(self):
        return self.best_model

    def getBestModelEpoch(self):
        return self.best_epoch

    def getBestModelScore(self):
        return self.best_score


class EarlyStoppingTrainer:
    """ref: EarlyStoppingTrainer (works for MultiLayerNetwork and
    ComputationGraph — both expose fit/score).

    ``steps_per_dispatch=K`` routes each epoch through the megastep path
    (ROADMAP PR-2 follow-up): K consecutive same-signature batches run as
    ONE compiled ``lax.scan`` dispatch, with iteration termination
    conditions scored between megabatches (the score checked after a
    K-step dispatch is the dispatch's final per-step loss — conditions
    fire at dispatch granularity, epoch semantics are unchanged).

    ``checkpoint=CheckpointConfig(dir, resume=True)`` (train.resilience)
    checkpoints the model + the trainer's own search state (best score /
    best epoch / score history) after every scored epoch, and resumes
    both from the newest validated checkpoint — an early-stopping run
    killed at epoch 37 restarts with its best-score bookkeeping intact
    instead of rediscovering (or worse, forgetting) its best model. Use
    a ``LocalFileModelSaver`` so the best model itself also survives the
    process."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator, steps_per_dispatch: int = 1,
                 checkpoint=None):
        self.config = config
        self.model = model
        self.iterator = train_iterator
        self.steps_per_dispatch = max(int(steps_per_dispatch), 1)
        self.checkpoint = checkpoint

    def _epoch_batches(self):
        self.iterator.reset()
        while self.iterator.hasNext():
            yield self.iterator.next()

    def _epoch_items(self):
        """Per-dispatch work items: plain DataSets at K=1, MegaBatches
        (with single-step fallbacks at signature changes / epoch tails)
        at K>1."""
        if self.steps_per_dispatch <= 1:
            return self._epoch_batches()
        from deeplearning4j_tpu.train import stepping as _stepping
        return _stepping.group_into_megabatches(self._epoch_batches(),
                                                self.steps_per_dispatch)

    def _resume(self, manager):
        """Restore model + search state from the newest valid checkpoint.
        Returns (best_score, best_epoch, scores, epoch)."""
        fresh = (float("inf"), -1, {}, 0)
        if manager is None or not self.checkpoint.resume:
            return fresh
        info = manager.restore(self.model)
        if info is None:
            return fresh
        es = (info.get("extra") or {}).get("earlystopping") or {}
        if isinstance(self.config.saver, LocalFileModelSaver) \
                and os.path.exists(self.config.saver.best_path):
            # re-arm the saver so getBestModel() works without a fresh
            # saveBestModel() call in the resumed process
            self.config.saver._model_cls = type(self.model)
        elif es.get("best_epoch", -1) >= 0:
            import warnings
            warnings.warn(
                "EarlyStoppingTrainer resume: the best-score bookkeeping was "
                "restored, but this saver cannot reload the best MODEL from a "
                "previous process — the result falls back to the final model "
                "unless the resumed run finds a new best. Use "
                "LocalFileModelSaver for resumable runs.", stacklevel=2)
        return (es.get("best_score", float("inf")),
                es.get("best_epoch", -1),
                {int(k): v for k, v in (es.get("scores") or {}).items()},
                int(es.get("epoch", 0)))

    def fit(self) -> EarlyStoppingResult:
        from deeplearning4j_tpu.train.stepping import MegaBatch
        cfg = self.config
        manager = None
        if self.checkpoint is not None:
            from deeplearning4j_tpu.train.resilience import CheckpointManager
            manager = CheckpointManager(self.checkpoint)
        best_score, best_epoch, scores, epoch = self._resume(manager)
        reason, details = "MaxEpochs", ""
        while True:
            # one epoch, watching iteration conditions between dispatches
            aborted = False
            for item in self._epoch_items():
                if isinstance(item, MegaBatch):
                    self.model._fit_mega(item)
                else:
                    self.model._fit_one(item)
                for ic in cfg.iter_conditions:
                    if ic.terminate_iteration(self.model.score()):
                        reason = "IterationTerminationCondition"
                        details = type(ic).__name__
                        aborted = True
                        break
                if aborted:
                    break
            if aborted:
                break
            epoch += 1
            if epoch % cfg.eval_every == 0:
                score = cfg.score_calculator.calculateScore(self.model)
                scores[epoch] = score
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.saver.saveBestModel(self.model, score)
            if manager is not None:
                manager.save(self.model, extra={"earlystopping": {
                    "best_score": best_score, "best_epoch": best_epoch,
                    "scores": {str(k): v for k, v in scores.items()},
                    "epoch": epoch}})
            stop = False
            for ec in cfg.epoch_conditions:
                if ec.terminate(epoch, scores.get(epoch, best_score), best_epoch):
                    reason = "EpochTerminationCondition"
                    details = type(ec).__name__
                    stop = True
                    break
            if stop:
                break
        best_model = cfg.saver.getBestModel() if best_epoch >= 0 else None
        if best_model is None:
            best_model = self.model
        return EarlyStoppingResult(reason, details, scores, best_epoch,
                                   best_score, epoch, best_model)
