"""Fault-tolerant training: auto-checkpoint/resume, preemption handling,
NaN/Inf recovery policies.

Periodic checkpointing with automatic recovery is a founding design
point of production training systems (TensorFlow, Abadi et al., 2016),
and long data-parallel accelerator jobs make preemption the COMMON
case, not the exception — yet a training loop without this layer loses
a multi-hour ``fit()`` to a single SIGTERM, NaN step, or flaky disk.
This module is the missing layer between the megastep engine and
anything production-shaped:

- :class:`CheckpointConfig` + :class:`CheckpointManager` — periodic
  **atomic** checkpoints of the FULL training state: params, updater
  state, layer states, the per-step RNG counter (the step clock ``t``
  that ``fold_in(seed, t)`` derives dropout keys from), epoch/step,
  the iterator's normalizer, and the data-iterator cursor. Writes go
  to a temp dir finalized by ONE ``os.replace`` (a crash mid-write can
  never leave a half-checkpoint under the real name); every file is
  SHA-256'd into the manifest; ``keep_last=N`` rotation; resume picks
  the newest checkpoint that passes checksum validation and
  QUARANTINES corrupt ones instead of trusting them.
- Preemption handling — SIGTERM/SIGINT (plus pluggable
  :class:`PreemptionSignal` implementations for tests and cluster
  schedulers) finish the in-flight (mega)step, write a checkpoint whose
  manifest is marked ``"preempted"``, and return cleanly from ``fit``.
- :class:`NanPolicy` — upgrades the NAN_PANIC raise-only debug knob to
  actual recovery: ``RAISE``, ``SKIP_STEP`` (drop the poisoned update,
  keep going), ``BACKOFF_LR`` (drop the update AND halve the learning
  rate, recovering it after a cooldown of clean steps), ``ROLLBACK``
  (restore the last good checkpoint). Tune via :class:`NanRecovery`.
- Transient-I/O retry with exponential backoff around checkpoint
  writes/reads (and, via ``data.dataset.RetryingDataSetIterator``,
  around data pulls).

Everything is observable in the profiler registry:
``dl4j_nonfinite_steps_total``, ``dl4j_rollbacks_total``,
``dl4j_checkpoint_seconds``, ``dl4j_resume_total``,
``dl4j_preemptions_total``, ``dl4j_checkpoint_quarantined_total``,
``dl4j_lr_backoffs_total`` (plus ``dl4j_data_retries_total`` from the
data layer). Every recovery path is pinned by a deterministic injected
fault (``deeplearning4j_tpu.faults``) in ``tests/test_resilience.py``.

Usage::

    net.fit(iterator, epochs=3,
            checkpoint=CheckpointConfig("/ckpts", every_steps=200,
                                        resume=True),
            nan_policy=NanPolicy.SKIP_STEP)

Resume is bit-exact: ``fit(N)`` == ``fit(k)`` + preemption + resume for
params, updater state, and the step RNG (pinned for MultiLayerNetwork,
ComputationGraph, and ``steps_per_dispatch>1`` megastep runs).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue as _queue
import shutil
import signal as _signal
import sys
import threading
import time
import warnings
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.data.dataset import (DataSetIterator,
                                             RetryingDataSetIterator)
from deeplearning4j_tpu.utils.concurrent import ErrorLatch
from deeplearning4j_tpu.utils.environment import NumericsPanicError

logger = logging.getLogger("deeplearning4j_tpu")

_REG = _prof.get_registry()
NONFINITE_STEPS = _REG.counter(
    "dl4j_nonfinite_steps_total",
    "Update steps whose loss came back NaN/Inf (one per poisoned step, "
    "whatever the recovery policy did about it)")
ROLLBACKS = _REG.counter(
    "dl4j_rollbacks_total",
    "Checkpoint rollbacks performed by NanPolicy.ROLLBACK")
CKPT_SECONDS = _REG.histogram(
    "dl4j_checkpoint_seconds",
    "Wall time to write one atomic training checkpoint")
RESUMES = _REG.counter(
    "dl4j_resume_total",
    "Successful auto-resumes from a validated checkpoint")
PREEMPTIONS = _REG.counter(
    "dl4j_preemptions_total",
    "Preemption requests honored (signal or synthetic) — each wrote a "
    "'preempted' checkpoint when a CheckpointConfig was active")
QUARANTINED = _REG.counter(
    "dl4j_checkpoint_quarantined_total",
    "Checkpoints failing checksum/manifest validation at resume, moved "
    "aside instead of loaded")
LR_BACKOFFS = _REG.counter(
    "dl4j_lr_backoffs_total",
    "Learning-rate halvings performed by NanPolicy.BACKOFF_LR")
CKPT_ASYNC_QUEUE = _REG.gauge(
    "dl4j_checkpoint_async_queue_depth",
    "Snapshots queued for the background checkpoint writer (a "
    "persistently full queue means the writer cannot keep up with "
    "every_steps and save() is applying backpressure)")


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed validation: unreadable/missing manifest, a
    file named by the manifest absent, or a SHA-256 mismatch. Resume
    quarantines the checkpoint and falls back to the previous one."""


class PreemptionRequested(Exception):
    """Internal control flow: a PreemptionSignal fired; the fit loop
    unwinds to its boundary, writes the 'preempted' checkpoint, and
    returns cleanly."""


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint write failed after its I/O retries. The
    error is raised on the TRAINING thread at the next fit step (or at
    fit exit) — a fit that believes it is checkpointing must not
    silently run bare."""


# --------------------------------------------------------------- I/O retry
def retry_io(fn: Callable, retries: int = 3, backoff: float = 0.05,
             exc=(OSError,)):
    """Run ``fn`` retrying transient I/O failures with exponential
    backoff — the storage layer under a checkpoint (NFS, object-store
    FUSE mounts) fails transiently as a matter of course on large
    clusters."""
    attempt = 0
    while True:
        try:
            return fn()
        except exc:
            if attempt >= retries:
                raise
            time.sleep(backoff * (2 ** attempt))
            attempt += 1


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# ------------------------------------------------------------ NaN policies
class NanPolicy(Enum):
    """What to do when a step's loss comes back non-finite (upgrades the
    raise-only NAN_PANIC debug mode to recovery)."""

    RAISE = "raise"            # fail fast (NumericsPanicError)
    SKIP_STEP = "skip_step"    # drop the poisoned update, keep training
    BACKOFF_LR = "backoff_lr"  # drop the update + halve LR (cooldown recovery)
    ROLLBACK = "rollback"      # restore the last good checkpoint


@dataclass
class NanRecovery:
    """A NanPolicy plus its tuning. ``fit(nan_policy=...)`` accepts
    either a bare :class:`NanPolicy` (defaults below) or this."""

    policy: NanPolicy
    backoff_factor: float = 0.5   # LR multiplier per BACKOFF_LR event
    cooldown_steps: int = 50      # clean steps before LR recovers one notch
    min_scale: float = 2.0 ** -16  # LR-scale floor: below this, raise
    max_rollbacks: int = 3        # consecutive ROLLBACKs before raising


# --------------------------------------------------------------- config
@dataclass
class CheckpointConfig:
    """Where/when/how to checkpoint. ``every_steps=0`` disables periodic
    saves (preemption and ``every_epochs`` still checkpoint).

    ``async_write=True`` moves serialization + fsync off the training
    thread: ``save()`` takes a device-side snapshot (one cheap on-device
    copy per buffer, safe against the compiled step's donation) and
    enqueues it for a background writer; the fit step continues while
    the writer serializes. The queue is bounded (``async_queue``) so a
    slow disk applies backpressure instead of accumulating snapshots in
    device memory, writer failures surface as
    :class:`AsyncCheckpointError` on the next fit step, and resume/
    rollback reads flush the queue first so they always see the newest
    write."""

    dir: str
    every_steps: int = 0
    every_epochs: int = 0
    resume: bool = False
    keep_last: int = 3
    io_retries: int = 3
    io_backoff: float = 0.05
    async_write: bool = False
    async_queue: int = 2


# ---------------------------------------------------------- preemption
class PreemptionSignal:
    """Pluggable preemption source: ``requested(step)`` is polled after
    every completed (mega)step. Subclass for cluster schedulers that
    announce preemption out-of-band (metadata server, borglet file)."""

    def requested(self, step: int) -> bool:
        return False


class StepPreemption(PreemptionSignal):
    """Synthetic preemption once ``step`` update steps have completed —
    the deterministic stand-in for SIGTERM that the fault harness and
    the resume-equivalence tests use."""

    def __init__(self, step: int):
        self.step = int(step)

    def requested(self, step: int) -> bool:
        return step >= self.step


class SignalPreemption(PreemptionSignal):
    """SIGTERM/SIGINT -> preemption flag. Installed for the duration of
    a resilient ``fit()`` (main thread only — signal handlers cannot be
    installed elsewhere); previous handlers are restored on close.

    ``on_request`` is an optional zero-arg callback invoked from the
    handler so a consumer polling from ANOTHER thread (the model
    server's serve loop reacting to SIGTERM with a drain) wakes
    immediately instead of at its next poll. It must be cheap and
    non-blocking — setting a ``threading.Event`` is the intended use;
    exceptions are swallowed (a failing callback must not break the
    signal handler)."""

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT),
                 on_request=None):
        self.signals = signals
        self.on_request = on_request
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}

    def install(self) -> bool:
        if threading.current_thread() is not threading.main_thread():
            return False
        for s in self.signals:
            self._prev[s] = _signal.signal(s, self._handler)
        return True

    def uninstall(self):
        for s, prev in self._prev.items():
            try:
                _signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev = {}

    def _handler(self, signum, frame):
        self._event.set()
        if self.on_request is not None:
            try:
                self.on_request()
            except Exception:
                pass

    def requested(self, step: int) -> bool:
        return self._event.is_set()


# ------------------------------------------------------------- manager
class CheckpointManager:
    """Atomic, checksummed, rotated training checkpoints.

    On-disk layout (one directory per checkpoint, finalized by a single
    ``os.replace`` so readers never observe a partial write)::

        <dir>/ckpt_0000000042/model.zip        full model (params, layer
                                               states, updater state,
                                               step/epoch counters)
        <dir>/ckpt_0000000042/extra.json       iterator cursor + caller
                                               extra state (early stopping)
        <dir>/ckpt_0000000042/normalizer.npz   iterator preprocessor (opt.)
        <dir>/ckpt_0000000042/manifest.json    step/epoch/status + per-file
                                               SHA-256
        <dir>/quarantine_ckpt_.../             failed validation at resume

    ``status`` in the manifest is ``"complete"`` or ``"preempted"``.
    """

    PREFIX = "ckpt_"

    def __init__(self, config: CheckpointConfig, fault_plan=None):
        self.config = config
        self.faults = fault_plan
        self._writer: Optional[_AsyncWriter] = None
        os.makedirs(config.dir, exist_ok=True)

    # ------------------------------------------------------------- naming
    def _name(self, step: int) -> str:
        return f"{self.PREFIX}{step:010d}"

    def checkpoints(self):
        """[(step, path)] ascending by step; quarantined/temp dirs are
        excluded."""
        out = []
        for entry in os.listdir(self.config.dir):
            if not entry.startswith(self.PREFIX):
                continue
            suffix = entry[len(self.PREFIX):]
            if not suffix.isdigit():
                continue
            out.append((int(suffix), os.path.join(self.config.dir, entry)))
        return sorted(out)

    # --------------------------------------------------------------- save
    def save(self, model, status: str = "complete", cursor=None,
             normalizer=None, extra: Optional[dict] = None) -> str:
        """Write one checkpoint. With ``async_write`` the state is
        snapshotted on device and the serialization/fsync happens on the
        background writer; the returned path is where the checkpoint
        WILL land (call :meth:`flush` to wait for it)."""
        if self.config.async_write:
            self.raise_async_errors()
            snap = _StateSnapshot(model)
            if self._writer is None:
                self._writer = _AsyncWriter(self, self.config.async_queue)
            self._writer.submit((snap, status, cursor, normalizer, extra))
            return os.path.join(self.config.dir, self._name(snap._iteration))
        return self._write(model, status, cursor, normalizer, extra)

    def _write(self, model, status: str = "complete", cursor=None,
               normalizer=None, extra: Optional[dict] = None) -> str:
        cfg = self.config
        step, epoch = int(model._iteration), int(model._epoch)
        t0 = time.perf_counter()
        name = self._name(step)
        final = os.path.join(cfg.dir, name)
        tmp = os.path.join(cfg.dir, f".tmp_{name}_{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)

        def write_model():
            if self.faults is not None \
                    and self.faults.checkpoint_write_error(step):
                raise OSError(
                    f"injected checkpoint write failure at step {step}")
            model.save(os.path.join(tmp, "model.zip"), save_updater=True)
        retry_io(write_model, cfg.io_retries, cfg.io_backoff)
        if normalizer is not None:
            try:
                from deeplearning4j_tpu.train.serializer import ModelSerializer
                ModelSerializer.writeNormalizer(
                    normalizer, os.path.join(tmp, "normalizer.npz"))
            except Exception as e:   # best effort: a normalizer that can't
                warnings.warn(       # serialize must not kill the checkpoint
                    f"checkpoint: could not serialize normalizer: {e}",
                    stacklevel=2)
        with open(os.path.join(tmp, "extra.json"), "w") as f:
            json.dump({"cursor": cursor, "extra": extra or {}}, f)
        files = {fn: _sha256_file(os.path.join(tmp, fn))
                 for fn in sorted(os.listdir(tmp))}
        manifest = {"format": 1, "step": step, "epoch": epoch,
                    "status": status, "files": files,
                    "unix_time": time.time()}
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        if os.path.isdir(final):     # re-save of the same step (preemption
            shutil.rmtree(final)     # right after a periodic save)
        retry_io(lambda: os.replace(tmp, final), cfg.io_retries,
                 cfg.io_backoff)
        if self.faults is not None:
            self.faults.corrupt_checkpoint(step, final)
        CKPT_SECONDS.observe(time.perf_counter() - t0)
        self._rotate()
        return final

    def _rotate(self):
        cps = self.checkpoints()
        while len(cps) > max(1, self.config.keep_last):
            _, path = cps.pop(0)
            retry_io(lambda p=path: shutil.rmtree(p, ignore_errors=False),
                     self.config.io_retries, self.config.io_backoff)

    # ----------------------------------------------------- async lifecycle
    def flush(self):
        """Block until every queued async write has been attempted (a
        failed attempt is reported by :meth:`raise_async_errors`, not
        here). No-op for sync managers."""
        if self._writer is not None:
            self._writer.flush()
            CKPT_ASYNC_QUEUE.set(0)

    def raise_async_errors(self):
        """Re-raise the FIRST background-write failure (once) as
        AsyncCheckpointError on the calling thread."""
        w = self._writer
        err = w.take_error() if w is not None else None
        if err is not None:
            raise AsyncCheckpointError(
                f"background checkpoint write failed: {err}") from err

    def close_writer(self):
        """Flush and stop the background writer (idempotent)."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            CKPT_ASYNC_QUEUE.set(0)

    # ----------------------------------------------------------- validate
    def validate(self, path: str) -> dict:
        """Manifest + per-file SHA-256 validation. Returns the manifest;
        raises CorruptCheckpointError naming the failing entry."""
        man_path = os.path.join(path, "manifest.json")
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CorruptCheckpointError(
                f"{path}: unreadable manifest ({e})") from e
        files = manifest.get("files") or {}
        if "model.zip" not in files:
            raise CorruptCheckpointError(f"{path}: manifest lists no model.zip")
        for fn, digest in files.items():
            fp = os.path.join(path, fn)
            if not os.path.exists(fp):
                raise CorruptCheckpointError(f"{path}: missing file {fn}")
            actual = _sha256_file(fp)
            if actual != digest:
                raise CorruptCheckpointError(
                    f"{path}: checksum mismatch for {fn} (manifest "
                    f"{digest[:12]}..., actual {actual[:12]}...)")
        return manifest

    def latest_valid(self):
        """Newest checkpoint passing validation as (path, manifest), or
        None. Corrupt checkpoints are QUARANTINED (renamed aside) so a
        bad newest write can never shadow a good older one forever."""
        self.flush()    # async writer: never resume past a queued write
        for step, path in reversed(self.checkpoints()):
            try:
                return path, self.validate(path)
            except CorruptCheckpointError as e:
                self._quarantine(path, str(e))
        return None

    def _quarantine(self, path: str, reason: str):
        dst = os.path.join(os.path.dirname(path),
                           "quarantine_" + os.path.basename(path))
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.replace(path, dst)
        QUARANTINED.inc()
        warnings.warn(f"quarantined corrupt checkpoint {path}: {reason}",
                      stacklevel=3)

    # ------------------------------------------------------------ restore
    def valid_at_step(self, step: int):
        """The checkpoint for exactly ``step`` as (path, manifest), or
        None when absent/corrupt (a corrupt one is quarantined). The
        elastic resume barrier restores THE AGREED step — the newest
        local checkpoint may be ahead of what every participant can
        reach."""
        self.flush()
        for s, path in self.checkpoints():
            if s == int(step):
                try:
                    return path, self.validate(path)
                except CorruptCheckpointError as e:
                    self._quarantine(path, str(e))
                return None
        return None

    def restore(self, model, normalizer=None, count_resume: bool = True,
                step: Optional[int] = None):
        """Load the newest valid checkpoint — or, with ``step=``, the
        checkpoint for exactly that step — INTO ``model`` (in place:
        params, layer states, updater state, step/epoch, device clock)
        and return ``{"path", "manifest", "cursor", "extra"}`` — or None
        when no valid checkpoint exists."""
        found = self.latest_valid() if step is None \
            else self.valid_at_step(step)
        if found is None:
            return None
        path, manifest = found
        cfg = self.config
        loaded = retry_io(
            lambda: type(model).load(os.path.join(path, "model.zip"),
                                     load_updater=True),
            cfg.io_retries, cfg.io_backoff)
        model._params = loaded._params
        model._states = loaded._states
        model._opt_state = loaded._opt_state
        model._iteration = loaded._iteration
        model._epoch = loaded._epoch
        model._t_dev = None          # clock rebuilds from _iteration
        extra_payload: dict = {}
        extra_path = os.path.join(path, "extra.json")
        if os.path.exists(extra_path):
            with open(extra_path) as f:
                extra_payload = json.load(f)
        norm_path = os.path.join(path, "normalizer.npz")
        if normalizer is not None and os.path.exists(norm_path):
            try:
                from deeplearning4j_tpu.train.serializer import ModelSerializer
                restored = retry_io(
                    lambda: ModelSerializer.restoreNormalizer(norm_path),
                    cfg.io_retries, cfg.io_backoff)
                for k, v in restored.__dict__.items():
                    setattr(normalizer, k, v)
            except Exception as e:
                warnings.warn(f"resume: could not restore normalizer: {e}",
                              stacklevel=2)
        if count_resume:
            RESUMES.inc()
        return {"path": path, "manifest": manifest,
                "cursor": extra_payload.get("cursor"),
                "extra": extra_payload.get("extra") or {}}


# ------------------------------------------------------------- session
@jax.jit
def _copy_leaves(leaves):
    # + 0 under ONE jit: a real on-device copy per buffer (immune to the
    # compiled step's donation), dispatched as a single program
    return [a + 0 for a in leaves]


def _device_copy(tree):
    """On-device snapshot of a pytree's jax.Array leaves in ONE dispatch
    (a per-leaf ``a + 0`` costs a host dispatch per buffer — ~10ms of
    training-thread time per snapshot on a small MLP, which would eat
    the async writer's entire win)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, a in enumerate(leaves) if isinstance(a, jax.Array)]
    if idx:
        copies = _copy_leaves([leaves[i] for i in idx])
        for i, c in zip(idx, copies):
            leaves[i] = c
    return jax.tree_util.tree_unflatten(treedef, leaves)


class _StateSnapshot:
    """Device-side snapshot of one model's full training state, duck-
    typed for the model classes' ``save()`` (``ModelSerializer.
    writeModel`` / ``ComputationGraph.save`` only touch ``conf``,
    ``_params``/``_states``/``_opt_state``, and the counters). The
    on-device ``a + 0`` copies are enqueued asynchronously and — unlike
    aliases — survive the compiled step's buffer donation; the writer
    thread's ``np.asarray`` pulls block there, off the critical path."""

    def __init__(self, model):
        self._model_cls = type(model)
        self._serial_type = type(model).__name__   # archive meta["type"]
        self.conf = model.conf
        self._params = _device_copy(model._params)
        self._states = _device_copy(model._states)
        self._opt_state = _device_copy(model._opt_state)
        self._iteration = int(model._iteration)
        self._epoch = int(model._epoch)

    def save(self, path: str, save_updater: bool = True):
        self._model_cls.save(self, path, save_updater)


class _AsyncWriter:
    """Bounded-queue background checkpoint writer. ``submit`` blocks
    when the queue is full (backpressure beats unbounded device-memory
    snapshots); the first write failure is parked in ``error`` for
    :meth:`CheckpointManager.raise_async_errors`."""

    _STOP = object()

    def __init__(self, manager: "CheckpointManager", depth: int):
        self.manager = manager
        self.queue: "_queue.Queue" = _queue.Queue(maxsize=max(1, int(depth)))
        self._pending = ErrorLatch()   # writer thread vs fit thread
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dl4j-ckpt-writer")
        self._thread.start()

    def take_error(self) -> Optional[BaseException]:
        """Pop the first unreported write failure (fit-thread side)."""
        return self._pending.take()

    def submit(self, job):
        self.queue.put(job)
        CKPT_ASYNC_QUEUE.set(self.queue.qsize())

    def _loop(self):
        while True:
            job = self.queue.get()
            try:
                if job is self._STOP:
                    return
                snap, status, cursor, normalizer, extra = job
                self.manager._write(snap, status=status, cursor=cursor,
                                    normalizer=normalizer, extra=extra)
            except BaseException as e:
                self._pending.record(e)   # first failure wins
            finally:
                self.queue.task_done()
                CKPT_ASYNC_QUEUE.set(self.queue.qsize())

    def flush(self):
        self.queue.join()

    def close(self):
        if self._thread.is_alive():
            self.queue.put(self._STOP)
            self._thread.join(timeout=30.0)


def _find_preprocessor(it):
    """Walk a wrapper chain (retry/fault/async wrappers all expose
    ``.base``) for the innermost iterator's preprocessor."""
    seen = set()
    while it is not None and id(it) not in seen:
        seen.add(id(it))
        pre = getattr(it, "_pre", None)
        if pre is not None:
            return pre
        it = getattr(it, "base", None)
    return None


class TrainingSession:
    """Per-``fit()`` resilience driver, attached as ``model._resilience``
    for the duration of the fit. The fit loops call four hooks:

    - ``before_step()`` / ``before_dispatch()`` — device-copy snapshot
      of (params, states, opt state) when the NaN policy needs one.
    - ``after_step()`` / ``after_dispatch(losses, k)`` — non-finite
      detection + recovery, periodic checkpoint, preemption poll.
    - ``on_epoch_end()`` — epoch-granularity checkpoints.
    - ``on_preempt()`` — the 'preempted' checkpoint.

    Megastep granularity: with ``steps_per_dispatch=K`` recovery acts on
    the whole K-step dispatch (a poisoned sub-step skips/rolls back all
    K — the dispatch is one atomic compiled program).
    """

    def __init__(self, model, checkpoint: Optional[CheckpointConfig] = None,
                 nan_policy=None, faults=None, iterator=None):
        self.model = model
        self.config = checkpoint
        self.manager = (CheckpointManager(checkpoint, fault_plan=faults)
                        if checkpoint is not None else None)
        if isinstance(nan_policy, NanPolicy):
            nan_policy = NanRecovery(nan_policy)
        self.recovery: Optional[NanRecovery] = nan_policy
        self.faults = faults
        self.iterator = iterator
        self.normalizer = _find_preprocessor(iterator)
        self._signals = []
        self._sig_handler: Optional[SignalPreemption] = None
        if faults is not None:
            sig = faults.preemption_signal()
            if sig is not None:
                self._signals.append(sig)
        self._cursors = deque()
        self._cursor_at_step = None
        self._last_batch_sig = None
        self._snapshot = None
        self._skip_reset = False
        self._next_save = None
        self._good_steps = 0
        self._rollbacks_in_row = 0
        self.resumed = False
        self.preempted = False

    # ----------------------------------------------------------- lifecycle
    def start(self):
        if self.manager is not None:
            self._sig_handler = SignalPreemption()
            if self._sig_handler.install():
                self._signals.append(self._sig_handler)
            else:
                self._sig_handler = None

    def close(self, raise_errors: bool = True):
        """End-of-fit teardown: restore signal handlers, detach from the
        model, and drain the async checkpoint writer. ``raise_errors=
        False`` (used while another exception is already unwinding)
        demotes a writer failure to a warning instead of masking the
        primary error."""
        if self._sig_handler is not None:
            self._sig_handler.uninstall()
            self._sig_handler = None
        if getattr(self.model, "_resilience", None) is self:
            self.model._resilience = None
        if self.manager is not None:
            try:
                self.manager.flush()
                self.manager.raise_async_errors()
            except BaseException as e:
                if raise_errors:
                    raise
                warnings.warn(f"async checkpoint writer failed during "
                              f"teardown: {e}", stacklevel=2)
            finally:
                self.manager.close_writer()

    def resume(self) -> bool:
        """Restore the newest valid checkpoint (when ``resume=True``)
        and seek the data iterator to its saved cursor. Returns True
        when a checkpoint was restored."""
        if self.manager is None or not self.config.resume:
            self._arm_next_save()
            return False
        info = self.manager.restore(self.model, normalizer=self.normalizer)
        if info is None:
            self._arm_next_save()
            return False
        cursor = info.get("cursor")
        if cursor is not None and self.iterator is not None:
            try:
                self.iterator.seek(cursor)
                self._skip_reset = True
            except NotImplementedError:
                warnings.warn(
                    "resume: iterator does not support seek(); replaying "
                    "the interrupted epoch from its start", stacklevel=2)
        # a restore is an out-of-band state mutation the provenance
        # sanitizer's replay window cannot reproduce
        from deeplearning4j_tpu.profiler import sanitizer as _san
        _san.invalidate(self.model)
        res_state = (info.get("extra") or {}).get("resilience") or {}
        lr_scale = res_state.get("lr_scale", 1.0)
        upd = self.model.conf.base.updater
        if lr_scale != getattr(upd, "_lr_scale", 1.0):
            upd._lr_scale = lr_scale
            self._bust_step_caches()
        self._good_steps = int(res_state.get("good_steps", 0))
        lss = res_state.get("loss_scale_state")
        if lss is not None and hasattr(self.model, "_dynamic_scaling") \
                and self.model._dynamic_scaling():
            # the dynamic loss-scale automaton resumes exactly where the
            # checkpoint left it (NOT at the policy's init value)
            self.model._scale_state = jax.numpy.asarray(
                lss, jax.numpy.float32)
        self.resumed = True
        self.restored = info
        logger.info("resumed from %s (step %d, status=%s)", info["path"],
                    self.model._iteration, info["manifest"].get("status"))
        self._arm_next_save()
        return True

    def warm_after_resume(self, steps_per_dispatch: int = 1) -> bool:
        """Kill the resume cold start: when the persistent compile cache
        is configured (nn.compilecache), AOT-warm the train step for the
        batch signature the restored checkpoint recorded — a previously-
        seen (model, shapes, policy) tuple deserializes from disk
        instead of paying the first-dispatch XLA compile. Fit loops call
        this right after ``begin_session`` (they know the dispatch K).
        Best-effort and gated OFF when no cache dir is configured, so
        un-cached fits behave exactly as before."""
        if not self.resumed:
            return False
        from deeplearning4j_tpu.nn import compilecache as _cc
        if _cc.cache_dir() is None:
            return False
        sig = ((self.restored.get("extra") or {}).get("resilience")
               or {}).get("batch_signature")
        return _cc.warm_from_batch_signature(
            self.model, sig, steps_per_dispatch=steps_per_dispatch)

    def _arm_next_save(self):
        if self.manager is not None and self.config.every_steps:
            self._next_save = self.model._iteration + self.config.every_steps

    def consume_skip_reset(self) -> bool:
        """True exactly once after a cursor seek: the first epoch's
        ``reset()`` must not wipe the restored position."""
        if self._skip_reset:
            self._skip_reset = False
            return True
        return False

    # ------------------------------------------------------------- batches
    def wrap_batches(self, stream):
        """Record the iterator cursor as each batch is pulled (pull
        order == apply order, so cursor j is the exact resume point
        after update step j lands), and run non-iterator fault
        injection for array/DataSet-fed fits."""
        it = self.iterator
        plan = self.faults if it is None else None  # iterator path injects
        for ds in stream:                           # inside the wrapper
            if plan is not None and plan._on_pull():
                from deeplearning4j_tpu.faults import _poison
                ds = _poison(ds)
            self._cursors.append(None if it is None else it.cursor())
            if self.manager is not None:
                # recorded into the checkpoint manifest so a resumed
                # process can AOT-warm the train step for this signature
                # (nn.compilecache) before its first dispatch
                from deeplearning4j_tpu.nn.compilecache import describe_batch
                self._last_batch_sig = describe_batch(ds)
            yield ds

    # --------------------------------------------------------------- hooks
    def before_step(self):
        if self.faults is not None:
            # planned layer-params poison (provenance-sanitizer pin):
            # lands BEFORE any recovery snapshot and before the
            # sanitizer's own pre-step snapshot, so both observe it
            self.faults.poison_layer_params(self.model,
                                            self.model._iteration + 1)
        rec = self.recovery
        if rec is not None and rec.policy in (NanPolicy.SKIP_STEP,
                                              NanPolicy.BACKOFF_LR):
            m = self.model
            self._snapshot = (_device_copy(m._params),
                              _device_copy(m._states),
                              _device_copy(m._opt_state))

    before_dispatch = before_step

    def after_step(self):
        self._after(1, self.model._score)

    def after_dispatch(self, losses, steps: int, pulls: int = None):
        """``steps`` update steps landed in one dispatch. ``pulls`` is
        how many BATCH PULLS they consumed — equal to ``steps`` for
        megasteps (K batches -> K steps, the default) but 1 for a TBPTT
        batch (1 batch -> ceil(T/L) segment steps), so the cursor queue
        stays aligned with the iterator."""
        self._after(steps, losses, pulls)

    def _after(self, k: int, losses, pulls: int = None):
        for _ in range(min(k if pulls is None else pulls,
                           len(self._cursors))):
            self._cursor_at_step = self._cursors.popleft()
        if self.manager is not None:
            # a background write that failed must surface HERE, on the
            # training thread, not rot silently in the writer
            self.manager.raise_async_errors()
        if self.recovery is not None:
            vals = np.asarray(jax.device_get(losses))
            bad = int(vals.size - np.count_nonzero(np.isfinite(vals)))
            if bad:
                self._handle_nonfinite(k, bad)
            else:
                self._snapshot = None
                self._rollbacks_in_row = 0
                self._recover_lr(k)
        else:
            self._snapshot = None
        m = self.model
        if self._next_save is not None and m._iteration >= self._next_save:
            self.checkpoint()
        if any(s.requested(m._iteration) for s in self._signals):
            raise PreemptionRequested(m._iteration)

    def on_epoch_end(self):
        # an epoch-boundary checkpoint must resume at the START of the
        # next epoch: the last step's cursor points at the exhausted end
        # of the finished epoch, and seeking there on resume would make
        # the first resumed epoch iterate zero batches (silently losing
        # one epoch of training)
        self._cursor_at_step = None
        self._cursors.clear()
        if (self.manager is not None and self.config.every_epochs
                and self.model._epoch % self.config.every_epochs == 0):
            self.checkpoint()

    def on_preempt(self):
        """A PreemptionSignal fired: record it and write the 'preempted'
        checkpoint — the in-flight (mega)step already completed because
        signals are only polled at dispatch boundaries."""
        self.preempted = True
        self.model._preempted = True
        PREEMPTIONS.inc()
        if self.manager is not None:
            self.checkpoint(status="preempted")

    # --------------------------------------------------------- checkpoints
    def checkpoint(self, status: str = "complete"):
        if self.manager is None:
            return None
        # the BACKOFF_LR recovery state is training state too: a resume
        # that silently restored full LR mid-backoff would re-trip the
        # very instability the backoff was suppressing. Likewise the
        # dynamic loss-scale automaton (nn.precision): resuming at the
        # policy's init scale mid-backoff would replay the overflows.
        upd = self.model.conf.base.updater
        res_extra = {
            "lr_scale": float(getattr(upd, "_lr_scale", 1.0)),
            "good_steps": int(self._good_steps),
            "batch_signature": self._last_batch_sig}
        scale_state = getattr(self.model, "_scale_state", None)
        if scale_state is not None:
            res_extra["loss_scale_state"] = [
                float(v) for v in np.asarray(jax.device_get(scale_state))]
        extra = {"resilience": res_extra}
        path = self.manager.save(
            self.model, status=status, cursor=self._cursor_at_step,
            normalizer=self.normalizer, extra=extra)
        if self.config.every_steps:
            self._next_save = self.model._iteration + self.config.every_steps
        return path

    # ---------------------------------------------------------- nonfinite
    def _restore_snapshot(self):
        if self._snapshot is None:
            return
        m = self.model
        m._params, m._states, m._opt_state = self._snapshot
        self._snapshot = None

    def _bust_step_caches(self):
        """An LR-scale change is baked into the compiled step at trace
        time — clear the per-model program caches so the next dispatch
        recompiles with the new scale."""
        m = self.model
        for attr in ("_train_step_cache", "_megastep_cache",
                     "_tbptt_step_cache"):
            cache = getattr(m, attr, None)
            if cache is not None:
                cache.clear()

    def _recover_lr(self, k: int):
        rec = self.recovery
        if rec.policy is not NanPolicy.BACKOFF_LR:
            return
        upd = self.model.conf.base.updater
        scale = getattr(upd, "_lr_scale", 1.0)
        if scale >= 1.0:
            return
        self._good_steps += k
        if self._good_steps >= rec.cooldown_steps:
            upd._lr_scale = min(scale / rec.backoff_factor, 1.0)
            self._good_steps = 0
            self._bust_step_caches()
            logger.info("BACKOFF_LR cooldown elapsed: lr scale %.2g -> %.2g",
                        scale, upd._lr_scale)

    def _handle_nonfinite(self, k: int, bad: int):
        NONFINITE_STEPS.inc(bad)
        rec = self.recovery
        m = self.model
        where = f"iteration {m._iteration}" if k == 1 else \
            f"iterations {m._iteration - k + 1}..{m._iteration} " \
            f"({bad} non-finite)"
        if rec.policy is NanPolicy.RAISE:
            raise NumericsPanicError(
                f"non-finite loss at {where} (NanPolicy.RAISE)")
        if rec.policy is NanPolicy.SKIP_STEP:
            self._restore_snapshot()
            logger.warning("non-finite loss at %s: update skipped "
                           "(NanPolicy.SKIP_STEP)", where)
            return
        if rec.policy is NanPolicy.BACKOFF_LR:
            self._restore_snapshot()
            upd = m.conf.base.updater
            scale = getattr(upd, "_lr_scale", 1.0) * rec.backoff_factor
            if scale < rec.min_scale:
                raise NumericsPanicError(
                    f"non-finite loss at {where}: BACKOFF_LR reached the "
                    f"lr-scale floor ({rec.min_scale:g}) — training cannot "
                    "make progress")
            upd._lr_scale = scale
            LR_BACKOFFS.inc()
            self._good_steps = 0
            self._bust_step_caches()
            logger.warning("non-finite loss at %s: update skipped, lr scale "
                           "-> %.2g (NanPolicy.BACKOFF_LR)", where, scale)
            return
        # ROLLBACK
        if self.manager is None:
            raise NumericsPanicError(
                f"non-finite loss at {where}: NanPolicy.ROLLBACK requires a "
                "CheckpointConfig (no checkpoint to restore)")
        self._rollbacks_in_row += 1
        if self._rollbacks_in_row > rec.max_rollbacks:
            raise NumericsPanicError(
                f"non-finite loss at {where}: {rec.max_rollbacks} "
                "consecutive rollbacks without a clean step — giving up")
        info = self.manager.restore(m, normalizer=self.normalizer,
                                    count_resume=False)
        if info is None:
            raise NumericsPanicError(
                f"non-finite loss at {where}: NanPolicy.ROLLBACK found no "
                "valid checkpoint to restore")
        self._snapshot = None
        ROLLBACKS.inc()
        logger.warning("non-finite loss at %s: rolled back to %s "
                       "(NanPolicy.ROLLBACK)", where, info["path"])


def epoch_target(session: Optional["TrainingSession"], model,
                 epochs: int) -> int:
    """Absolute epoch index a fit should run to: ``epochs`` counts from
    zero for a RESUMED session (the restored checkpoint already banked
    ``model._epoch`` of them) and from the model's current epoch
    otherwise. One definition, shared by :func:`fit_scope` and the
    elastic driver's shrink-retry loop, so the accounting cannot
    drift."""
    if session is not None and session.resumed:
        return epochs
    return model._epoch + epochs


@contextmanager
def fit_scope(session: Optional["TrainingSession"], model, epochs: int):
    """The shared resilience envelope around a fit's epoch loop: yields
    the number of epochs left to run (``epochs`` minus epochs already
    completed by a resumed checkpoint), converts a PreemptionRequested
    unwind into the 'preempted' checkpoint + clean return, and closes
    the session (restoring signal handlers) on every exit path. Used by
    MultiLayerNetwork.fit, ComputationGraph.fit, and ParallelWrapper.fit
    so the recovery protocol cannot drift between the three loops."""
    from deeplearning4j_tpu.profiler import flightrec as _flightrec
    from deeplearning4j_tpu.profiler import tracecontext as _tracectx
    n_epochs = max(epoch_target(session, model, epochs) - model._epoch, 0)
    try:
        # the run's root span: its trace_id doubles as the run_id, and
        # every step/op span recorded inside the fit inherits it via the
        # ambient context — how a training dispatch correlates with the
        # run that issued it
        with _tracectx.run_span("train:run",
                                model=type(model).__name__,
                                epochs=n_epochs):
            yield n_epochs
    except PreemptionRequested:
        if session is None:
            raise
        session.on_preempt()
    except BaseException as e:
        # any other crash unwinding a fit — NonfiniteAttributionError,
        # a dead-device dispatch, an OOM — triggers the flight recorder
        # while the evidence (recent spans, metric state, dispatch
        # signatures) is still in the ring
        _flightrec.get_flight_recorder().dump(
            f"fit:{type(e).__name__}", exc=e)
        raise
    finally:
        if session is not None:
            # surface a failed async checkpoint write at fit exit — unless
            # another exception is already unwinding (don't mask it)
            session.close(raise_errors=sys.exc_info()[1] is None)


def begin_session(model, data, checkpoint=None, nan_policy=None, faults=None):
    """Build and start a TrainingSession for one ``fit()``:

    - wraps a DataSetIterator source with the fault-injection iterator
      (when a FaultPlan is given) and the transient-error retry wrapper,
    - attaches the session as ``model._resilience``,
    - installs the signal handler and performs auto-resume.

    Returns ``(session, data)`` where ``data`` is the possibly-wrapped
    iterator the fit loop should consume instead of the original.
    """
    iterator = data if isinstance(data, DataSetIterator) else None
    wrapped = data
    if iterator is not None:
        from deeplearning4j_tpu.data.dataset import AsyncDataSetIterator
        if checkpoint is not None and isinstance(iterator,
                                                 AsyncDataSetIterator):
            # the async worker pulls ahead of the applied step, so
            # cursor() overstates position by up to prefetch+1 batches —
            # a resumed fit would silently skip those batches
            warnings.warn(
                "checkpointing with an AsyncDataSetIterator source: resume "
                "cursors are APPROXIMATE (the prefetch worker runs ahead of "
                "the applied step). Pass the un-wrapped iterator for exact "
                "resume; fit() overlaps host prep via its own prefetch "
                "paths.", stacklevel=3)
        if faults is not None:
            wrapped = faults.wrap_iterator(wrapped)
        retries = checkpoint.io_retries if checkpoint is not None else 3
        backoff = checkpoint.io_backoff if checkpoint is not None else 0.05
        wrapped = RetryingDataSetIterator(wrapped, max_retries=retries,
                                          backoff=backoff)
    session = TrainingSession(
        model, checkpoint=checkpoint, nan_policy=nan_policy, faults=faults,
        iterator=wrapped if iterator is not None else None)
    model._resilience = session
    session.start()
    try:
        session.resume()
    except BaseException:
        # a failed restore must not leak the installed signal handlers or
        # leave a dead session attached to the model
        session.close()
        raise
    return session, wrapped


# ------------------------------------------------- lifecycle driver state
class DriverStateStore:
    """Atomic, checksummed persistence for the lifecycle driver's state
    machine (ISSUE 20) — the same durability contract as a training
    checkpoint, scaled down to one JSON document: a crash mid-write can
    never leave a half-state under the real name (temp file + one
    ``os.replace``), every load verifies a SHA-256 over the canonical
    payload, and a corrupt file is QUARANTINED (renamed aside) instead
    of trusted, so a resumed driver starts from "no state" rather than
    from garbage. Writes ride :func:`retry_io`.

    The driver persists at every phase transition, so after a SIGKILL
    the successor knows exactly which round/phase/candidate was in
    flight and whether a canary must be aborted before continuing.
    """

    FILENAME = "lifecycle_driver_state.json"

    def __init__(self, state_dir: str, io_retries: int = 3,
                 io_backoff: float = 0.05):
        self.dir = state_dir
        self.path = os.path.join(state_dir, self.FILENAME)
        self._retries = int(io_retries)
        self._backoff = float(io_backoff)
        os.makedirs(state_dir, exist_ok=True)

    @staticmethod
    def _digest(state: dict) -> str:
        canon = json.dumps(state, sort_keys=True,
                           separators=(",", ":")).encode()
        return hashlib.sha256(canon).hexdigest()

    def save(self, state: dict) -> None:
        """Persist ``state`` atomically (JSON-serializable values only)."""
        doc = {"state": state, "sha256": self._digest(state)}
        tmp = self.path + ".tmp"

        def write():
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

        retry_io(write, retries=self._retries, backoff=self._backoff)

    def load(self) -> Optional[dict]:
        """The last saved state, or None (no state yet, or the file was
        corrupt — in which case it has been quarantined and counted in
        ``dl4j_checkpoint_quarantined_total``)."""
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path) as f:
                doc = json.load(f)
            state = doc["state"]
            if self._digest(state) != doc["sha256"]:
                raise CorruptCheckpointError(
                    f"driver state {self.path}: checksum mismatch")
            return state
        except (OSError, ValueError, KeyError, TypeError,
                CorruptCheckpointError) as e:
            quarantine = os.path.join(
                self.dir, "quarantine_" + self.FILENAME)
            try:
                os.replace(self.path, quarantine)
            except OSError:
                pass
            QUARANTINED.inc()
            logger.warning(
                "driver state %s failed validation (%s) — quarantined to "
                "%s; the driver resumes stateless", self.path, e, quarantine)
            return None

    def clear(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass
