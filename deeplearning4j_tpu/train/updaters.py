"""Updaters (optimizers) — the full ND4J updater surface.

Reference parity: ``org.nd4j.linalg.learning.config.{Sgd, Adam, AdamW,
Nesterovs, RmsProp, AdaGrad, AdaDelta, AdaMax, AMSGrad, Nadam, NoOp}`` and
the paired ``org.nd4j.linalg.learning.*Updater`` state machines
(SURVEY.md §2.2 "Training infra"). Same update math, same defaults.

TPU-native: each updater is a pure ``(grad, state, lr, t) -> (update,
state')`` function over pytrees — the whole optimizer step fuses into the
compiled train step (the reference mutates flat state vectors op-by-op
through JNI). The returned ``update`` is SUBTRACTED from params, matching
the reference's ``params.subi(update)`` contract (SURVEY.md §3.1).

State is a dict of arrays shaped like the param — checkpointable exactly
like the reference's updater-state binary (ModelSerializer parity).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.train.schedules import ISchedule, resolve

Update = Any
State = Dict[str, Any]


class IUpdater:
    """Config object; ``init_state(param)`` + ``apply(grad, state, lr, t)``.

    ``lr`` resolves through a schedule at trace time; ``t`` is the traced
    iteration counter.
    """

    #: default learning rate if none given (mirrors each ref config's default)
    DEFAULT_LR = 0.001
    has_state = True

    def __init__(self, learning_rate=None):
        self.learning_rate = resolve(self.DEFAULT_LR if learning_rate is None else learning_rate)

    def lr_at(self, t, epoch=0):
        # _lr_scale is the NanPolicy.BACKOFF_LR recovery knob
        # (train.resilience): baked into the compiled step at trace time,
        # so the resilience layer busts the step caches when it changes
        lr = self.learning_rate.valueAt(t, epoch)
        scale = getattr(self, "_lr_scale", 1.0)
        return lr if scale == 1.0 else lr * scale

    def init_state(self, param) -> State:
        return {}

    def apply(self, grad, state: State, lr, t) -> Tuple[Update, State]:
        raise NotImplementedError

    # -- config (de)serialization, ModelSerializer parity --
    def to_config(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            d[k] = v.to_config() if isinstance(v, ISchedule) else v
        return d

    @staticmethod
    def from_config(d):
        d = dict(d)
        cls = UPDATERS[d.pop("@class")]
        obj = cls.__new__(cls)
        for k, v in d.items():
            if k == "learning_rate" and isinstance(v, dict):
                v = ISchedule.from_config(v)
            setattr(obj, k, v)
        return obj

    def __repr__(self):
        return f"{type(self).__name__}({self.__dict__})"


class Sgd(IUpdater):
    """update = lr * g (ref: SgdUpdater)."""

    DEFAULT_LR = 0.1
    has_state = False

    def apply(self, grad, state, lr, t):
        return lr * grad, state


class NoOp(IUpdater):
    """Frozen params (ref: NoOpUpdater)."""

    has_state = False

    def __init__(self, learning_rate=None):
        super().__init__(0.0)

    def apply(self, grad, state, lr, t):
        return jnp.zeros_like(grad), state


class Adam(IUpdater):
    """ref: AdamUpdater — alpha_t = lr*sqrt(1-b2^t)/(1-b1^t)."""

    DEFAULT_LR = 0.001

    def __init__(self, learning_rate=None, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        t1 = t + 1
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * jnp.square(grad)
        alpha = lr * jnp.sqrt(1 - self.beta2 ** t1) / (1 - self.beta1 ** t1)
        update = alpha * m / (jnp.sqrt(v) + self.epsilon)
        return update, {"m": m, "v": v}


class AdamW(Adam):
    """Adam + decoupled weight decay (ref: AdamW/... config). The decay
    term is added by the trainer via ``weight_decay_update`` because it
    needs the param value."""

    def __init__(self, learning_rate=None, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.0):
        super().__init__(learning_rate, beta1, beta2, epsilon)
        self.weight_decay = weight_decay

    def weight_decay_update(self, param, lr):
        return lr * self.weight_decay * param


class AMSGrad(Adam):
    """ref: AMSGradUpdater — keeps max of v."""

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param),
                "vhat": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        t1 = t + 1
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * jnp.square(grad)
        vhat = jnp.maximum(state["vhat"], v)
        alpha = lr * jnp.sqrt(1 - self.beta2 ** t1) / (1 - self.beta1 ** t1)
        update = alpha * m / (jnp.sqrt(vhat) + self.epsilon)
        return update, {"m": m, "v": v, "vhat": vhat}


class AdaMax(Adam):
    """ref: AdaMaxUpdater — infinity-norm variant."""

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        t1 = t + 1
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        update = (lr / (1 - self.beta1 ** t1)) * m / (u + self.epsilon)
        return update, {"m": m, "u": u}


class Nadam(Adam):
    """ref: NadamUpdater — Nesterov-accelerated Adam."""

    def apply(self, grad, state, lr, t):
        t1 = t + 1
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * jnp.square(grad)
        m_hat = m / (1 - self.beta1 ** t1)
        v_hat = v / (1 - self.beta2 ** t1)
        update = lr * (self.beta1 * m_hat + (1 - self.beta1) * grad / (1 - self.beta1 ** t1)) \
            / (jnp.sqrt(v_hat) + self.epsilon)
        return update, {"m": m, "v": v}


class Nesterovs(IUpdater):
    """ref: NesterovsUpdater (Bengio formulation):
    v' = mu*v - lr*g; applied step = mu²*v - (1+mu)*lr*g."""

    DEFAULT_LR = 0.1

    def __init__(self, learning_rate=None, momentum: float = 0.9):
        super().__init__(learning_rate)
        self.momentum = momentum

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        mu = self.momentum
        v_new = mu * state["v"] - lr * grad
        update = -(mu * v_new - lr * grad)  # params -= update → += mu²v - (1+mu)lr g
        return update, {"v": v_new}


class RmsProp(IUpdater):
    """ref: RmsPropUpdater."""

    DEFAULT_LR = 0.1

    def __init__(self, learning_rate=None, rms_decay: float = 0.95,
                 epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.rms_decay, self.epsilon = rms_decay, epsilon

    def init_state(self, param):
        return {"g2": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        g2 = self.rms_decay * state["g2"] + (1 - self.rms_decay) * jnp.square(grad)
        update = lr * grad / (jnp.sqrt(g2) + self.epsilon)
        return update, {"g2": g2}


class AdaGrad(IUpdater):
    """ref: AdaGradUpdater."""

    DEFAULT_LR = 0.1

    def __init__(self, learning_rate=None, epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.epsilon = epsilon

    def init_state(self, param):
        return {"h": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        h = state["h"] + jnp.square(grad)
        update = lr * grad / (jnp.sqrt(h) + self.epsilon)
        return update, {"h": h}


class AdaDelta(IUpdater):
    """ref: AdaDeltaUpdater — LR-free."""

    has_state = True

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        super().__init__(1.0)
        self.rho, self.epsilon = rho, epsilon

    def init_state(self, param):
        return {"Eg2": jnp.zeros_like(param), "Ex2": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, t):
        rho, eps = self.rho, self.epsilon
        Eg2 = rho * state["Eg2"] + (1 - rho) * jnp.square(grad)
        update = grad * jnp.sqrt(state["Ex2"] + eps) / jnp.sqrt(Eg2 + eps)
        Ex2 = rho * state["Ex2"] + (1 - rho) * jnp.square(update)
        return update, {"Eg2": Eg2, "Ex2": Ex2}


UPDATERS = {c.__name__: c for c in
            [Sgd, NoOp, Adam, AdamW, AMSGrad, AdaMax, Nadam, Nesterovs,
             RmsProp, AdaGrad, AdaDelta]}


# ---------------------------------------------------------------- gradient ops
def clip_by_value(grads, clip: float):
    """ref: GradientNormalization.ClipElementWiseAbsoluteValue."""
    return jax.tree_util.tree_map(lambda g: jnp.clip(g, -clip, clip), grads)


def clip_by_norm(grads, max_norm: float):
    """Per-tensor L2 clip (ref: ClipL2PerLayer/PerParamType)."""
    def clip(g):
        n = jnp.sqrt(jnp.sum(jnp.square(g)))
        return g * jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))
    return jax.tree_util.tree_map(clip, grads)


def clip_by_global_norm(grads, max_norm: float):
    """Global-norm clip over the whole gradient pytree."""
    leaves = jax.tree_util.tree_leaves(grads)
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gn, 1e-12))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def renormalize_l2(grads):
    """ref: GradientNormalization.RenormalizeL2PerLayer — divide by norm."""
    def renorm(g):
        n = jnp.sqrt(jnp.sum(jnp.square(g)))
        return g / jnp.maximum(n, 1e-12)
    return jax.tree_util.tree_map(renorm, grads)


def apply_regularization(param, grad, l1: float = 0.0, l2: float = 0.0):
    """ref semantics: L1/L2 fold into the gradient BEFORE the updater
    (SURVEY.md §3.1 'gradient clipping/L2 → updater math')."""
    if l2 > 0:
        grad = grad + l2 * param
    if l1 > 0:
        grad = grad + l1 * jnp.sign(param)
    return grad
