"""Model checkpointing — ModelSerializer equivalent.

Reference parity: ``org.deeplearning4j.util.ModelSerializer`` — a zip
container with config JSON + params + **updater state** so optimizer-exact
resume works (SURVEY.md §5 "Checkpoint / resume"), plus normalizer
serialization (``NormalizerSerializer``).

Format: zip{conf.json, arrays.npz} where arrays.npz holds per-layer params
(``p{i}::name``), layer states (``s{i}::name``), flattened updater-state
leaves (``u::{j}``), and counters. Arrays are saved as numpy — portable,
no pickle.

Crash-safety (ISSUE 5): every write goes to a temp file in the target
directory finalized by ONE ``os.replace`` — a crash mid-write can never
leave a truncated, unloadable archive under the real name. Every restore
failure surfaces as a structured :class:`CorruptModelError` naming the
missing/bad entry instead of a raw ``KeyError``/``BadZipFile``.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from contextlib import contextmanager
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


class CorruptModelError(Exception):
    """A model/normalizer archive failed to restore: truncated zip,
    missing entry, CRC mismatch, or unparseable metadata. ``entry``
    names the offending archive member (None for container-level
    damage)."""

    def __init__(self, path: str, entry, detail: str):
        self.path = path
        self.entry = entry
        where = f"{path}[{entry}]" if entry else path
        super().__init__(f"corrupt model archive {where}: {detail}")


@contextmanager
def atomic_write(path: str):
    """Yield a temp path in ``path``'s directory; on clean exit,
    ``os.replace`` it over ``path`` (atomic on POSIX — readers see the
    old file or the new file, never a partial one). On error the temp
    file is removed and the original is untouched."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        yield tmp
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise


def write_model_zip(path: str, conf_json: str, meta: dict,
                    arrays: Dict[str, np.ndarray]) -> None:
    """Shared atomic writer for the model-archive format (used by
    ModelSerializer.writeModel and ComputationGraph.save)."""
    with atomic_write(path) as tmp:
        with zipfile.ZipFile(tmp, "w") as z:
            z.writestr("conf.json", conf_json)
            z.writestr("meta.json", json.dumps(meta))
            buf = io.BytesIO()
            np.savez(buf, **arrays) if arrays else np.savez(
                buf, __empty__=np.zeros(1))
            z.writestr("arrays.npz", buf.getvalue())


def read_model_zip(path: str):
    """Shared validating reader: returns (conf_json_str, meta_dict,
    npz_arrays), raising CorruptModelError naming the bad entry."""
    try:
        z = zipfile.ZipFile(path)
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, OSError) as e:
        raise CorruptModelError(path, None,
                                f"not a readable zip ({e})") from e
    with z:
        names = set(z.namelist())
        for req in ("conf.json", "meta.json", "arrays.npz"):
            if req not in names:
                raise CorruptModelError(path, req, "entry missing")
        try:
            bad = z.testzip()
        except (zipfile.BadZipFile, OSError) as e:
            raise CorruptModelError(path, None,
                                    f"CRC scan failed ({e})") from e
        if bad is not None:
            raise CorruptModelError(path, bad, "CRC mismatch (truncated or "
                                    "bit-flipped write)")
        conf_json = z.read("conf.json").decode()
        try:
            meta = json.loads(z.read("meta.json"))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CorruptModelError(path, "meta.json",
                                    f"unparseable ({e})") from e
        try:
            arrays = np.load(io.BytesIO(z.read("arrays.npz")))
        except (ValueError, OSError) as e:
            raise CorruptModelError(path, "arrays.npz",
                                    f"unloadable npz ({e})") from e
    return conf_json, meta, arrays


def require_array(arrays, key: str, path: str):
    """Fetch one npz member, raising CorruptModelError (not KeyError)
    when the archive lacks it."""
    if key not in arrays.files:
        raise CorruptModelError(path, f"arrays.npz::{key}", "entry missing")
    return arrays[key]


class ModelSerializer:
    @staticmethod
    def writeModel(model, path: str, save_updater: bool = True):
        conf_json = model.conf.to_json()
        # _serial_type: snapshot proxies (resilience._StateSnapshot) name
        # the REAL model class so async and sync archives are identical
        meta = {"type": getattr(model, "_serial_type", type(model).__name__),
                "iteration": model._iteration,
                "epoch": model._epoch, "save_updater": bool(save_updater and
                                                           model._opt_state is not None)}
        arrays: Dict[str, np.ndarray] = {}
        for i, p in enumerate(model._params):
            for name, arr in p.items():
                arrays[f"p{i}::{name}"] = np.asarray(arr)
        for i, s in enumerate(model._states):
            for name, arr in s.items():
                arrays[f"s{i}::{name}"] = np.asarray(arr)
        if meta["save_updater"]:
            leaves, treedef = jax.tree_util.tree_flatten(model._opt_state)
            for j, leaf in enumerate(leaves):
                arrays[f"u::{j}"] = np.asarray(leaf)
        write_model_zip(path, conf_json, meta, arrays)

    @staticmethod
    def restoreMultiLayerNetwork(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf_json, meta, arrays = read_model_zip(path)
        try:
            conf = MultiLayerConfiguration.from_json(conf_json)
        except Exception as e:
            raise CorruptModelError(path, "conf.json",
                                    f"unparseable configuration ({e})") from e
        net = MultiLayerNetwork(conf)
        net.init()
        for k in arrays.files:
            if k == "__empty__":
                continue
            kind, _, name = k.partition("::")
            if kind.startswith("p") and kind != "p":
                net._params[int(kind[1:])][name] = jnp.asarray(arrays[k])
            elif kind.startswith("s") and kind != "s":
                net._states[int(kind[1:])][name] = jnp.asarray(arrays[k])
        net._iteration = meta["iteration"]
        net._epoch = meta["epoch"]
        if load_updater and meta.get("save_updater"):
            net._ensure_opt_state()
            leaves, treedef = jax.tree_util.tree_flatten(net._opt_state)
            new_leaves = [jnp.asarray(require_array(arrays, f"u::{j}", path))
                          for j in range(len(leaves))]
            net._opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net

    # normalizer (ref: NormalizerSerializer)
    @staticmethod
    def writeNormalizer(norm, path: str):
        state = norm.state() if hasattr(norm, "state") else norm.__dict__
        with atomic_write(path) as tmp:
            # write through a file object: np.savez(path) appends ".npz"
            # to extension-less paths, which would break the final replace
            with open(tmp, "wb") as f:
                np.savez(f, __class__=np.asarray(type(norm).__name__),
                         **{k: np.asarray(v) for k, v in state.items()
                            if v is not None})

    @staticmethod
    def restoreNormalizer(path: str):
        from deeplearning4j_tpu.data import dataset as D
        try:
            data = np.load(path, allow_pickle=False)
        except FileNotFoundError:
            raise
        except (ValueError, OSError) as e:
            raise CorruptModelError(path, None,
                                    f"unloadable normalizer npz ({e})") from e
        if "__class__" not in data.files:
            raise CorruptModelError(path, "__class__", "entry missing")
        cls = getattr(D, str(data["__class__"]), None)
        if cls is None:
            raise CorruptModelError(path, "__class__",
                                    f"unknown normalizer class "
                                    f"{data['__class__']!r}")
        norm = cls()
        for k in data.files:
            if k != "__class__":
                setattr(norm, k, data[k])
        return norm
