"""Model checkpointing — ModelSerializer equivalent.

Reference parity: ``org.deeplearning4j.util.ModelSerializer`` — a zip
container with config JSON + params + **updater state** so optimizer-exact
resume works (SURVEY.md §5 "Checkpoint / resume"), plus normalizer
serialization (``NormalizerSerializer``).

Format: zip{conf.json, arrays.npz} where arrays.npz holds per-layer params
(``p{i}::name``), layer states (``s{i}::name``), flattened updater-state
leaves (``u::{j}``), and counters. Arrays are saved as numpy — portable,
no pickle.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


class ModelSerializer:
    @staticmethod
    def writeModel(model, path: str, save_updater: bool = True):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf_json = model.conf.to_json()
        meta = {"type": type(model).__name__, "iteration": model._iteration,
                "epoch": model._epoch, "save_updater": bool(save_updater and
                                                           model._opt_state is not None)}
        arrays: Dict[str, np.ndarray] = {}
        for i, p in enumerate(model._params):
            for name, arr in p.items():
                arrays[f"p{i}::{name}"] = np.asarray(arr)
        for i, s in enumerate(model._states):
            for name, arr in s.items():
                arrays[f"s{i}::{name}"] = np.asarray(arr)
        if meta["save_updater"]:
            leaves, treedef = jax.tree_util.tree_flatten(model._opt_state)
            for j, leaf in enumerate(leaves):
                arrays[f"u::{j}"] = np.asarray(leaf)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("conf.json", conf_json)
            z.writestr("meta.json", json.dumps(meta))
            buf = io.BytesIO()
            np.savez(buf, **arrays) if arrays else np.savez(buf, __empty__=np.zeros(1))
            z.writestr("arrays.npz", buf.getvalue())

    @staticmethod
    def restoreMultiLayerNetwork(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        with zipfile.ZipFile(path) as z:
            conf = MultiLayerConfiguration.from_json(z.read("conf.json").decode())
            meta = json.loads(z.read("meta.json"))
            arrays = np.load(io.BytesIO(z.read("arrays.npz")))
        net = MultiLayerNetwork(conf)
        net.init()
        for k in arrays.files:
            if k == "__empty__":
                continue
            kind, _, name = k.partition("::")
            if kind.startswith("p"):
                net._params[int(kind[1:])][name] = jnp.asarray(arrays[k])
            elif kind.startswith("s") and kind != "s":
                net._states[int(kind[1:])][name] = jnp.asarray(arrays[k])
        net._iteration = meta["iteration"]
        net._epoch = meta["epoch"]
        if load_updater and meta.get("save_updater"):
            net._ensure_opt_state()
            leaves, treedef = jax.tree_util.tree_flatten(net._opt_state)
            new_leaves = [jnp.asarray(arrays[f"u::{j}"]) for j in range(len(leaves))]
            net._opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net

    # normalizer (ref: NormalizerSerializer)
    @staticmethod
    def writeNormalizer(norm, path: str):
        state = norm.state() if hasattr(norm, "state") else norm.__dict__
        np.savez(path, __class__=np.asarray(type(norm).__name__),
                 **{k: np.asarray(v) for k, v in state.items() if v is not None})

    @staticmethod
    def restoreNormalizer(path: str):
        from deeplearning4j_tpu.data import dataset as D
        data = np.load(path, allow_pickle=False)
        cls = getattr(D, str(data["__class__"]))
        norm = cls()
        for k in data.files:
            if k != "__class__":
                setattr(norm, k, data[k])
        return norm
