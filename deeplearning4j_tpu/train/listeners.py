"""Training listeners — the observability seam of the training loop.

Reference parity: ``org.deeplearning4j.optimize.api.TrainingListener`` and
``listeners.{ScoreIterationListener, PerformanceListener,
CheckpointListener, EvaluativeListener, TimeIterationListener}``
(SURVEY.md §2.2 "Optimize/solvers", §5 "Metrics / logging": the listener
bus is the single observability seam — score, eval, checkpoints, UI stats
all hang off it).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Listener protocol (ref: TrainingListener)."""

    def iterationDone(self, model, iteration: int, epoch: int):
        pass

    def onEpochEnd(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ref: ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, out: Callable = None):
        self.n = print_iterations
        self.out = out or (lambda msg: logger.info(msg))
        self.history: List[float] = []

    def iterationDone(self, model, iteration, epoch):
        score = model.score()
        self.history.append(score)
        if iteration % self.n == 0:
            self.out(f"Score at iteration {iteration} is {score}")


class PerformanceListener(TrainingListener):
    """Throughput/timing (ref: PerformanceListener: samples/sec, batches/sec)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True,
                 out: Callable = None):
        self.frequency = frequency
        self.report_batch = report_batch
        self.out = out or (lambda msg: logger.info(msg))
        self._last_time = None
        self._last_iter = 0
        self._samples = 0
        self.samples_per_sec: Optional[float] = None

    def iterationDone(self, model, iteration, epoch):
        now = time.time()
        self._samples += getattr(model, "_last_batch_size", 0)
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0:
                self.samples_per_sec = self._samples / dt
                msg = f"iter {iteration}: {iters / dt:.1f} iterations/sec"
                if self.report_batch and self._samples:
                    msg += f", {self.samples_per_sec:.1f} samples/sec"
                self.out(msg)
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class TimeIterationListener(TrainingListener):
    """ETA logging (ref: TimeIterationListener)."""

    def __init__(self, total_iterations: int, out: Callable = None):
        self.total = total_iterations
        self.start = time.time()
        self.out = out or (lambda msg: logger.info(msg))

    def iterationDone(self, model, iteration, epoch):
        elapsed = time.time() - self.start
        if iteration > 0:
            remaining = elapsed / iteration * (self.total - iteration)
            self.out(f"iter {iteration}/{self.total}, ETA {remaining:.0f}s")


class CheckpointListener(TrainingListener):
    """Periodic checkpoints, keep-last-K rotation (ref: CheckpointListener)."""

    def __init__(self, directory: str, save_every_n_iterations: int = None,
                 save_every_n_epochs: int = None, keep_last: int = 3):
        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path, save_updater=True)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iterationDone(self, model, iteration, epoch):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def onEpochEnd(self, model):
        if self.every_epoch and model.getEpochCount() % self.every_epoch == 0:
            self._save(model, f"epoch_{model.getEpochCount()}")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (ref: EvaluativeListener)."""

    def __init__(self, iterator, frequency: int, evaluation_factory=None,
                 out: Callable = None):
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation
        self.iterator = iterator
        self.frequency = frequency
        self.factory = evaluation_factory or Evaluation
        self.out = out or (lambda msg: logger.info(msg))
        self.last_evaluation = None

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            ev = model.evaluate(self.iterator, self.factory())
            self.last_evaluation = ev
            self.out(f"iter {iteration}: accuracy={ev.accuracy():.4f}")
