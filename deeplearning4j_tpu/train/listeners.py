"""Training listeners — the observability seam of the training loop.

Reference parity: ``org.deeplearning4j.optimize.api.TrainingListener`` and
``listeners.{ScoreIterationListener, PerformanceListener,
CheckpointListener, EvaluativeListener, TimeIterationListener}``
(SURVEY.md §2.2 "Optimize/solvers", §5 "Metrics / logging": the listener
bus is the single observability seam — score, eval, checkpoints, UI stats
all hang off it).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Listener protocol (ref: TrainingListener)."""

    def iterationDone(self, model, iteration: int, epoch: int):
        pass

    def onEpochEnd(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ref: ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, out: Callable = None):
        self.n = print_iterations
        self.out = out or (lambda msg: logger.info(msg))
        self.history: List[float] = []

    def iterationDone(self, model, iteration, epoch):
        score = model.score()
        self.history.append(score)
        if iteration % self.n == 0:
            self.out(f"Score at iteration {iteration} is {score}")


class PerformanceListener(TrainingListener):
    """Throughput/timing (ref: PerformanceListener: samples/sec, batches/sec)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True,
                 out: Callable = None):
        self.frequency = frequency
        self.report_batch = report_batch
        self.out = out or (lambda msg: logger.info(msg))
        self._last_time = None
        self._last_iter = 0
        self._samples = 0
        self.samples_per_sec: Optional[float] = None

    def iterationDone(self, model, iteration, epoch):
        # monotonic: throughput is a duration, and an NTP wall-clock step
        # mid-window would report negative (or absurd) samples/sec (W210)
        now = time.monotonic()
        self._samples += getattr(model, "_last_batch_size", 0)
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0:
                self.samples_per_sec = self._samples / dt
                msg = f"iter {iteration}: {iters / dt:.1f} iterations/sec"
                if self.report_batch and self._samples:
                    msg += f", {self.samples_per_sec:.1f} samples/sec"
                self.out(msg)
                # one source of truth: the dashboard and GET /metrics see
                # the same throughput numbers (profiler metrics registry)
                from deeplearning4j_tpu.profiler import get_registry
                reg = get_registry()
                reg.gauge("dl4j_throughput_batches_per_sec",
                          "Training throughput (PerformanceListener)"
                          ).set(iters / dt)
                if self._samples:
                    reg.gauge("dl4j_throughput_samples_per_sec",
                              "Training throughput (PerformanceListener)"
                              ).set(self.samples_per_sec)
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class TimeIterationListener(TrainingListener):
    """ETA logging (ref: TimeIterationListener)."""

    def __init__(self, total_iterations: int, out: Callable = None):
        self.total = total_iterations
        self.start = time.monotonic()   # duration math: W210
        self.out = out or (lambda msg: logger.info(msg))

    def iterationDone(self, model, iteration, epoch):
        elapsed = time.monotonic() - self.start
        if iteration > 0:
            remaining = elapsed / iteration * (self.total - iteration)
            self.out(f"iter {iteration}/{self.total}, ETA {remaining:.0f}s")


class CheckpointListener(TrainingListener):
    """Periodic checkpoints, keep-last-K rotation (ref: CheckpointListener)."""

    def __init__(self, directory: str, save_every_n_iterations: int = None,
                 save_every_n_epochs: int = None, keep_last: int = 3):
        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path, save_updater=True)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iterationDone(self, model, iteration, epoch):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def onEpochEnd(self, model):
        if self.every_epoch and model.getEpochCount() % self.every_epoch == 0:
            self._save(model, f"epoch_{model.getEpochCount()}")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (ref: EvaluativeListener)."""

    def __init__(self, iterator, frequency: int, evaluation_factory=None,
                 out: Callable = None):
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation
        self.iterator = iterator
        self.frequency = frequency
        self.factory = evaluation_factory or Evaluation
        self.out = out or (lambda msg: logger.info(msg))
        self.last_evaluation = None

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            ev = model.evaluate(self.iterator, self.factory())
            self.last_evaluation = ev
            self.out(f"iter {iteration}: accuracy={ev.accuracy():.4f}")


class StatsListener(TrainingListener):
    """Collect per-iteration training statistics into a StatsStorage
    (ref: org.deeplearning4j.ui.model.stats.StatsListener — the producer
    side of the StatsListener -> StatsStorage -> UIServer chain,
    SURVEY.md §1 L8, §5 "Metrics/logging").

    TPU-native capture: all per-layer summaries (param/update means, stds,
    L2 norms, update:param ratios, optional histograms) are computed ON
    DEVICE in one jitted program per sampled iteration and pulled to the
    host as a handful of scalars — never the weight tensors themselves.
    The pre-step parameter snapshot is a device-side copy (the train step
    donates its input buffers, so the listener must not alias them).
    """

    def __init__(self, storage, frequency: int = 1, session_id: str = None,
                 with_histograms: bool = False, hist_bins: int = 20):
        import uuid
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"sess_{uuid.uuid4().hex[:12]}"
        self.with_histograms = with_histograms
        self.hist_bins = hist_bins
        self._snapshot = None
        self._static_sent = False
        self._stats_fn = None
        self._t_iter_start = None

    # -------------------------------------------------------------- capture
    def _sampled(self, iteration: int) -> bool:
        return iteration % self.frequency == 0

    def onIterationStart(self, model, iteration: int):
        import jax
        if not self._sampled(iteration):
            return
        self._t_iter_start = time.monotonic()   # duration math: W210
        # device-side copy (donation-safe; freed after the diff is taken)
        self._snapshot = jax.tree_util.tree_map(lambda a: a + 0,
                                                model._params)

    def _leaf_name(self, path) -> str:
        parts = []
        for p in path:
            parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
        return "/".join(parts)

    def _build_stats_fn(self):
        import jax
        import jax.numpy as jnp
        bins = self.hist_bins
        with_hist = self.with_histograms

        def stats(new_params, old_params):
            out = {}
            leaves = jax.tree_util.tree_flatten_with_path(new_params)[0]
            old_leaves = jax.tree_util.tree_flatten_with_path(old_params)[0]
            for (path, w), (_, w0) in zip(leaves, old_leaves):
                if w.size == 0:
                    continue
                name = self._leaf_name(path)
                w32 = w.astype(jnp.float32)
                upd = w32 - w0.astype(jnp.float32)
                pn = jnp.sqrt(jnp.sum(jnp.square(w32)))
                un = jnp.sqrt(jnp.sum(jnp.square(upd)))
                rec = {"param_mean": jnp.mean(w32),
                       "param_std": jnp.std(w32),
                       "param_norm": pn,
                       "update_norm": un,
                       "update_ratio": un / (pn + 1e-12)}
                if with_hist:
                    lo, hi = jnp.min(w32), jnp.max(w32)
                    counts, _ = jnp.histogram(w32, bins=bins)
                    rec["hist_counts"] = counts
                    rec["hist_range"] = jnp.stack([lo, hi])
                out[name] = rec
            return out
        return jax.jit(stats)

    def iterationDone(self, model, iteration, epoch):
        import jax
        if not self._sampled(iteration) or self._snapshot is None:
            return
        if not self._static_sent:
            self._send_static(model)
        if self._stats_fn is None:
            self._stats_fn = self._build_stats_fn()
        per_layer = jax.device_get(self._stats_fn(model._params,
                                                  self._snapshot))
        self._snapshot = None
        layers = {}
        for name, rec in per_layer.items():
            layers[name] = {k: (v.tolist() if hasattr(v, "tolist") and
                                getattr(v, "ndim", 0) else float(v))
                            for k, v in rec.items()}
        dur = (time.monotonic() - self._t_iter_start) \
            if self._t_iter_start else None
        self.storage.putUpdate({
            "session_id": self.session_id,
            "worker_id": "0",
            "iteration": iteration,
            "epoch": epoch,
            "score": float(model.score()),
            "minibatch_size": getattr(model, "_last_batch_size", None),
            "iteration_time_sec": dur,
            "layers": layers,
        })

    def _send_static(self, model):
        import jax
        import numpy as _np
        n_params = sum(int(_np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(model._params))
        self.storage.putStaticInfo({
            "session_id": self.session_id,
            "worker_id": "0",
            "model_class": type(model).__name__,
            "n_parameters": n_params,
            "backend": jax.default_backend(),
            "n_devices": jax.device_count(),
        })
        self._static_sent = True


class MetricsListener(TrainingListener):
    """Bridge the listener bus into the profiler metrics registry
    (SURVEY.md §5: the listener bus is the single observability seam —
    this listener makes the same signals scrapeable at ``GET /metrics``).

    Per iteration: increments ``dl4j_train_iterations_total``, sets the
    ``dl4j_train_score`` gauge, observes ``dl4j_train_iteration_seconds``
    (wall time between the start/done hooks — includes the host sync the
    score read forces, making it the honest end-to-end iteration cost).
    Per epoch: increments ``dl4j_train_epochs_total``.

    ``sync_score=False`` skips the ``model.score()`` host sync for
    dispatch-bound training where a per-iteration blocking read is too
    expensive; the score gauge then keeps its last value.
    """

    def __init__(self, registry=None, sync_score: bool = True):
        from deeplearning4j_tpu.profiler import get_registry
        reg = registry or get_registry()
        self.registry = reg
        self.sync_score = sync_score
        self._c_iters = reg.counter(
            "dl4j_train_iterations_total",
            "Training iterations seen by MetricsListener")
        self._c_epochs = reg.counter(
            "dl4j_train_epochs_total",
            "Training epochs seen by MetricsListener")
        self._g_score = reg.gauge(
            "dl4j_train_score", "Last minibatch score (loss)")
        self._g_epoch = reg.gauge(
            "dl4j_train_epoch", "Current epoch number")
        self._h_iter = reg.histogram(
            "dl4j_train_iteration_seconds",
            "Wall time per iteration incl. listener-forced host sync")
        self._t0 = None

    def onIterationStart(self, model, iteration):
        self._t0 = time.perf_counter()

    def iterationDone(self, model, iteration, epoch):
        self._c_iters.inc()
        self._g_epoch.set(epoch)
        if self.sync_score:
            score = model.score()
            if score == score:      # skip NaN: gauges keep last real value
                self._g_score.set(float(score))
        if self._t0 is not None:
            self._h_iter.observe(time.perf_counter() - self._t0)
            self._t0 = None

    def onEpochEnd(self, model):
        self._c_epochs.inc()


class ProfilingListener(TrainingListener):
    """Chrome-trace profiling of training iterations (ref:
    ProfilingListener / OpProfiler, SURVEY.md §5 "Tracing/profiling").

    This captures the XLA/device side via ``jax.profiler``; for the
    framework-side timeline (dispatch, data-wait, transfers) use the
    in-process span tracer (``deeplearning4j_tpu.profiler.trace_span``),
    which supersedes ad-hoc trace writing here and serves ``GET /trace``.

    TPU-native: delegates to ``jax.profiler`` — the trace captures XLA
    device ops, host dispatch, and transfers; view in Perfetto/TensorBoard.
    Traces iterations [start_iter, end_iter) once, then stops."""

    def __init__(self, log_dir: str = None, start_iter: int = 2,
                 n_iters: int = 3, create_perfetto_trace: bool = True):
        if log_dir is None:
            # honour the env registry's DL4J_TPU_PROFILE_DIR knob
            from deeplearning4j_tpu.utils.environment import Environment
            log_dir = Environment.get().profile_dir
        self.log_dir = log_dir
        self.start_iter = start_iter
        self.n_iters = n_iters
        self.create_perfetto = create_perfetto_trace
        self._active = False
        self._done = False
        self._trace_start = None

    def onIterationStart(self, model, iteration: int):
        import jax
        if self._done or self._active or iteration < self.start_iter:
            return
        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir,
                                 create_perfetto_trace=self.create_perfetto)
        self._active = True
        self._trace_start = iteration   # window is RELATIVE to actual start

    def _stop(self, model):
        import jax
        model.score()  # sync before stopping so device ops land in-trace
        jax.profiler.stop_trace()
        self._active = False
        self._done = True

    def iterationDone(self, model, iteration, epoch):
        # both hooks are 1-based; trace covers exactly n_iters steps from
        # wherever the trace actually started
        if self._active and iteration - self._trace_start + 1 >= self.n_iters:
            self._stop(model)

    def onEpochEnd(self, model):
        if self._active:  # epoch shorter than the trace window
            self._stop(model)
