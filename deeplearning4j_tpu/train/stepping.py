"""Multi-step on-device training dispatch (megasteps).

The paper's core claim amortizes the reference's hundreds of JNI
crossings per training step down to ~1 dispatch per step (SURVEY.md
§3.1) — but on a high-latency device link one dispatch per step is
still the ceiling. This module batches K same-signature minibatches
into ONE compiled ``lax.scan`` program that performs K full update
steps (forward + loss + backward + clip + updater + frozen-layer
gating) per dispatch, the same move CUDA Graphs makes for kernel-launch
overhead and TensorFlow makes with in-graph loops (Abadi et al., 2016):
per-step host dispatch, listener bookkeeping, and link round trips all
drop by ~K×.

Pieces:

- :class:`MegaBatch` — K stacked batches, ``[K, B, ...]`` per array.
- :func:`group_into_megabatches` — signature-aware grouping of a batch
  stream; signature changes and epoch tails fall back to single-step
  fits, so ``fit(steps_per_dispatch=K)`` is ALWAYS numerically
  equivalent to K single-step fits (the hard guarantee the tests pin).
- :func:`scan_megastep` — wraps a single-step body into the scanned
  K-step program; the body is byte-for-byte the one the single-step
  path jits, so the per-iteration RNG (``fold_in(base, t)``), updater
  math, and frozen-layer gating are identical by construction.
- :func:`fit_epoch_multistep` — the epoch driver both
  ``MultiLayerNetwork.fit`` and ``ComputationGraph.fit`` delegate to:
  megabatch grouping behind a :class:`~deeplearning4j_tpu.data.dataset.
  DevicePrefetcher` (megabatch K+1 stages H2D while K computes), then
  ``model._fit_mega`` / ``model._fit_one`` per item.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterable, Iterator, List, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.profiler import sanitizer as _sanitizer

# How many update steps the most recent compiled dispatch performed.
STEPS_PER_DISPATCH = _prof.get_registry().gauge(
    "dl4j_steps_per_dispatch",
    "Update steps performed by the most recent compiled train dispatch "
    "(1 = classic per-step dispatch, K = lax.scan megastep)")
# Total update steps, advanced by K per megastep dispatch. A
# dl4j_train_step_seconds sample covers ONE dispatch (1 or K steps), so
# per-step host dispatch time under mixed K is
# rate(dl4j_train_step_seconds_sum) / rate(dl4j_train_iterations_total)
# — NOT sum/count, which a megastep/tail-fallback mix would skew.
TRAIN_ITERATIONS = _prof.get_registry().counter(
    "dl4j_train_iterations_total",
    "Update steps performed by compiled train dispatches (a K-step "
    "megastep advances this by K)")


def stage_batch(model, a, mega: bool = False):
    """Batch staging for the fit functions: plain ``jnp.asarray`` — or,
    when a :class:`~deeplearning4j_tpu.distributed.gspmd.
    ShardedTrainingPlan` is attached, ``device_put`` per the plan's
    batch PartitionSpec (dim 0 — dim 1 under a ``[K, B, ...]``
    megabatch — sharded over the plan's batch axes, replicated over
    model/seq axes). A no-op copy-wise for arrays a DevicePrefetcher
    already placed with the same sharding."""
    if a is None:
        return None
    plan = getattr(model, "_sharding_plan", None)
    if plan is None:
        return jnp.asarray(a)
    return plan.place(a, mega)


def batch_placement(model):
    """The DevicePrefetcher ``placement(array, mega)`` hook derived from
    the attached sharding plan's batch PartitionSpec — ``None`` (default
    device staging) when no plan is attached."""
    plan = getattr(model, "_sharding_plan", None)
    return None if plan is None else plan.place


def constrain_tree(tree, shardings):
    """``with_sharding_constraint`` over a whole pytree — how the GSPMD
    step pins its outputs (params, ZeRO-sharded updater state) to the
    plan's shardings INSIDE the one compiled program, so XLA cannot
    silently all-gather the sharded state at the step boundary.
    ``shardings=None`` is the identity (pure-replication plans compile
    byte-identical programs to the wrapper path)."""
    if shardings is None:
        return tree
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s),
        tree, shardings)


def fence_generation(model):
    """Entry half of the elastic dispatch-commit fence: the generation
    observed before dispatching (None when no fence is attached —
    non-elastic fits pay only this getattr)."""
    fence = getattr(model, "_dispatch_fence", None)
    return None if fence is None else fence.generation


@contextmanager
def dispatch_commit(model, gen):
    """Commit gate for a finished dispatch. Yields True when the
    dispatch may commit its outputs; False when the elastic layer
    bumped the fence while this dispatch was in flight (a watchdog-
    abandoned thread that un-hung after a mesh shrink) — the caller
    must DISCARD the result: the restored checkpoint state must not be
    overwritten, and no bookkeeping (iteration, listeners, checkpoint
    hooks) may run for a step the recovery already rolled back.
    The commit happens under the fence lock, mutually exclusive with
    the shrink path's bump+restore."""
    fence = getattr(model, "_dispatch_fence", None)
    if fence is None:
        yield True
        return
    with fence.lock:
        yield fence.generation == gen


class MegaBatch:
    """K same-signature training batches stacked along a leading axis.

    ``features``/``labels``/masks are ``[K, B, ...]`` arrays (or lists of
    them when ``multi`` — the MultiDataSet/ComputationGraph container);
    ``steps`` is K. Masks are None when absent from every stacked batch.
    """

    __slots__ = ("features", "labels", "features_mask", "labels_mask",
                 "steps", "multi")

    def numExamples(self) -> int:
        a = self.features[0] if self.multi else self.features
        return int(a.shape[0] * a.shape[1])


def batch_signature(ds):
    """Grouping key: two batches may share a compiled megastep iff their
    array shapes/dtypes and mask arities all match (the same condition
    under which the single-step jit cache would reuse one program)."""
    def sig(a):
        return None if a is None else (tuple(a.shape), str(a.dtype))
    if isinstance(ds, MultiDataSet):
        return ("multi",
                tuple(sig(a) for a in ds.features),
                tuple(sig(a) for a in ds.labels),
                tuple(sig(a) for a in (ds.features_masks or ())),
                tuple(sig(a) for a in (ds.labels_masks or ())))
    return ("single", sig(ds.features), sig(ds.labels),
            sig(ds.features_mask), sig(ds.labels_mask))


def _stack(arrs):
    if arrs[0] is None:
        return None
    if any(isinstance(a, jax.Array) for a in arrs):
        return jnp.stack(arrs)
    return np.stack(arrs)


def stack_megabatch(group: List[Union[DataSet, MultiDataSet]]) -> MegaBatch:
    """Stack K same-signature batches into one MegaBatch (host-side
    np.stack unless inputs are already device-resident)."""
    first = group[0]
    mb = MegaBatch()
    mb.steps = len(group)
    if isinstance(first, MultiDataSet):
        mb.multi = True
        mb.features = [_stack([d.features[i] for d in group])
                       for i in range(len(first.features))]
        mb.labels = [_stack([d.labels[i] for d in group])
                     for i in range(len(first.labels))]
        mb.features_mask = (
            [_stack([d.features_masks[i] for d in group])
             for i in range(len(first.features_masks))]
            if first.features_masks else None)
        mb.labels_mask = (
            [_stack([d.labels_masks[i] for d in group])
             for i in range(len(first.labels_masks))]
            if first.labels_masks else None)
    else:
        mb.multi = False
        mb.features = _stack([d.features for d in group])
        mb.labels = _stack([d.labels for d in group])
        mb.features_mask = _stack([d.features_mask for d in group])
        mb.labels_mask = _stack([d.labels_mask for d in group])
    return mb


def group_into_megabatches(batches: Iterable, steps: int) -> Iterator:
    """Yield MegaBatches of ``steps`` consecutive same-signature batches;
    batches stranded by a signature change or the epoch tail are yielded
    as plain DataSets (single-step fits) — equivalence over cleverness.
    Items that arrive ALREADY stacked (a staged pipeline's
    ``dispatch_stream()`` emits contiguous MegaBatch buffers directly —
    no re-stack, one H2D transfer) pass through untouched."""
    if steps <= 1:
        yield from batches
        return
    pending, sig = [], None
    for ds in batches:
        if isinstance(ds, MegaBatch):
            yield from pending
            pending, sig = [], None
            yield ds
            continue
        s = batch_signature(ds)
        if pending and s != sig:
            yield from pending
            pending = []
        sig = s
        pending.append(ds)
        if len(pending) == steps:
            yield stack_megabatch(pending)
            pending = []
    yield from pending


def use_dispatch_stream(data, steps: int, session) -> bool:
    """True when a fit can pull native megabatches from a staged
    pipeline iterator: K matches the iterator's declared staging, no
    resilience session (cursors are recorded per pull — a K-batch pull
    would make them dispatch-granular), and no per-batch preprocessor
    (those run on the host path; use device augmentation instead)."""
    return (steps > 1 and session is None
            and getattr(data, "megabatch_steps", 1) == steps
            and hasattr(data, "dispatch_stream")
            and getattr(data, "_pre", None) is None)


def scan_megastep(body, num_carry: int):
    """Wrap a single-step ``body(*carry, *xs) -> (*new_carry, loss)`` into
    a K-step program: carry threads (params, states, opt_state, t) —
    plus the dynamic loss-scale state ``[scale, good_steps]`` when the
    attached PrecisionPolicy is dynamic (``num_carry=5``) — every xs
    leaf gains a leading K axis, and the K per-step losses come back as
    ONE device vector. The body is the exact function the single-step
    path jits, so K scanned steps == K single-step fits numerically
    (the scale automaton ticks per scanned sub-step exactly as it would
    per dispatch)."""
    def megastep(*args):
        carry, xs = args[:num_carry], args[num_carry:]

        def scan_body(c, x):
            out = body(*c, *x)
            return tuple(out[:-1]), out[-1]

        carry, losses = jax.lax.scan(scan_body, tuple(carry), tuple(xs))
        return (*carry, losses)
    return megastep


def record_megastep(model, losses, steps: int, batch_size: int,
                    san_token=None) -> None:
    """Shared post-dispatch bookkeeping for ``_fit_mega`` (both network
    classes): numerics panic gate over the K-loss vector (with first-
    nonfinite provenance when the sanitizer armed ``san_token``), then
    per-step listener delivery — each ``losses[j]`` stays a lazy device
    scalar unless a listener actually pulls ``score()``.

    Listener semantics under megasteps: all K callback pairs fire AFTER
    the dispatch, so a listener that inspects model state (params,
    checkpoints) at iteration N observes the END-OF-DISPATCH state, not
    iteration N's. Iteration-indexed side effects (CheckpointListener
    intervals, EvaluativeListener) should use an interval K divides — or
    choose K to divide the interval — so callbacks land on dispatch
    boundaries where state and iteration number agree."""
    _sanitizer.check(
        model, san_token, losses,
        context=f"megastep losses at iterations "
                f"{model._iteration + 1}..{model._iteration + steps}")
    if _prof.instrumentation_active():
        TRAIN_ITERATIONS.inc(steps)
    model._last_batch_size = batch_size
    if not model._listeners:
        # no one consumes per-step losses: ONE lazy slice for score()
        # instead of K tiny indexing dispatches per megastep
        model._iteration += steps
        model._score = losses[steps - 1]
    else:
        for j in range(steps):
            model._score = losses[j]
            model._iteration += 1
            for lst in model._listeners:
                if hasattr(lst, "onIterationStart"):
                    lst.onIterationStart(model, model._iteration)
                if hasattr(lst, "iterationDone"):
                    lst.iterationDone(model, model._iteration, model._epoch)
    # resilience seam (train.resilience): non-finite recovery, periodic
    # checkpoint, and preemption all act at dispatch granularity — the
    # in-flight megastep always completes before any of them fire
    res = getattr(model, "_resilience", None)
    if res is not None:
        res.after_dispatch(losses, steps)


def fit_epoch_multistep(model, batches: Iterable, steps: int,
                        prefetch: int = 2, placement=None) -> None:
    """One epoch of multi-step dispatch: group the batch stream into
    megabatches and stage each onto the device from a background thread
    (double buffer — megabatch K+1 transfers while K computes), then run
    each through the model's compiled megastep. ``prefetch <= 0`` runs
    the whole pipeline synchronously on the calling thread (no worker
    thread; for iterators backed by thread-affine resources)."""
    from deeplearning4j_tpu.data.dataset import DevicePrefetcher, stage_item

    def drive(items):
        for item in _prof.iter_with_data_wait(items):
            if isinstance(item, MegaBatch):
                model._fit_mega(item)
            else:
                model._fit_one(item)

    if prefetch and prefetch > 0:
        with DevicePrefetcher(batches, steps_per_dispatch=steps,
                              prefetch=prefetch, placement=placement) as pf:
            drive(pf)
    else:
        drive(stage_item(item, placement)
              for item in group_into_megabatches(batches, steps))


def apply_tuned_plan(model, tune, steps_per_dispatch: int, prefetch: int):
    """Resolve ``fit(tune=...)`` (ISSUE 17): ``"auto"`` consults the
    autotuner record store for this (model, mesh, backend, jax version)
    key; a :class:`~deeplearning4j_tpu.tune.space.TuningPlan` instance
    applies directly.  The plan's model-level seams (layout, fusion,
    precision) apply through the model's own signature-keyed setters —
    re-applying an equal plan keeps every compiled-step cache — and the
    plan's fit-level knobs take over only where the caller left the
    defaults.  Returns the effective ``(steps_per_dispatch, prefetch)``."""
    from deeplearning4j_tpu.tune import records as _trecords
    from deeplearning4j_tpu.tune.space import TuningPlan
    if isinstance(tune, TuningPlan):
        plan = tune
        plan.apply(model)
    elif tune == "auto":
        plan = _trecords.auto_apply(
            model, mesh=getattr(model, "_sharding_plan", None),
            context="fit")
    else:
        raise ValueError(
            f'tune= expects "auto" or a TuningPlan, got {tune!r}')
    if plan is not None:
        if steps_per_dispatch == 1:
            steps_per_dispatch = plan.steps_per_dispatch
        if prefetch == 2:
            prefetch = plan.prefetch
    return steps_per_dispatch, prefetch
