"""Learning-rate (and value) schedules.

Reference parity: ``org.nd4j.linalg.schedule.{ISchedule, FixedSchedule,
StepSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
SigmoidSchedule, MapSchedule, CycleSchedule, RampSchedule}``
(SURVEY.md §2.2 "Training infra").

TPU-native: ``valueAt(iteration, epoch)`` is pure jnp math on traced
scalars, so the schedule evaluates INSIDE the compiled train step — no
host round-trip per iteration (the reference recomputes on the JVM side
each step).
"""

from __future__ import annotations

import jax.numpy as jnp


class ISchedule:
    """valueAt(iteration, epoch) -> value. Subclasses are stateless."""

    def valueAt(self, iteration, epoch=0):
        raise NotImplementedError

    def __call__(self, iteration, epoch=0):
        return self.valueAt(iteration, epoch)

    def to_config(self):
        d = {"@class": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items()})
        return d

    @staticmethod
    def from_config(d):
        d = dict(d)
        cls_name = d.pop("@class")
        if cls_name == "RampSchedule":
            return RampSchedule(ISchedule.from_config(d["base"]), d["num_iter"])
        if cls_name == "MapSchedule":
            # through __init__ so JSON string keys are coerced back to int
            return MapSchedule(d["schedule_type"], d["values"])
        cls = _SCHEDULES[cls_name]
        obj = cls.__new__(cls)
        obj.__dict__.update(d)
        return obj


class FixedSchedule(ISchedule):
    def __init__(self, value: float):
        self.value = float(value)

    def valueAt(self, iteration, epoch=0):
        return self.value


class StepSchedule(ISchedule):
    """value * decayRate^floor(iter/step) (ref: StepSchedule)."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.1,
                 decay_rate: float = 0.5, step: float = 1000):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.decay_rate = float(decay_rate)
        self.step = float(step)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        return self.initial_value * self.decay_rate ** jnp.floor(t / self.step)


class ExponentialSchedule(ISchedule):
    """value * gamma^t (ref: ExponentialSchedule)."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.1,
                 gamma: float = 0.999):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.gamma = float(gamma)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        return self.initial_value * self.gamma ** t


class InverseSchedule(ISchedule):
    """value / (1 + gamma*t)^power (ref: InverseSchedule)."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.1,
                 gamma: float = 0.001, power: float = 1.0):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.gamma = float(gamma)
        self.power = float(power)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        return self.initial_value / (1.0 + self.gamma * t) ** self.power


class PolySchedule(ISchedule):
    """value * (1 - t/maxIter)^power (ref: PolySchedule)."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.1,
                 power: float = 1.0, max_iter: int = 10000):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.power = float(power)
        self.max_iter = int(max_iter)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


class SigmoidSchedule(ISchedule):
    """value / (1 + exp(gamma*(t - stepSize))) (ref: SigmoidSchedule)."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.1,
                 gamma: float = 0.01, step_size: int = 1000):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.gamma = float(gamma)
        self.step_size = int(step_size)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (t - self.step_size)))


class MapSchedule(ISchedule):
    """Piecewise-constant from {iteration: value} (ref: MapSchedule).
    jit-friendly: lowered to a chain of wheres."""

    def __init__(self, schedule_type: str = "iteration", values: dict = None):
        self.schedule_type = schedule_type
        self.values = {int(k): float(v) for k, v in (values or {}).items()}
        if 0 not in self.values:
            raise ValueError("MapSchedule requires a value for t=0")

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        out = jnp.asarray(self.values[0], jnp.float32)
        for k in sorted(self.values):
            out = jnp.where(t >= k, self.values[k], out)
        return out


class CycleSchedule(ISchedule):
    """1cycle policy (ref: CycleSchedule): ramp up to maxLR, down to
    initial, then anneal to initial/100 over the final fraction."""

    def __init__(self, schedule_type: str = "iteration", initial_value: float = 0.01,
                 max_value: float = 0.1, cycle_length: int = 1000,
                 annealing_length: int = 100, annealing_decay: float = 0.01):
        self.schedule_type = schedule_type
        self.initial_value = float(initial_value)
        self.max_value = float(max_value)
        self.cycle_length = int(cycle_length)
        self.annealing_length = int(annealing_length)
        self.annealing_decay = float(annealing_decay)

    def valueAt(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "iteration" else epoch
        ramp = (self.cycle_length - self.annealing_length) / 2
        pos = t % self.cycle_length
        up = self.initial_value + (self.max_value - self.initial_value) * (pos / jnp.maximum(ramp, 1))
        down = self.max_value - (self.max_value - self.initial_value) * ((pos - ramp) / jnp.maximum(ramp, 1))
        anneal_pos = (pos - 2 * ramp) / jnp.maximum(self.annealing_length, 1)
        anneal = self.initial_value * (1.0 - (1.0 - self.annealing_decay) * anneal_pos)
        out = jnp.where(pos < ramp, up, jnp.where(pos < 2 * ramp, down, anneal))
        return out


class RampSchedule(ISchedule):
    """Linear warmup wrapper (ref: RampSchedule): scales an underlying
    schedule by t/numIter for the first numIter steps."""

    def __init__(self, base: ISchedule, num_iter: int):
        self.base = base
        self.num_iter = int(num_iter)

    def valueAt(self, iteration, epoch=0):
        scale = jnp.clip((iteration + 1) / self.num_iter, 0.0, 1.0)
        return scale * self.base.valueAt(iteration, epoch)

    def to_config(self):
        return {"@class": "RampSchedule", "base": self.base.to_config(),
                "num_iter": self.num_iter}


_SCHEDULES = {c.__name__: c for c in
              [FixedSchedule, StepSchedule, ExponentialSchedule, InverseSchedule,
               PolySchedule, SigmoidSchedule, MapSchedule, CycleSchedule,
               RampSchedule]}


def resolve(lr) -> ISchedule:
    """Accept a float or an ISchedule."""
    if isinstance(lr, ISchedule):
        return lr
    return FixedSchedule(float(lr))
