"""Training infra: updaters, schedules, losses, listeners, checkpoints
(ref: org.nd4j.linalg.learning + org.deeplearning4j.optimize — SURVEY.md §2.2)."""

from deeplearning4j_tpu.train import schedules, updaters  # noqa: F401
from deeplearning4j_tpu.train import stepping  # noqa: F401  (multi-step dispatch)
from deeplearning4j_tpu.train.listeners import (  # noqa: F401
    CheckpointListener,
    EvaluativeListener,
    PerformanceListener,
    ScoreIterationListener,
    TimeIterationListener,
    TrainingListener,
)
from deeplearning4j_tpu.train.resilience import (  # noqa: F401
    CheckpointConfig,
    CheckpointManager,
    CorruptCheckpointError,
    NanPolicy,
    NanRecovery,
    PreemptionSignal,
    SignalPreemption,
    StepPreemption,
)
from deeplearning4j_tpu.train.serializer import (  # noqa: F401
    CorruptModelError,
    ModelSerializer,
)
