"""The lifecycle driver: continuous training as a service.

:class:`LifecycleDriver` closes the loop the rest of the stack left
open — the trainer (fit / fit_elastic with async checkpoints) and the
serving registry already coexist on one mesh; this is the state
machine that moves candidates between them, round after round::

    train -> gate -> load -> canary -> observe -> promote -> confirm
                |                 \\                            |
                +-> quarantine     +-> abort_canary            +-> rollback

Every phase transition persists through a
:class:`~deeplearning4j_tpu.train.resilience.DriverStateStore` (atomic
+ checksummed + quarantining), so a SIGKILL anywhere in the loop —
including mid-roll, the chaos-pinned case — leaves a successor driver
knowing exactly what was in flight: it aborts the stale canary (the
registry stays consistent at the incumbent throughout; abort is
idempotent), re-attempts the interrupted round's candidate, and
continues. The serving side never drops a request across any of this:
requests are owned by the server that admitted them (exactly-once
resolution), and both canary begin/abort and roll/rollback are pointer
swaps under the registry lock.

The failure ladder, cheapest exit first:

1. **gate** — a candidate with non-finite outputs or a regressed
   scorecard vs the serving incumbent is quarantined with a structured
   reason; it is NEVER ``load()``-ed (zero serving-side cost).
2. **canary observe** — the candidate takes a deterministic traffic
   fraction; the judge watches p99/shed/breaker via
   ``registry.load_hints()`` and burn rates via
   ``SLOEngine.burn_over(window)`` for ``observe_ticks``; unhealthy ->
   ``abort_canary`` (incumbent never stopped serving the rest).
3. **post-promote confirm** — the judge keeps watching for
   ``confirm_ticks`` after the roll; an SLO regression here ->
   automatic ``rollback()``, bit-identical to the pre-roll incumbent
   (the old server is still loaded and warmed).

Chaos seams (:class:`~deeplearning4j_tpu.faults.FaultPlan`):
``bad_candidate_at`` poisons a round's candidate (NaN outputs or a
deterministic regression — the GATE does the rejecting),
``trainer_death_at_roll`` SIGKILLs the trainer subprocess mid-roll and
kills the driver loop (the resume path does the recovering), and
``slo_regression_during_canary`` induces a genuine judge failure in the
confirm window (the ROLLBACK path does the restoring).
"""

from __future__ import annotations

import os
import signal as _signal
import subprocess
import sys
import time
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.profiler import flightrec as _flightrec
from deeplearning4j_tpu.profiler import tracecontext as _tracectx
from deeplearning4j_tpu.train.resilience import DriverStateStore

from .capture import TrafficCapture
from .gate import EvalGate, GateVerdict

import logging

logger = logging.getLogger("deeplearning4j_tpu")

_REG = _prof.get_registry()
ROUNDS = _REG.counter(
    "dl4j_lifecycle_rounds_total",
    "Lifecycle rounds completed, by how the round ended",
    labelnames=("outcome",))
PROMOTIONS = _REG.counter(
    "dl4j_lifecycle_promotions_total",
    "Candidates promoted to the active route after a clean confirm")
LC_ROLLBACKS = _REG.counter(
    "dl4j_lifecycle_rollbacks_total",
    "Automatic rollbacks on post-promote SLO regression")
QUARANTINES = _REG.counter(
    "dl4j_lifecycle_quarantines_total",
    "Candidates quarantined, by structured reason",
    labelnames=("reason",))
GATE_SECONDS = _REG.histogram(
    "dl4j_lifecycle_gate_seconds",
    "Wall time of one eval-gate evaluation")
ROLL_SECONDS = _REG.histogram(
    "dl4j_lifecycle_roll_seconds",
    "Wall time of one promote (registry roll) in the lifecycle loop")
TRAINER_DEATHS = _REG.counter(
    "dl4j_lifecycle_trainer_deaths_total",
    "Trainer processes killed at the trainer_death_at_roll chaos seam")
LC_RESUMES = _REG.counter(
    "dl4j_lifecycle_resumes_total",
    "Driver starts that resumed an interrupted round from persisted "
    "state")


class TrainerKilledError(RuntimeError):
    """The trainer process died (chaos seam: SIGKILL mid-roll). The
    driver's state machine was persisted BEFORE the death — construct a
    new driver over the same ``state_dir`` and ``run()`` resumes the
    interrupted round."""

    def __init__(self, round_index: int, roll_index: int):
        self.round_index = round_index
        self.roll_index = roll_index
        super().__init__(
            f"trainer killed mid-roll (round {round_index}, roll "
            f"{roll_index}) — resume by running a new driver over the "
            "same state_dir")


def spawn_trainer_process() -> subprocess.Popen:
    """A stand-in trainer subprocess for chaos tests: a sleep loop with
    no heavy imports, cheap to spawn and SIGKILL-able. A real
    deployment passes its actual training job's handle as
    ``trainer_process`` instead."""
    return subprocess.Popen(
        [sys.executable, "-c",
         "import time\nwhile True: time.sleep(3600)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


class _PoisonedCandidate:
    """Wrap a candidate model per the ``bad_candidate_at`` chaos kinds:
    ``"nan"`` makes every output NaN (the gate's finiteness check must
    reject it); ``"regressed"`` adds a constant offset (a genuine,
    deterministic scorecard/parity regression the gate must catch).
    Callable, so it composes with ``resolve_forward`` everywhere."""

    def __init__(self, model, kind: str):
        from deeplearning4j_tpu.serving.server import resolve_forward
        self.model = model
        self.kind = kind
        self._fwd = resolve_forward(model)

    def __call__(self, x):
        out = np.asarray(self._fwd(x))
        if self.kind == "nan":
            return np.full_like(out, np.nan)
        return out + 1.0


class LifecycleDriver:
    """Drive continuous train -> gate -> canary -> promote/rollback
    rounds against a :class:`~deeplearning4j_tpu.serving.registry.
    ModelRegistry` (module doc for the state machine).

    Parameters
    ----------
    registry : the serving registry (trainer and registry share one
        mesh — the zero-recompile pin holds across the whole loop).
    name : the model name the driver owns in the registry.
    trainer : ``trainer(round_index) -> candidate model`` — typically a
        closure over ``fit()``/``fit_elastic()`` with async checkpoints
        that returns the round's candidate.
    state_dir : where the driver checkpoints its own state machine.
    eval_x / eval_y : held-out eval set for the gate. When ``eval_x``
        is None the driver reads the live-traffic capture at
        ``capture_path`` instead (production inputs as eval set).
    gate : an :class:`EvalGate` (default: one with default policy).
    canary_fraction : traffic fraction the canary takes while
        observing.
    observe_ticks / confirm_ticks : judge evaluations before promote /
        after promote; ``tick_interval`` seconds between them.
    observation_window : lookback (seconds) for
        ``SLOEngine.burn_over`` at each tick.
    slo_engine : optional :class:`~deeplearning4j_tpu.profiler.slo.
        SLOEngine` consulted by the default judge.
    judge : ``judge(hints, burns, induced) -> bool`` overriding the
        default health check (truthy = healthy).
    max_shed_rate : default judge's ceiling on the model's shed rate.
    faults : a :class:`~deeplearning4j_tpu.faults.FaultPlan` wiring the
        lifecycle chaos seams.
    shapes / load_kw : forwarded to ``registry.load`` for candidates.
    trainer_process : a live trainer process handle (``.pid``); the
        ``trainer_death_at_roll`` seam SIGKILLs it.
    """

    def __init__(self, registry, name: str, trainer: Callable,
                 state_dir: str, eval_x=None, eval_y=None,
                 capture_path: Optional[str] = None,
                 gate: Optional[EvalGate] = None,
                 canary_fraction: float = 0.25,
                 observe_ticks: int = 2, confirm_ticks: int = 2,
                 tick_interval: float = 0.0,
                 observation_window: float = 5.0,
                 slo_engine=None, judge: Optional[Callable] = None,
                 max_shed_rate: float = 0.5,
                 faults=None, shapes=None, load_kw: Optional[dict] = None,
                 trainer_process=None):
        self.registry = registry
        self.name = name
        self.trainer = trainer
        self.eval_x = eval_x
        self.eval_y = eval_y
        self.capture_path = capture_path
        self.gate = gate or EvalGate()
        self.canary_fraction = float(canary_fraction)
        self.observe_ticks = int(observe_ticks)
        self.confirm_ticks = int(confirm_ticks)
        self.tick_interval = float(tick_interval)
        self.observation_window = float(observation_window)
        self.slo_engine = slo_engine
        self.judge = judge
        self.max_shed_rate = float(max_shed_rate)
        self.faults = faults
        self.shapes = shapes
        self.load_kw = dict(load_kw or {})
        self.trainer_process = trainer_process
        self.store = DriverStateStore(state_dir)
        self._trace = _tracectx.TraceContext.new()
        self._state = self.store.load()
        self.resumed = False
        if self._state is None:
            self._state = {"round": 0, "phase": "idle", "in_round": None,
                           "roll_index": 0, "incumbent": None,
                           "candidate_version": None, "quarantined": [],
                           "promotions": 0, "rollbacks": 0}
            self.store.save(self._state)
        elif self._state.get("in_round") is not None:
            self.resumed = True

    # --------------------------------------------------------- state I/O
    def _persist(self, phase: Optional[str] = None, **updates) -> None:
        if phase is not None:
            self._state["phase"] = phase
        self._state.update(updates)
        self.store.save(self._state)

    @property
    def incumbent_version(self) -> Optional[int]:
        return self._state["incumbent"]

    @property
    def quarantined(self) -> list:
        return list(self._state["quarantined"])

    @property
    def promotions(self) -> int:
        return self._state["promotions"]

    @property
    def rollbacks(self) -> int:
        return self._state["rollbacks"]

    # ------------------------------------------------------------- spans
    def _span(self, which: str, t0_us: int, **args) -> None:
        _tracectx.record_span(
            f"lifecycle:{which}", self._trace.child(), t0_us,
            _prof.now_us() - t0_us, args=dict(args, model=self.name))

    # -------------------------------------------------------------- run
    def run(self, rounds: int) -> dict:
        """Execute rounds until ``state["round"] == rounds`` (so a
        resumed driver finishes the SAME total, never extra). Returns a
        summary dict. Raises :class:`TrainerKilledError` at the
        trainer-death chaos seam AFTER persisting — rerun to resume."""
        if self.resumed:
            self._recover()
        while self._state["round"] < rounds:
            r = self._state["round"] + 1
            self._run_round(r)
        summary = {"rounds": self._state["round"],
                   "incumbent": self._state["incumbent"],
                   "promotions": self._state["promotions"],
                   "rollbacks": self._state["rollbacks"],
                   "quarantined": self.quarantined}
        self._persist(phase="idle")
        return summary

    def _recover(self) -> None:
        """Pick up an interrupted round: the registry is left consistent
        (abort any stale canary — idempotent), then the interrupted
        candidate re-enters at the canary phase; an interruption before
        ``load`` just replays the round from ``train``."""
        st = self._state
        LC_RESUMES.inc()
        aborted = self.registry.abort_canary(self.name)
        _flightrec.get_flight_recorder().record(
            "lifecycle:resume", model=self.name,
            round=st["in_round"], phase=st["phase"],
            aborted_canary=aborted)
        logger.info("lifecycle: resumed %s at round %s phase %s "
                    "(aborted canary: %s)", self.name, st["in_round"],
                    st["phase"], aborted)
        r = st["in_round"]
        self.resumed = False
        if r is None:
            return
        if st["phase"] in ("canary", "observe", "promote", "confirm") \
                and st["candidate_version"] is not None:
            # the candidate is already loaded and warmed: re-attempt
            # its canary rather than retraining
            self._canary_and_promote(r, st["candidate_version"])
        else:
            # died before load: replay the round from train
            self._run_round(r)

    def _run_round(self, r: int) -> None:
        self._persist(phase="train", in_round=r, candidate_version=None)
        candidate = self.trainer(r)
        kind = self.faults.candidate_fault(r) if self.faults is not None \
            else None
        if kind is not None:
            candidate = _PoisonedCandidate(candidate, kind)
        verdict = self._gate(r, candidate)
        if not verdict:
            self._quarantine(r, None, f"gate:{verdict.reason}",
                             verdict.to_dict())
            self._complete_round(r, "gate_rejected")
            return
        version = self._load(r, candidate)
        if self._state["incumbent"] is None:
            # bootstrap: the first version has nothing to canary against
            self._persist(phase="promote")
            self.registry.roll(self.name, version)
            self._state["promotions"] += 1
            PROMOTIONS.inc()
            self._persist(incumbent=version)
            self._complete_round(r, "promoted")
            return
        self._canary_and_promote(r, version)

    # ------------------------------------------------------------ phases
    def _gate(self, r: int, candidate) -> GateVerdict:
        self._persist(phase="gate")
        eval_x, eval_y = self.eval_x, self.eval_y
        if eval_x is None and self.capture_path is not None:
            eval_x = TrafficCapture.eval_features(self.capture_path)
            eval_y = None
        incumbent = None
        if self._state["incumbent"] is not None:
            incumbent = self.registry.server(
                self.name, self._state["incumbent"]).model
        t0_us = _prof.now_us()
        t0 = time.perf_counter()
        verdict = self.gate.evaluate(candidate, incumbent, eval_x, eval_y)
        GATE_SECONDS.observe(time.perf_counter() - t0)
        self._span("gate", t0_us, round=r, passing=verdict.passing,
                   reason=verdict.reason)
        return verdict

    def _load(self, r: int, candidate) -> int:
        self._persist(phase="load")
        version = self.registry.load(self.name, candidate, roll=False,
                                     shapes=self.shapes, **self.load_kw)
        self._persist(candidate_version=version)
        return version

    def _kill_trainer(self) -> None:
        proc = self.trainer_process
        if proc is None:
            return
        pid = getattr(proc, "pid", None)
        if pid is None:
            return
        try:
            os.kill(pid, _signal.SIGKILL)
        except (OSError, AttributeError):
            pass
        if isinstance(proc, subprocess.Popen):
            try:
                proc.wait(timeout=5.0)
            except Exception:
                pass

    def _canary_and_promote(self, r: int, version: int) -> bool:
        st = self._state
        if st["phase"] not in ("observe", "promote", "confirm"):
            st["roll_index"] += 1
        roll_idx = st["roll_index"]
        if self.registry.active_version(self.name) == version:
            # resumed after the promote already landed: nothing to
            # canary — go straight to the confirm window
            self._persist(phase="confirm", in_round=r,
                          candidate_version=version)
            return self._confirm(r, version, roll_idx)
        self._persist(phase="canary", in_round=r,
                      candidate_version=version)
        t0_us = _prof.now_us()
        if self.registry.canary(self.name) is None:
            self.registry.begin_canary(self.name, version,
                                       fraction=self.canary_fraction)
        self._span("canary", t0_us, round=r, version=version,
                   fraction=self.canary_fraction)
        if self.faults is not None \
                and self.faults.trainer_dies_at_roll(roll_idx):
            # THE mid-roll death: the canary is live, the state machine
            # is persisted — kill the trainer and die. The successor
            # driver aborts the canary (registry consistent at the
            # incumbent) and re-attempts this candidate.
            self._kill_trainer()
            TRAINER_DEATHS.inc()
            _flightrec.get_flight_recorder().record(
                "lifecycle:trainer_death", model=self.name, round=r,
                roll_index=roll_idx)
            raise TrainerKilledError(r, roll_idx)
        self._persist(phase="observe")
        for _tick in range(self.observe_ticks):
            if not self._judge_tick(induced=False):
                self.registry.abort_canary(self.name)
                self._quarantine(r, version, "canary_unhealthy",
                                 {"tick": _tick})
                self._complete_round(r, "canary_aborted")
                return False
            if self.tick_interval:
                time.sleep(self.tick_interval)
        self._persist(phase="promote")
        t0_us = _prof.now_us()
        t0 = time.perf_counter()
        prev = self.registry.roll(self.name, version)
        ROLL_SECONDS.observe(time.perf_counter() - t0)
        self._span("roll", t0_us, round=r, version=version, previous=prev)
        self._persist(phase="confirm")
        return self._confirm(r, version, roll_idx)

    def _confirm(self, r: int, version: int, roll_idx: int) -> bool:
        for _tick in range(self.confirm_ticks):
            induced = (self.faults is not None
                       and self.faults.canary_regression(roll_idx))
            if not self._judge_tick(induced=induced):
                self.registry.rollback(self.name)
                self._state["rollbacks"] += 1
                LC_ROLLBACKS.inc()
                self._quarantine(
                    r, version,
                    "slo_regression" if induced else "confirm_unhealthy",
                    {"tick": _tick, "induced": bool(induced)})
                self._complete_round(r, "rolled_back")
                return False
            if self.tick_interval:
                time.sleep(self.tick_interval)
        self._state["promotions"] += 1
        PROMOTIONS.inc()
        self._persist(incumbent=version)
        self._complete_round(r, "promoted")
        return True

    # ------------------------------------------------------------ judge
    def _judge_tick(self, induced: bool = False) -> bool:
        hints = self.registry.load_hints()
        burns = (self.slo_engine.burn_over(self.observation_window)
                 if self.slo_engine is not None else {})
        if self.judge is not None:
            return bool(self.judge(hints, burns, induced))
        if induced:
            return False
        model = hints["models"].get(self.name, {})
        for h in (model, model.get("canary") or {}):
            if h.get("shed_rate", 0.0) > self.max_shed_rate:
                return False
            if h.get("breaker") == "open":
                return False
        threshold = getattr(self.slo_engine, "threshold", 1.0)
        return all(b <= threshold for b in burns.values())

    # ------------------------------------------------------- bookkeeping
    def _quarantine(self, r: int, version: Optional[int], reason: str,
                    detail: dict) -> None:
        rec = {"round": r, "version": version, "reason": reason,
               "detail": detail}
        self._state["quarantined"].append(rec)
        QUARANTINES.labels(reason=reason).inc()
        _flightrec.get_flight_recorder().record(
            "lifecycle:quarantine", model=self.name, **rec)
        logger.warning("lifecycle: quarantined %s round %d (%s)",
                       self.name, r, reason)

    def _complete_round(self, r: int, outcome: str) -> None:
        ROUNDS.labels(outcome=outcome).inc()
        _flightrec.get_flight_recorder().record(
            "lifecycle:round", model=self.name, round=r, outcome=outcome)
        self._persist(phase="idle", round=r, in_round=None,
                      candidate_version=None)
