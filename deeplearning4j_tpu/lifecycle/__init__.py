"""Continuous training as a service (ISSUE 20).

The closed loop over the rest of the stack: a
:class:`~deeplearning4j_tpu.lifecycle.driver.LifecycleDriver` runs the
trainer alongside the serving registry on one mesh, moving each
candidate through eval gate -> canary roll -> promote-or-rollback,
with its own state machine checkpointed
(:class:`~deeplearning4j_tpu.train.resilience.DriverStateStore`) so a
SIGKILL anywhere resumes cleanly and the registry never serves an
inconsistent version. ``python -m deeplearning4j_tpu.lifecycle`` lints
a lifecycle plan (DL4J-W113/W114) before it runs.
"""

from .capture import TrafficCapture
from .driver import (LifecycleDriver, TrainerKilledError,
                     spawn_trainer_process)
from .gate import EvalGate, GatePolicy, GateVerdict

__all__ = [
    "EvalGate",
    "GatePolicy",
    "GateVerdict",
    "LifecycleDriver",
    "TrafficCapture",
    "TrainerKilledError",
    "spawn_trainer_process",
]
