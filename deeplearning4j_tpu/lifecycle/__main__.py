"""CLI: lint a lifecycle plan before it drives traffic.

Usage::

    python -m deeplearning4j_tpu.lifecycle --observation-window 30 \\
        --canary-fraction 0.1 --slo-windows 60,600 \\
        --requests-per-tick 40 --buckets 8,16,32

Exit status 0 only when the plan is clean (DL4J-W113/W114 count as
failures unless ``--warnings-ok``). Purely static — no jax, no
registry, no traffic.
"""

from __future__ import annotations

import argparse
import sys

from deeplearning4j_tpu.analysis.lifecycle import lint_lifecycle


def _floats(csv: str):
    return [float(v) for v in csv.split(",") if v.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.lifecycle",
        description="Static lint for a lifecycle driver plan "
                    "(DL4J-W113/W114)")
    ap.add_argument("--observation-window", type=float, required=True,
                    help="judge burn-rate lookback per tick, seconds")
    ap.add_argument("--canary-fraction", type=float, required=True,
                    help="fraction of unpinned traffic the canary takes")
    ap.add_argument("--slo-windows", type=_floats, default=None,
                    metavar="FAST,SLOW",
                    help="the SLOSpec windows the judge consults")
    ap.add_argument("--requests-per-tick", type=float, default=None,
                    help="expected unpinned requests per observation tick")
    ap.add_argument("--buckets", type=_floats, default=None,
                    metavar="B1,B2,...",
                    help="the canary server's batch bucket ladder")
    ap.add_argument("--warnings-ok", action="store_true",
                    help="exit 0 even when warnings fire")
    args = ap.parse_args(argv)

    report = lint_lifecycle(
        observation_window=args.observation_window,
        canary_fraction=args.canary_fraction,
        slo_windows=args.slo_windows,
        requests_per_tick=args.requests_per_tick,
        buckets=[int(b) for b in args.buckets] if args.buckets else None)
    if not report.diagnostics:
        print("lifecycle plan: clean")
        return 0
    for d in report.diagnostics:
        print(d.format())
    return 0 if args.warnings_ok else 1


if __name__ == "__main__":
    sys.exit(main())
