"""The eval gate: no candidate reaches the registry without a verdict.

The gate sits between "the trainer produced a checkpoint" and
"``registry.load()``" — the single place the lifecycle loop can stop a
bad model BEFORE it costs a warmed bucket ladder, let alone traffic.
It scores the candidate on a held-out eval set (by preference the
live-traffic capture, so the score reflects production inputs) and
compares against the serving incumbent:

- **finiteness** — a candidate whose outputs are NaN/Inf on real eval
  rows is rejected outright (the classic poisoned-checkpoint failure);
- **scorecard** — with labels, candidate loss must stay within
  ``max_regression`` of the incumbent's loss on the same rows;
- **loss parity** — without labels, the candidate's outputs must stay
  within a relative ``parity_bound`` of the incumbent's (a continuous-
  training step should refine the function, not replace it).

A failing candidate is returned as a structured
:class:`GateVerdict` (reason + both scores + detail) the driver
quarantines and records — it is never loaded, so a gate rejection
costs zero serving-side work.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu import profiler as _prof

_REG = _prof.get_registry()
GATE_VERDICTS = _REG.counter(
    "dl4j_lifecycle_gate_verdicts_total",
    "Eval-gate decisions by outcome",
    labelnames=("outcome",))


def _forward(model, x: np.ndarray) -> np.ndarray:
    from deeplearning4j_tpu.serving.server import resolve_forward
    return np.asarray(resolve_forward(model)(x))


def _mse(a: np.ndarray, b: np.ndarray) -> float:
    return float(np.mean((np.asarray(a, np.float64)
                          - np.asarray(b, np.float64)) ** 2))


class GatePolicy:
    """Tuning knobs for :class:`EvalGate` (README: "Continuous
    training" for the full table).

    - ``max_regression``: with labels, allow candidate_loss up to
      ``incumbent_loss * (1 + max_regression) + abs_slack``.
    - ``parity_bound``: without labels, allow relative output
      divergence vs the incumbent up to this bound.
    - ``require_finite``: reject non-finite candidate outputs.
    - ``min_eval_rows``: refuse to pass a candidate on fewer rows (an
      empty eval set proves nothing — fail CLOSED, reason
      ``"insufficient_eval"``).
    """

    __slots__ = ("max_regression", "parity_bound", "require_finite",
                 "min_eval_rows", "abs_slack")

    def __init__(self, max_regression: float = 0.05,
                 parity_bound: float = 0.25,
                 require_finite: bool = True,
                 min_eval_rows: int = 1,
                 abs_slack: float = 1e-6):
        if max_regression < 0 or parity_bound < 0:
            raise ValueError("gate bounds must be non-negative")
        self.max_regression = float(max_regression)
        self.parity_bound = float(parity_bound)
        self.require_finite = bool(require_finite)
        self.min_eval_rows = int(min_eval_rows)
        self.abs_slack = float(abs_slack)


class GateVerdict:
    """Structured gate outcome: truthy = candidate may load. A failing
    verdict carries the machine-readable ``reason`` the driver writes
    into the quarantine record."""

    __slots__ = ("passing", "reason", "candidate_score",
                 "incumbent_score", "detail")

    def __init__(self, passing: bool, reason: Optional[str] = None,
                 candidate_score: Optional[float] = None,
                 incumbent_score: Optional[float] = None,
                 detail: Optional[dict] = None):
        self.passing = bool(passing)
        self.reason = reason
        self.candidate_score = candidate_score
        self.incumbent_score = incumbent_score
        self.detail = detail or {}

    def __bool__(self) -> bool:
        return self.passing

    def to_dict(self) -> dict:
        return {"passing": self.passing, "reason": self.reason,
                "candidate_score": self.candidate_score,
                "incumbent_score": self.incumbent_score,
                "detail": self.detail}

    def __repr__(self):
        if self.passing:
            return "GateVerdict(PASS)"
        return f"GateVerdict(FAIL: {self.reason})"


class EvalGate:
    """Score a candidate against the serving incumbent on held-out
    rows. ``score_fn(model, x, y) -> float`` overrides the default
    scorer (MSE vs labels, or vs the incumbent's outputs when
    unlabeled); lower is better either way."""

    def __init__(self, policy: Optional[GatePolicy] = None,
                 score_fn: Optional[Callable] = None):
        self.policy = policy or GatePolicy()
        self.score_fn = score_fn

    def evaluate(self, candidate, incumbent, eval_x,
                 eval_y=None) -> GateVerdict:
        pol = self.policy
        n = 0 if eval_x is None else int(np.asarray(eval_x).shape[0])
        if n < pol.min_eval_rows:
            # fail CLOSED: no evidence is not a pass
            v = GateVerdict(False, "insufficient_eval",
                            detail={"rows": n,
                                    "min_rows": pol.min_eval_rows})
            GATE_VERDICTS.labels(outcome="insufficient_eval").inc()
            return v
        x = np.asarray(eval_x)
        cand_out = _forward(candidate, x)
        if pol.require_finite and not np.all(np.isfinite(cand_out)):
            bad = int(np.size(cand_out) - np.sum(np.isfinite(cand_out)))
            v = GateVerdict(False, "non_finite_outputs",
                            detail={"non_finite_values": bad,
                                    "rows": n})
            GATE_VERDICTS.labels(outcome="non_finite").inc()
            return v
        inc_out = None if incumbent is None else _forward(incumbent, x)
        if self.score_fn is not None:
            cand = float(self.score_fn(candidate, x, eval_y))
            inc = (float(self.score_fn(incumbent, x, eval_y))
                   if incumbent is not None else None)
        elif eval_y is not None:
            y = np.asarray(eval_y)
            cand = _mse(cand_out, y)
            inc = _mse(inc_out, y) if inc_out is not None else None
        else:
            # unlabeled: parity vs the incumbent's function
            cand = (_mse(cand_out, inc_out) if inc_out is not None
                    else 0.0)
            inc = 0.0 if inc_out is not None else None
        detail = {"rows": n, "labeled": eval_y is not None}
        if inc is not None and eval_y is None and self.score_fn is None:
            # parity mode: divergence bound relative to output scale
            scale = float(np.mean(np.abs(inc_out)) ** 2) + pol.abs_slack
            rel = cand / scale
            detail["parity_rel"] = rel
            if rel > pol.parity_bound:
                v = GateVerdict(False, "parity_violation", cand, inc,
                                detail)
                GATE_VERDICTS.labels(outcome="parity_violation").inc()
                return v
        elif inc is not None:
            bound = inc * (1.0 + pol.max_regression) + pol.abs_slack
            detail["bound"] = bound
            if cand > bound:
                v = GateVerdict(False, "scorecard_regression", cand, inc,
                                detail)
                GATE_VERDICTS.labels(
                    outcome="scorecard_regression").inc()
                return v
        GATE_VERDICTS.labels(outcome="pass").inc()
        return GateVerdict(True, None, cand, inc, detail)
