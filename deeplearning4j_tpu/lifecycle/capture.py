"""Seeded live-traffic capture on the serve path.

A :class:`TrafficCapture` hangs off ``ModelServer(capture=...)`` and
samples requests at admission into a JSONL file in the
:class:`~deeplearning4j_tpu.faults.ServingLoad` replay format (arrival
offset + rows + deadline), plus the actual feature values. The one
stream serves three masters:

- **eval set** — :meth:`eval_features` stacks the captured rows into
  the held-out matrix the lifecycle gate scores candidates on, so the
  gate judges on exactly the traffic production sees, not a synthetic
  distribution;
- **chaos input** — :meth:`to_serving_load` rebuilds a ``ServingLoad``
  whose replay reproduces the captured arrival process against any
  server, deterministic end to end;
- **flight evidence** — capture survives the process it ran in:
  :meth:`load` tolerates a truncated trailing record (the crash case)
  the same way the flight recorder does, parsing every complete line
  and skipping the torn tail instead of refusing the file.

Capture must never hurt the serve path: sampling is a seeded counter
(deterministic, like the registry's canary accumulator — exactly
``round(n * sample_rate)`` of any n requests), records are appended
and flushed under a lock, the file is bounded by ``max_records``, and
ANY write failure increments a drop counter instead of raising into
``submit``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu import profiler as _prof

_REG = _prof.get_registry()
CAPTURED = _REG.counter(
    "dl4j_lifecycle_captured_requests_total",
    "Requests sampled into the traffic-capture file")
CAPTURE_DROPPED = _REG.counter(
    "dl4j_lifecycle_capture_dropped_total",
    "Capture records lost to write errors or the max_records bound "
    "(the serve path never pays for a failing capture)")


class TrafficCapture:
    """Append-only JSONL capture of sampled serve-path requests.

    Parameters
    ----------
    path : the JSONL file (created/appended; parent dir must exist).
    sample_rate : fraction of requests to record, applied as a
        deterministic credit accumulator (1.0 = everything).
    max_records : stop recording past this many (bounds disk + replay
        length); excess requests count as dropped.
    clock : injectable monotonic clock for the arrival offsets.
    """

    def __init__(self, path: str, sample_rate: float = 1.0,
                 max_records: int = 10000, clock=None):
        if not 0.0 < sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in (0, 1], got {sample_rate!r}")
        import time as _time
        self.path = path
        self.sample_rate = float(sample_rate)
        self.max_records = int(max_records)
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._acc = 0.0
        self.captured = 0
        self.dropped = 0

    # ------------------------------------------------------------ record
    def record(self, features, deadline: Optional[float] = None) -> bool:
        """Maybe-record one request (called from ``ModelServer.submit``
        after validation). Returns True when the record was written.
        NEVER raises — a broken capture disk must not fail admission."""
        try:
            with self._lock:
                now = self._clock()
                if self._t0 is None:
                    self._t0 = now
                self._acc += self.sample_rate
                if self._acc < 1.0 - 1e-9:
                    return False
                self._acc -= 1.0
                if self.captured >= self.max_records:
                    self.dropped += 1
                    CAPTURE_DROPPED.inc()
                    return False
                x = np.asarray(features)
                rec = {"at": now - self._t0, "rows": int(x.shape[0]),
                       "deadline": deadline,
                       "features": x.tolist()}
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
                self.captured += 1
                CAPTURED.inc()
                return True
        except Exception:
            # count, never raise: the serve path owns the caller's thread
            with self._lock:
                self.dropped += 1
            CAPTURE_DROPPED.inc()
            return False

    # ------------------------------------------------------------- load
    @staticmethod
    def load(path: str) -> List[dict]:
        """Parse every COMPLETE record; a truncated trailing line (the
        process died mid-append) is skipped, flight-recorder style —
        a crash must not poison the eval set it left behind."""
        if not os.path.exists(path):
            return []
        out: List[dict] = []
        with open(path, "rb") as f:
            data = f.read()
        for line in data.split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn tail (or garbage) — skip, keep rest
            if isinstance(rec, dict) and "rows" in rec and "at" in rec:
                out.append(rec)
        return out

    @classmethod
    def to_serving_load(cls, path: str):
        """Rebuild the captured arrival process as a
        :class:`~deeplearning4j_tpu.faults.ServingLoad` — replayable
        against any server/registry as deterministic chaos input."""
        from deeplearning4j_tpu.faults import RequestSpec, ServingLoad
        specs = [RequestSpec(rec["at"], rec["rows"], rec.get("deadline"))
                 for rec in cls.load(path)]
        return ServingLoad(specs)

    @classmethod
    def eval_features(cls, path: str, max_rows: Optional[int] = None
                      ) -> Optional[np.ndarray]:
        """Stack the captured feature rows into one [n, ...] eval
        matrix (None when the capture is empty or held no features)."""
        rows = []
        for rec in cls.load(path):
            feats = rec.get("features")
            if feats is None:
                continue
            x = np.asarray(feats, dtype=np.float32)
            if x.ndim >= 1:
                rows.append(x)
            if max_rows is not None and sum(r.shape[0] for r in rows) \
                    >= max_rows:
                break
        if not rows:
            return None
        out = np.concatenate(rows, axis=0)
        return out[:max_rows] if max_rows is not None else out
