"""The autotuner search driver (ISSUE 17).

Search shape, per the TVM loop (PAPERS.md): a cheap EXPLORE pass
(seeded random sample, 1 timing rep each), a SUCCESSIVE-HALVING pass
(the top half re-measured at full min-of-reps fidelity), then a GREEDY
REFINEMENT walk (single-axis mutations of the incumbent, axis order
seeded by ``DeviceTimeTable.top_offenders`` so conv-dominated profiles
try the layout/fusion seams first).  Every trial dispatches through the
networks' normal ``CachedDispatch`` seam, so with the persistent
compile cache configured each candidate is AOT-cached the first time it
is seen and near-free to revisit — in this process or the next.

The winner is gated by a LOSS-PARITY guard (the PR-14 bench machinery:
same-seed loss curves, deltas bounded at 10% of curve scale) before it
is persisted or left applied — a tuned plan can never silently change
numerics; a candidate that fails parity is discarded and the next-best
one is gated instead, all the way down to the default plan.
"""

from __future__ import annotations

import math
import time
import warnings
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.profiler import metrics as _metrics
from deeplearning4j_tpu.profiler.locks import InstrumentedLock
from deeplearning4j_tpu.utils.concurrent import ErrorLatch
from deeplearning4j_tpu.tune import records as _records
from deeplearning4j_tpu.tune.space import (TuningPlan, TuningSpace,
                                           axis_priority)

_REG = _metrics.get_registry()
TRIALS_TOTAL = _REG.counter(
    "dl4j_tune_trials_total",
    "Autotuner trials evaluated (one timing measurement per increment)",
    ("model",))
BEST_MFU = _REG.gauge(
    "dl4j_tune_best_mfu",
    "Best model FLOPs utilization found by the autotuner for a model",
    ("model",))

#: Default parity bound — the PR-14 bench guard's bound: per-step loss
#: deltas under 10% of the curve's scale count as "same training".
PARITY_TOL = 0.10


class Trial:
    """One timing measurement of one plan."""

    def __init__(self, plan: TuningPlan, cost_s: float, phase: str,
                 reps: int, error: Optional[str] = None):
        self.plan = plan
        self.cost_s = float(cost_s)
        self.phase = phase                 # default|explore|halving|refine
        self.reps = int(reps)
        self.error = error

    @property
    def ok(self) -> bool:
        return self.error is None and math.isfinite(self.cost_s)

    def __repr__(self):
        c = f"{self.cost_s * 1e3:.2f}ms" if self.ok else "FAILED"
        return f"Trial({self.phase}, {self.plan.signature()}, {c})"


class TuneResult:
    """What a tuning run produced: the gated winner, the baseline, the
    full trial log, and the persisted record (if any)."""

    def __init__(self, best_plan: TuningPlan, best_cost_s: float,
                 default_cost_s: float, trials: List[Trial],
                 record=None, model_fp: str = "",
                 rejected: Optional[List[tuple]] = None,
                 mfu: Optional[float] = None,
                 pruned: Optional[List[tuple]] = None):
        self.best_plan = best_plan
        self.best_cost_s = float(best_cost_s)
        self.default_cost_s = float(default_cost_s)
        self.trials = trials
        self.record = record
        self.model_fp = model_fp
        self.rejected = rejected or []     # [(plan, reason)]
        self.mfu = mfu
        self.pruned = pruned or []         # [(plan, reason)] — never measured

    @property
    def speedup(self) -> float:
        if self.best_cost_s <= 0:
            return 1.0
        return self.default_cost_s / self.best_cost_s

    def summary(self) -> str:
        lines = [f"{'phase':8} {'ms/step':>9}  plan"]
        for t in self.trials:
            c = f"{t.cost_s * 1e3:9.2f}" if t.ok else "   FAILED"
            lines.append(f"{t.phase:8} {c}  {t.plan.signature()}")
        lines.append(
            f"best: {self.best_plan.signature()}  "
            f"{self.best_cost_s * 1e3:.2f} ms/step "
            f"(default {self.default_cost_s * 1e3:.2f} ms/step, "
            f"{self.speedup:.2f}x)")
        for plan, reason in self.rejected:
            lines.append(f"rejected: {plan.signature()} — {reason}")
        if self.pruned:
            lines.append(f"statically pruned (cost model, no measurement "
                         f"spent): {len(self.pruned)} candidate(s)")
            for plan, reason in self.pruned:
                lines.append(f"pruned: {plan.signature()} — {reason}")
        return "\n".join(lines)


# ------------------------------------------------------------ measurement
def _sync(model):
    """Block until the model's device work drains — the timing fence."""
    import jax
    jax.block_until_ready(model._params)


def _measure_plan(model, plan: TuningPlan, features, labels, *,
                  reps: int, base_steps: int) -> float:
    """Min-of-reps per-step seconds for ``plan`` applied to ``model``.

    One unmeasured warm pass first (the compile / AOT-cache load), then
    ``reps`` timed passes of ``k * m ~= base_steps`` real update steps
    through the public ``fit`` path — megastep scan, prefetcher, and
    host bookkeeping included, because those are exactly what the K and
    prefetch axes trade against."""
    from deeplearning4j_tpu.data.dataset import DataSet
    kw = plan.apply(model)
    k = kw["steps_per_dispatch"]
    m = max(1, int(round(base_steps / k)) or 1)
    n_steps = k * m
    batches = [DataSet(features, labels) for _ in range(n_steps)]
    fit_kw = dict(steps_per_dispatch=k, prefetch=kw["prefetch"])
    model.fit(batches, **fit_kw)           # warm (uncounted)
    _sync(model)
    best = math.inf
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        model.fit(batches, **fit_kw)
        _sync(model)
        best = min(best, (time.perf_counter() - t0) / n_steps)
    return best


def estimate_mfu(model, batch: int, cost_s: float,
                 peak_flops: Optional[float] = None,
                 train_factor: float = 3.0) -> Optional[float]:
    """Model FLOPs utilization for one tuned step from the analyzer's
    jax-free FLOP model (forward FLOPs x ~3 for the update step)."""
    from deeplearning4j_tpu.profiler import devicetime as _dt
    try:
        flops = sum(f for _, _, f in _dt.layer_flop_model(model.conf))
    except Exception:
        return None
    if not flops or cost_s <= 0:
        return None
    peak = peak_flops if peak_flops else _dt.DEFAULT_PEAK_FLOPS
    return (flops * int(batch) * train_factor) / (cost_s * peak)


# ------------------------------------------------------------ parity guard
def loss_parity(factory: Callable[[], object], plan: TuningPlan,
                features, labels, *, steps: int = 6,
                tol: float = PARITY_TOL) -> bool:
    """Same-seed loss curves, default plan vs ``plan``, per-step deltas
    bounded at ``tol`` of the curve's own scale (the PR-14
    ``_loss_parity`` bound).  ``factory`` must return a fresh,
    deterministically-seeded network each call."""
    from deeplearning4j_tpu.data.dataset import DataSet
    ds = DataSet(features, labels)

    def curve(tuned: bool) -> List[float]:
        net = factory()
        if tuned:
            plan.apply(net)
        losses = []
        for _ in range(steps):
            net.fit(ds)
            losses.append(float(net.score()))
        return losses

    la, lb = curve(False), curve(True)
    scale = max(abs(la[0]), 1e-6)
    return max(abs(a - b) / scale for a, b in zip(la, lb)) < tol


# ------------------------------------------------------------------ search
def tune(model_or_factory, features, labels, *, budget: int = 20,
         reps: int = 3, base_steps: int = 8, seed: int = 0,
         space: Optional[TuningSpace] = None, mesh=None,
         backend: Optional[str] = None, model_name: Optional[str] = None,
         persist: bool = True, parity_guard: bool = True,
         parity_steps: int = 6, parity_tol: float = PARITY_TOL,
         timings=None, peak_flops: Optional[float] = None,
         trial_fn: Optional[Callable[[TuningPlan], float]] = None,
         parity_fn: Optional[Callable[[TuningPlan], bool]] = None,
         cost_spec=None, pruner=None, prune_bound: float = 3.0
         ) -> TuneResult:
    """Search ``space`` for the fastest plan on live hardware.

    ``model_or_factory``: a zero-arg callable returning a fresh,
    deterministically-seeded network (enables the parity guard), or a
    live network instance (parity is skipped with a warning — there is
    no way to rebuild the untuned twin).  ``budget`` caps the number of
    timing measurements, baseline included.  ``timings`` (a
    ``DeviceTimeTable``) seeds the refinement axis order from measured
    top offenders.  ``trial_fn``/``parity_fn`` replace the real
    measurement / parity check — the mock-cost harness used by the
    planted-optimum tests, and the seam a future learned cost model
    plugs into.

    ``cost_spec`` (a :class:`~deeplearning4j_tpu.analysis.cost.CostSpec`,
    chip name, or dict) turns on STATIC PRUNING: before any non-default
    candidate is measured, the analysis.cost model predicts its step
    peak and step time — a candidate that OOMs the declared chip or
    predicts slower than ``prune_bound`` x the default plan's prediction
    is dropped without spending a measurement, recorded on
    ``TuneResult.pruned`` with the reason.  ``pruner`` overrides the
    auto-built one (any ``plan -> Optional[reason]`` callable).  The
    incumbent default plan is never offered for pruning.

    The model the search measured is left with the WINNING plan applied.
    The winner is persisted to the record store (``persist=True``) under
    the (model fingerprint, mesh, backend, jax version) key, where
    ``fit(tune="auto")`` / ``warmup(tuned=True)`` / the serving registry
    will find it.
    """
    factory = model_or_factory if callable(model_or_factory) else None
    model = factory() if factory is not None else model_or_factory
    if space is None:
        space = TuningSpace.for_model(model)
    budget = max(2, int(budget))
    label = model_name or type(model).__name__
    trials_counter = TRIALS_TOTAL.labels(model=label)

    book: Dict[str, Trial] = {}    # plan signature -> best trial so far
    log: List[Trial] = []
    book_lock = InstrumentedLock("tune:driver")
    latch = ErrorLatch()
    spent = [0]                    # measurements consumed against budget

    default = space.default_plan()
    pruned: List[tuple] = []       # [(plan, reason)] — dropped unmeasured
    pruned_sigs: set = set()
    if pruner is None and cost_spec is not None:
        from deeplearning4j_tpu.analysis import cost as _cost
        try:
            pruner = _cost.plan_pruner(model, None if features is None
                                       else getattr(features, "shape",
                                                    (None,))[0],
                                       cost_spec, mesh=mesh,
                                       bound=prune_bound)
        except Exception as e:     # an unlowerable harness object: search
            warnings.warn(         # without pruning rather than die
                f"tune: static pruning disabled — the cost model cannot "
                f"lower this model ({type(e).__name__}: {e})",
                stacklevel=2)
            pruner = None

    def evaluate(plan: TuningPlan, phase: str, n_reps: int
                 ) -> Optional[Trial]:
        sig = plan.signature()
        with book_lock:
            prev = book.get(sig)
            if prev is not None and prev.reps >= n_reps:
                return prev        # already measured at >= this fidelity
        # static domination check — BEFORE the measurement is spent; the
        # default plan (the yardstick) is never offered for pruning
        if pruner is not None and plan != default:
            with book_lock:
                if sig in pruned_sigs:
                    return None
            try:
                reason = pruner(plan)
            except Exception:      # a pruner bug must not cost coverage
                reason = None
            if reason is not None:
                with book_lock:
                    pruned_sigs.add(sig)
                    pruned.append((plan, reason))
                return None
        spent[0] += 1
        trials_counter.inc()
        try:
            with _prof.trace_span("tune:trial", plan=sig, phase=phase):
                if trial_fn is not None:
                    cost = float(trial_fn(plan))
                else:
                    cost = _measure_plan(model, plan, features, labels,
                                         reps=n_reps,
                                         base_steps=base_steps)
            t = Trial(plan, cost, phase, n_reps)
        except Exception as e:  # one broken candidate must not kill the run
            latch.record(e)
            t = Trial(plan, math.inf, phase, n_reps,
                      error=f"{type(e).__name__}: {e}")
        with book_lock:
            log.append(t)
            if t.ok and (sig not in book or t.cost_s < book[sig].cost_s
                         or t.reps > book[sig].reps):
                book[sig] = t
        return t if t.ok else None

    # ---- baseline: the default plan is trial #0 and the yardstick
    base = evaluate(default, "default", reps)
    if base is None:
        # the DEFAULT plan failing is not a tuning result — re-raise
        err = latch.take()
        raise RuntimeError("autotuner baseline trial failed") from err
    default_cost = base.cost_s

    # ---- explore: seeded random sample at 1-rep fidelity
    explore_n = min(space.size - 1, max(1, (budget - spent[0]) * 2 // 3))
    sampled = [p for p in space.sample(explore_n + 1, seed)
               if p != default][:explore_n]
    for plan in sampled:
        if spent[0] >= budget:
            break
        evaluate(plan, "explore", 1)

    # ---- successive halving: survivors re-measured at full fidelity
    with book_lock:
        ranked = sorted((t for t in book.values() if t.plan != default),
                        key=lambda t: t.cost_s)
    for t in ranked[:max(1, math.ceil(len(ranked) / 2))]:
        if spent[0] >= budget:
            break
        evaluate(t.plan, "halving", reps)

    # ---- greedy refinement around the incumbent, offender-seeded order
    order = axis_priority(timings)

    def incumbent() -> Trial:
        with book_lock:
            return min(book.values(), key=lambda t: t.cost_s)

    improved = True
    while improved and spent[0] < budget:
        improved = False
        cur = incumbent()
        for _axis, nb in space.neighbors(cur.plan, order):
            if spent[0] >= budget:
                break
            with book_lock:
                seen = nb.signature() in book
            if seen:
                continue
            t = evaluate(nb, "refine", reps)
            if t is not None and t.cost_s < cur.cost_s:
                improved = True
                break              # re-anchor the walk on the new best

    # ---- parity gate, best-first, falling back toward the default
    with book_lock:
        candidates = sorted(book.values(), key=lambda t: t.cost_s)
    rejected: List[tuple] = []
    check = parity_fn
    if check is None and parity_guard:
        if factory is not None:
            check = lambda p: loss_parity(factory, p, features, labels,
                                          steps=parity_steps,
                                          tol=parity_tol)
        else:
            warnings.warn(
                "tune: parity guard skipped — pass a model FACTORY "
                "(not a live instance) so the default-plan twin can be "
                "rebuilt for the same-seed loss comparison", stacklevel=2)
    winner = base
    for t in candidates:
        if t.plan == default:
            winner = t
            break                  # the default trivially passes parity
        if check is not None and not check(t.plan):
            rejected.append((t.plan, "loss parity failed — plan changes "
                                     "numerics beyond the "
                                     f"{parity_tol:.0%} bound"))
            continue
        winner = t
        break

    # leave the measured model in the winning state (the search walked
    # it through arbitrary plans)
    if trial_fn is None:
        winner.plan.apply(model)

    mfu = None
    if features is not None and getattr(features, "shape", None):
        mfu = estimate_mfu(model, features.shape[0], winner.cost_s,
                           peak_flops=peak_flops)
        if mfu is not None:
            BEST_MFU.labels(model=label).set(mfu)

    record = None
    try:
        fp = _records.model_fingerprint(model)
    except Exception:
        fp = ""        # a trial_fn harness may tune a non-network object
    if persist and not fp:
        persist = False
        warnings.warn("tune: model has no config fingerprint — winner "
                      "not persisted", stacklevel=2)
    if persist:
        record = _records.TuningRecord(
            fp, winner.plan, cost_s=winner.cost_s,
            default_cost_s=default_cost, mfu=mfu, trials=spent[0],
            mesh=mesh, backend=backend, model_name=label)
        if _records.put(record) is None:
            record = None
    return TuneResult(winner.plan, winner.cost_s, default_cost, log,
                      record=record, model_fp=fp, rejected=rejected,
                      mfu=mfu, pruned=pruned)


#: The tuning report type the serving/bench surfaces name — the search
#: result IS the report (trials, rejections, static prunes, summary()).
TuningReport = TuneResult
