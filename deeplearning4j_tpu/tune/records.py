"""Persistent tuning-record store (ISSUE 17).

Winning :class:`~deeplearning4j_tpu.tune.space.TuningPlan`\\ s are
durable artifacts, not one-off bench settings (the TensorFlow-Serving
saved-model discipline): one record file per (model architecture
fingerprint x mesh x backend x jax version) key, written atomically,
checksummed, and quarantined on content damage — the exact discipline
``nn.compilecache.DiskCompileCache`` uses for serialized executables,
so the two stores can share a fleet filesystem and the same failure
model.  A record that survives :func:`lookup` is what
``fit(tune="auto")`` / ``warmup(tuned=True)`` / ``ModelRegistry.load
(tuned=True)`` auto-apply.

Layout of a record file (``tr_<sha256>.json``)::

    DL4JTR1\\n
    {"format": 1, "sha256": <payload sha>, "created": <ts>}\\n
    <record JSON payload>

Key facts mirrored from the compile cache: an OSError on read is a
transient miss (stale NFS handles on a fleet share are not corruption);
a bad magic / truncated header / checksum mismatch renames the file to
``quarantine_*`` so one damaged entry can never wedge every process
that maps to it.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import warnings
from typing import Optional

from deeplearning4j_tpu.profiler.locks import InstrumentedLock
from deeplearning4j_tpu.tune.space import TuningPlan

_MAGIC = b"DL4JTR1\n"
_FORMAT = 1

#: Environment override for the record directory.
ENV_DIR = "DL4J_TPU_TUNE_DIR"
_DEFAULT_DIR = os.path.join("~", ".cache", "deeplearning4j_tpu", "tune")

_CONFIGURED_DIR: Optional[str] = os.environ.get(ENV_DIR)
_ENABLED = True


def configure(directory: Optional[str]) -> None:
    """Set the record directory for this process (overriding
    ``DL4J_TPU_TUNE_DIR``); ``configure(None)`` disables the store —
    lookups miss, puts are dropped with a warning."""
    global _CONFIGURED_DIR, _ENABLED
    _CONFIGURED_DIR = directory
    _ENABLED = directory is not None


def reset_configuration() -> None:
    """Restore env/default resolution (test isolation hook)."""
    global _CONFIGURED_DIR, _ENABLED
    _CONFIGURED_DIR = os.environ.get(ENV_DIR)
    _ENABLED = True


def record_dir(create: bool = False) -> Optional[str]:
    """The active record directory (configured > env > user cache), or
    None when the store is disabled."""
    if not _ENABLED:
        return None
    d = _CONFIGURED_DIR if _CONFIGURED_DIR is not None \
        else os.environ.get(ENV_DIR)
    if d is None:
        d = os.path.expanduser(_DEFAULT_DIR)
    if create:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return None
    return d


# ------------------------------------------------------------------ keys
def mesh_signature(mesh) -> str:
    """Stable identity of the mesh/sharding context a plan was tuned
    under: a plan tuned on an 8-way data mesh must not auto-apply to a
    2x4 model-parallel one.  Accepts None (single-host default), a
    ``ShardedTrainingPlan``/``DeviceMesh`` (their ``signature()``), or a
    plain label string (the CLI's ``--mesh``)."""
    if mesh is None:
        return "none"
    sig = getattr(mesh, "signature", None)
    if callable(sig):
        try:
            return str(sig())
        except Exception:
            pass
    if isinstance(mesh, str):
        return mesh
    # a DeviceMesh wraps the jax Mesh at .mesh; jax Mesh.shape is an
    # axis->size mapping — "data=8xmodel=1" is stable across processes
    # with the same topology, which is exactly the sharing we want
    for m in (mesh, getattr(mesh, "mesh", None)):
        shape = getattr(m, "shape", None)
        if shape is not None:
            try:
                return "x".join(f"{k}={v}" for k, v in dict(shape).items())
            except (TypeError, ValueError):
                continue
    return type(mesh).__name__


def _backend(backend: Optional[str]) -> str:
    if backend is not None:
        return str(backend)
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    try:
        return jax.default_backend()
    except Exception:
        return "unknown"


def _jax_version() -> str:
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        try:
            import jax       # the driver imported it; analysis may not
        except Exception:
            return "unknown"
    return getattr(jax, "__version__", "unknown")


def record_key(model_fp: str, mesh=None, backend: Optional[str] = None
               ) -> str:
    """SHA-256 key over (model fingerprint, mesh signature, backend,
    jax version) — the compile cache's key shape, minus the per-program
    content hash: ONE best plan per deployment context."""
    parts = (str(model_fp), mesh_signature(mesh), _backend(backend),
             _jax_version())
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


#: Config keys the tuning plan itself writes when applied — the model's
#: IDENTITY must be computed modulo these, or applying the winning plan
#: would change the fingerprint and the record would stop matching the
#: very model it tuned (the next ``fit(tune="auto")`` would miss).
_SEAM_KEYS = frozenset({"compute_layout", "data_format"})


def _scrub_seams(node):
    if isinstance(node, dict):
        return {k: _scrub_seams(v) for k, v in node.items()
                if k not in _SEAM_KEYS}
    if isinstance(node, list):
        return [_scrub_seams(v) for v in node]
    return node


def model_fingerprint(model) -> str:
    """Stable identity of the model ARCHITECTURE: the config JSON hashed
    with the tunable-seam keys scrubbed at every depth, so a plan's
    ``apply()`` (which stamps ``compute_layout``/``data_format`` into
    the config) is fingerprint-neutral.  Falls back to the compile
    cache's raw fingerprint when the config does not serialize."""
    from deeplearning4j_tpu.nn import compilecache as _cc
    conf = getattr(model, "conf", model)
    try:
        cfg = _scrub_seams(json.loads(conf.to_json()))
    except Exception:
        return _cc.model_fingerprint(model)
    return hashlib.sha256(
        json.dumps(cfg, sort_keys=True).encode()).hexdigest()[:16]


# --------------------------------------------------------------- records
class TuningRecord:
    """One persisted tuning result: the winning plan plus enough
    context (costs, trial count, provenance) to audit it later."""

    def __init__(self, model_fp: str, plan: TuningPlan, *,
                 cost_s: float, default_cost_s: Optional[float] = None,
                 mfu: Optional[float] = None, trials: int = 0,
                 mesh=None, backend: Optional[str] = None,
                 model_name: Optional[str] = None,
                 created: Optional[float] = None):
        self.model_fp = str(model_fp)
        self.plan = plan
        self.cost_s = float(cost_s)
        self.default_cost_s = None if default_cost_s is None \
            else float(default_cost_s)
        self.mfu = None if mfu is None else float(mfu)
        self.trials = int(trials)
        self.mesh_sig = mesh_signature(mesh)
        self.backend = _backend(backend)
        self.model_name = model_name
        self.created = time.time() if created is None else float(created)

    @property
    def speedup(self) -> Optional[float]:
        if not self.default_cost_s or self.cost_s <= 0:
            return None
        return self.default_cost_s / self.cost_s

    def to_json(self) -> dict:
        return {"model_fp": self.model_fp,
                "plan": self.plan.to_config(),
                "signature": self.plan.signature(),
                "cost_s": self.cost_s,
                "default_cost_s": self.default_cost_s,
                "mfu": self.mfu,
                "trials": self.trials,
                "mesh": self.mesh_sig,
                "backend": self.backend,
                "model_name": self.model_name,
                "created": self.created}

    @classmethod
    def from_json(cls, d: dict) -> "TuningRecord":
        return cls(d["model_fp"], TuningPlan.from_config(d["plan"]),
                   cost_s=d["cost_s"],
                   default_cost_s=d.get("default_cost_s"),
                   mfu=d.get("mfu"), trials=d.get("trials", 0),
                   mesh=d.get("mesh"), backend=d.get("backend"),
                   model_name=d.get("model_name"),
                   created=d.get("created"))


def _path(key: str) -> Optional[str]:
    d = record_dir()
    if d is None:
        return None
    return os.path.join(d, f"tr_{key}.json")


def _quarantine(path: str, reason: str) -> None:
    dst = os.path.join(os.path.dirname(path),
                       "quarantine_" + os.path.basename(path))
    try:
        os.replace(path, dst)
    except OSError:
        return
    warnings.warn(f"tuning records: quarantined corrupt entry {path}: "
                  f"{reason}", stacklevel=3)


def put(record: TuningRecord) -> Optional[str]:
    """Atomically persist ``record`` under its deployment key (temp +
    ``os.replace`` — same crash/concurrent-writer guarantees as the
    compile cache).  Returns the path, or None when the store is
    disabled/unwritable (a tuning run must never die on a read-only
    share)."""
    d = record_dir(create=True)
    if d is None:
        if not _ENABLED:
            warnings.warn("tuning records: store is disabled "
                          "(configure(None)) — winner not persisted",
                          stacklevel=2)
        return None
    key = record_key(record.model_fp, record.mesh_sig, record.backend)
    path = os.path.join(d, f"tr_{key}.json")
    payload = json.dumps(record.to_json(), sort_keys=True).encode()
    header = {"format": _FORMAT,
              "sha256": hashlib.sha256(payload).hexdigest(),
              "created": time.time()}
    tmp = os.path.join(d, f".tmp_tr_{key[:16]}_{os.getpid()}_"
                          f"{threading.get_ident()}")
    try:
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(json.dumps(header).encode() + b"\n")
            f.write(payload)
        os.replace(tmp, path)
    except OSError as e:
        try:
            os.remove(tmp)
        except OSError:
            pass
        warnings.warn(f"tuning records: write failed ({e}) — winner not "
                      f"persisted", stacklevel=2)
        return None
    return path


def lookup(model, mesh=None, backend: Optional[str] = None
           ) -> Optional[TuningRecord]:
    """The record for (model, mesh, backend, this jax version), or None.
    ``model`` may be a network/config (fingerprinted here) or an
    already-computed fingerprint string."""
    fp = model if isinstance(model, str) else model_fingerprint(model)
    key = record_key(fp, mesh, backend)
    path = _path(key)
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"bad magic {magic!r}")
            header = json.loads(f.readline().decode())
            payload = f.read()
    except FileNotFoundError:
        return None
    except OSError:
        # transient I/O on a fleet share is NOT corruption — miss now,
        # retry next process (compile-cache discipline)
        return None
    except (ValueError, UnicodeDecodeError) as e:
        _quarantine(path, str(e))
        return None
    if header.get("format") != _FORMAT:
        return None
    digest = hashlib.sha256(payload).hexdigest()
    if digest != header.get("sha256"):
        _quarantine(path, f"payload checksum mismatch (header "
                          f"{str(header.get('sha256'))[:12]}..., actual "
                          f"{digest[:12]}...)")
        return None
    try:
        return TuningRecord.from_json(json.loads(payload.decode()))
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        _quarantine(path, f"undecodable record: {e}")
        return None


def best_plan(model, mesh=None, backend: Optional[str] = None
              ) -> Optional[TuningPlan]:
    """The winning plan for this deployment context, or None."""
    rec = lookup(model, mesh=mesh, backend=backend)
    return rec.plan if rec is not None else None


# ------------------------------------------------------------ auto-apply
# one fallback warning per (model fingerprint, mesh, backend) per
# process — fit() runs every epoch loop, and a warning storm is worse
# than no warning
_WARNED = set()
_WARNED_LOCK = InstrumentedLock("tune:records")


def auto_apply(model, mesh=None, backend: Optional[str] = None,
               context: str = "fit") -> Optional[TuningPlan]:
    """Consult the store and apply the winning plan to ``model`` —
    the ``tune="auto"`` / ``tuned=True`` entry point.  Returns the
    applied plan, or None (with ONE warning per deployment key) when no
    record exists; defaults then stand."""
    fp = model_fingerprint(model)
    rec = lookup(fp, mesh=mesh, backend=backend)
    if rec is None:
        key = record_key(fp, mesh, backend)
        with _WARNED_LOCK:
            first = key not in _WARNED
            _WARNED.add(key)
        if first:
            warnings.warn(
                f"tune: no tuning record for this (model, mesh, backend) "
                f"— {context} falls back to default plan settings; run "
                f"`python -m deeplearning4j_tpu.tune <model>` to tune "
                f"and persist one", stacklevel=3)
        return None
    rec.plan.apply(model)
    return rec.plan


def reset_warned() -> None:
    """Test hook: forget which deployment keys already warned."""
    with _WARNED_LOCK:
        _WARNED.clear()
