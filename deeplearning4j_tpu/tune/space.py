"""Declarative tuning space over the optimization seams (ISSUE 17).

The TVM insight (PAPERS.md) applied to this stack: every performance
knob the repo grew — conv compute layout (PR 14), fused epilogues
(PR 14), ``steps_per_dispatch`` megasteps (PR 2), mixed precision
(PR 11), prefetch depth, serving bucket ladders (PR 7/12), sharding
plans (PR 15) — is already a *seam*: a setter whose change busts the
compiled-step caches exactly once and whose value is part of the
persistent compile-cache key. A :class:`TuningPlan` is one point in the
cross product of those seams; a :class:`TuningSpace` enumerates the
points deterministically so a search driver (``tune.driver``) can walk
them and a record store (``tune.records``) can persist the winner under
a stable :meth:`TuningPlan.signature`.

This module is jax-free at import (the plan applies itself through the
models' own setters); it must stay importable in analysis/CLI contexts
that never touch a device.
"""

from __future__ import annotations

import itertools
import random
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Axis names in CANONICAL ORDER — signatures, enumeration order, and
#: the greedy refinement walk all follow it, so two processes building
#: the same space agree on plan identity and trial order.
AXES = ("compute_layout", "fuse_epilogues", "steps_per_dispatch",
        "precision", "prefetch", "bucket_limit", "sharding")

_LAYOUTS = ("NCHW", "NHWC")
#: Megastep K candidates (ISSUE-17 spec): 1 = plain per-batch dispatch.
K_CHOICES = (1, 4, 8, 16)


def _sharding_sig(value) -> Optional[str]:
    """A sharding-axis value is None or an object with ``signature()``
    (a ``ShardedTrainingPlan`` / ZeRO variant); records persist the
    signature string, so a restored plan may carry the bare string."""
    if value is None:
        return None
    sig = getattr(value, "signature", None)
    return sig() if callable(sig) else str(value)


class TuningPlan:
    """One candidate assignment over the optimization seams.

    Immutable by convention (use :meth:`replace`); equality and hashing
    follow :meth:`signature`, so a search driver can dedupe revisits and
    the record store can key winners stably across processes.
    """

    def __init__(self, compute_layout: str = "NCHW",
                 fuse_epilogues: bool = False,
                 steps_per_dispatch: int = 1,
                 precision: Optional[str] = None,
                 prefetch: int = 2,
                 bucket_limit: Optional[int] = None,
                 sharding=None):
        if compute_layout not in _LAYOUTS:
            raise ValueError(f"compute_layout must be one of {_LAYOUTS}, "
                             f"got {compute_layout!r}")
        if int(steps_per_dispatch) < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if int(prefetch) < 0:
            raise ValueError("prefetch must be >= 0")
        self.compute_layout = compute_layout
        self.fuse_epilogues = bool(fuse_epilogues)
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.precision = precision      # None (fp32) or a policy string
        self.prefetch = int(prefetch)
        self.bucket_limit = None if bucket_limit is None \
            else int(bucket_limit)
        self.sharding = sharding

    # ------------------------------------------------------------ identity
    def signature(self) -> str:
        """Stable, human-greppable identity — the record-store key
        component and the dedupe key for trial revisits."""
        return (f"layout={self.compute_layout}"
                f";fuse={int(self.fuse_epilogues)}"
                f";k={self.steps_per_dispatch}"
                f";prec={self.precision or 'fp32'}"
                f";prefetch={self.prefetch}"
                f";buckets={self.bucket_limit if self.bucket_limit else '-'}"
                f";shard={_sharding_sig(self.sharding) or '-'}")

    def __repr__(self):
        return f"TuningPlan({self.signature()})"

    def __eq__(self, other):
        return isinstance(other, TuningPlan) \
            and other.signature() == self.signature()

    def __hash__(self):
        return hash(self.signature())

    # ---------------------------------------------------------- transforms
    def replace(self, **kv) -> "TuningPlan":
        cfg = {a: getattr(self, a) for a in AXES}
        cfg.update(kv)
        return TuningPlan(**cfg)

    def to_config(self) -> dict:
        """JSON-serializable form for the record store. The sharding
        axis degrades to its signature string — an attached
        ``ShardedTrainingPlan`` holds live mesh/device handles that
        cannot round-trip a process boundary; the record's KEY already
        carries the mesh, so the string is informational."""
        return {"compute_layout": self.compute_layout,
                "fuse_epilogues": self.fuse_epilogues,
                "steps_per_dispatch": self.steps_per_dispatch,
                "precision": self.precision,
                "prefetch": self.prefetch,
                "bucket_limit": self.bucket_limit,
                "sharding": _sharding_sig(self.sharding)}

    @classmethod
    def from_config(cls, cfg: dict) -> "TuningPlan":
        known = {k: cfg[k] for k in AXES if k in cfg}
        return cls(**known)

    # ------------------------------------------------------------- applying
    def apply(self, model) -> dict:
        """Apply the model-level seams to ``model`` (layout, fusion,
        precision) and return the FIT-level knobs as kwargs
        (``steps_per_dispatch``, ``prefetch``) for the caller's
        ``fit``/megastep loop.  Each setter is signature-keyed: applying
        an equal plan twice keeps the compiled-step caches (zero
        steady-state recompiles).  The sharding axis is NOT re-attached
        here — a restored plan only carries its signature string, and
        tuning runs inside the caller's chosen mesh (the record key
        separates meshes)."""
        if hasattr(model, "setComputeLayout"):
            model.setComputeLayout(self.compute_layout)
        if hasattr(model, "setEpilogueFusion"):
            model.setEpilogueFusion(self.fuse_epilogues)
        if hasattr(model, "setPrecisionPolicy"):
            model.setPrecisionPolicy(self.precision)
        if self.sharding is not None and hasattr(self.sharding, "mesh") \
                and hasattr(model, "setShardingPlan"):
            model.setShardingPlan(self.sharding)
        return {"steps_per_dispatch": self.steps_per_dispatch,
                "prefetch": self.prefetch}


class TuningSpace:
    """The cross product of per-axis candidate values.

    ``axes`` maps axis name -> value tuple; missing axes pin to the
    :class:`TuningPlan` default.  Enumeration is deterministic
    (itertools.product in canonical ``AXES`` order) and sampling is
    seeded, so the same (space, seed, budget) triple visits the same
    plans on every host — a property the record store's cross-process
    key-identity test pins.
    """

    def __init__(self, axes: Dict[str, Sequence]):
        unknown = set(axes) - set(AXES)
        if unknown:
            raise ValueError(f"unknown tuning axes {sorted(unknown)}; "
                             f"valid axes: {list(AXES)}")
        defaults = TuningPlan()
        self.axes: Dict[str, Tuple] = {}
        for name in AXES:       # canonical order, defaults filled in
            vals = tuple(axes.get(name, (getattr(defaults, name),)))
            if not vals:
                vals = (getattr(defaults, name),)
            self.axes[name] = vals

    @classmethod
    def for_model(cls, model=None, *, serving: bool = False,
                  sharding_variants: Sequence = (),
                  max_steps_per_dispatch: int = 16) -> "TuningSpace":
        """The default search space for a network: both conv layouts,
        fusion on/off, megastep K, bf16-vs-fp32, prefetch depth.  A
        model without conv layers (no ``setComputeLayout`` consumer
        benefit) keeps the layout/fusion axes anyway — they are cheap
        no-ops there and the K/precision axes dominate; callers with
        tighter budgets pass explicit ``axes``.  ``serving=True`` adds
        the bucket-ladder cap axis; distributed runs pass live
        ``ShardedTrainingPlan`` objects as ``sharding_variants``."""
        ks = tuple(k for k in K_CHOICES if k <= max_steps_per_dispatch)
        axes = {"compute_layout": _LAYOUTS,
                "fuse_epilogues": (False, True),
                "steps_per_dispatch": ks or (1,),
                "precision": (None, "bf16"),
                "prefetch": (0, 2, 4)}
        if serving:
            axes["bucket_limit"] = (None, 8, 32)
        if sharding_variants:
            axes["sharding"] = (None,) + tuple(sharding_variants)
        return cls(axes)

    # ---------------------------------------------------------- enumeration
    @property
    def size(self) -> int:
        n = 1
        for vals in self.axes.values():
            n *= len(vals)
        return n

    def default_plan(self) -> TuningPlan:
        return TuningPlan()

    def enumerate_plans(self) -> List[TuningPlan]:
        """Every plan, deterministic order (product over canonical axis
        order, values in declaration order)."""
        names = list(self.axes)
        plans = []
        for combo in itertools.product(*(self.axes[n] for n in names)):
            plans.append(TuningPlan(**dict(zip(names, combo))))
        return plans

    def sample(self, n: int, seed: int = 0) -> List[TuningPlan]:
        """``n`` distinct plans, seeded — the random phase of the search.
        Sampling enumerates first (spaces here are small — tens to a few
        thousand points) so identical (seed, n) pairs agree across
        hosts regardless of hash randomization."""
        plans = self.enumerate_plans()
        if n >= len(plans):
            return plans
        return random.Random(int(seed)).sample(plans, n)

    def neighbors(self, plan: TuningPlan,
                  axis_order: Optional[Iterable[str]] = None
                  ) -> List[Tuple[str, TuningPlan]]:
        """Single-axis mutations of ``plan`` — the greedy-refinement
        moves.  ``axis_order`` biases which seams are tried first (the
        driver feeds ``DeviceTimeTable.top_offenders`` through
        ``axis_priority``); axes not listed follow in canonical order."""
        order = [a for a in (axis_order or ()) if a in self.axes]
        order += [a for a in AXES if a not in order]
        out: List[Tuple[str, TuningPlan]] = []
        for name in order:
            for val in self.axes[name]:
                if val != getattr(plan, name):
                    out.append((name, plan.replace(**{name: val})))
        return out


def axis_priority(timings) -> List[str]:
    """Map a :class:`~deeplearning4j_tpu.profiler.devicetime.
    DeviceTimeTable` onto a refinement order: conv-dominated profiles
    try the layout/fusion seams first (the MXU-facing knobs), matmul/
    attention-dominated ones try precision and megastep K.  ``None`` (no
    device timing available) keeps the canonical order."""
    if timings is None:
        return list(AXES)
    try:
        offenders = timings.top_offenders(3)
    except Exception:
        return list(AXES)
    kinds = " ".join(str(getattr(r, "op", r)) for r in offenders).lower()
    if "conv" in kinds or "pool" in kinds or "norm" in kinds:
        lead = ["compute_layout", "fuse_epilogues", "precision",
                "steps_per_dispatch"]
    else:
        lead = ["precision", "steps_per_dispatch", "prefetch"]
    return lead + [a for a in AXES if a not in lead]
