"""``tune/`` — a TVM-style autotuner over the optimization seams.

The three pieces (ISSUE 17):

- :mod:`~deeplearning4j_tpu.tune.space` — :class:`TuningSpace`
  enumerates candidate :class:`TuningPlan`\\ s over the existing seams
  (conv compute layout, fused epilogues, megastep K, precision policy,
  prefetch depth, serving bucket ladder, sharding variants), each plan
  reduced to a stable signature.
- :mod:`~deeplearning4j_tpu.tune.driver` — :func:`tune` searches the
  space on live hardware (random + successive halving + offender-seeded
  greedy refinement; min-of-reps trials through ``CachedDispatch``; a
  loss-parity gate on the winner; with ``cost_spec=`` the
  :mod:`analysis.cost` model statically prunes dominated candidates —
  predicted OOM or step time far beyond the default plan — before any
  measurement is spent, recording each prune's reason on the
  :class:`TuningReport`).
- :mod:`~deeplearning4j_tpu.tune.records` — the persistent
  :class:`TuningRecord` store, keyed like the compile cache (model
  fingerprint x mesh x backend x jax version), consulted by
  ``fit(tune="auto")``, ``warmup(tuned=True)``, and the serving
  registry.

CLI: ``python -m deeplearning4j_tpu.tune <zoo-model> --budget N``.
"""

from deeplearning4j_tpu.tune.space import (AXES, K_CHOICES, TuningPlan,
                                           TuningSpace, axis_priority)
from deeplearning4j_tpu.tune.driver import (Trial, TuneResult,
                                            TuningReport, estimate_mfu,
                                            loss_parity, tune)
from deeplearning4j_tpu.tune.records import (TuningRecord, auto_apply,
                                             best_plan, configure, lookup,
                                             mesh_signature, put,
                                             record_key,
                                             reset_configuration)

__all__ = [
    "AXES", "K_CHOICES", "TuningPlan", "TuningSpace", "axis_priority",
    "Trial", "TuneResult", "TuningReport", "estimate_mfu", "loss_parity",
    "tune",
    "TuningRecord", "auto_apply", "best_plan", "configure", "lookup",
    "mesh_signature", "put", "record_key", "reset_configuration",
]
