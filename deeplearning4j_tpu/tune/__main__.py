"""``python -m deeplearning4j_tpu.tune <zoo-model> --budget N`` — tune a
zoo architecture on the local backend and persist the winning plan.

The record lands in the store (``--dir`` / ``DL4J_TPU_TUNE_DIR``) under
the (model fingerprint, mesh, backend, jax version) key, where a later
process's ``fit(tune="auto")`` / ``warmup(tuned=True)`` / registry load
picks it up.  Configure the persistent compile cache (``--cache-dir`` /
``DL4J_TPU_COMPILE_CACHE_DIR``) and every candidate the search compiles
is AOT-cached too — the tuned fresh-process cold start then pays zero
XLA compiles (record + compile cache both hit).
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.tune",
        description="Autotune a zoo model over the optimization seams")
    p.add_argument("model", help="zoo architecture, case-insensitive "
                                 "(e.g. resnet50, tinyyolo, simplecnn)")
    p.add_argument("--budget", type=int, default=20,
                   help="max timing trials, baseline included")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--hw", type=int, default=None,
                   help="input H=W (default: the architecture's native "
                        "size — pass something small on CPU)")
    p.add_argument("--classes", type=int, default=None,
                   help="output classes (default: architecture default)")
    p.add_argument("--reps", type=int, default=3,
                   help="timing reps per full-fidelity trial (min wins)")
    p.add_argument("--steps", type=int, default=8,
                   help="~update steps measured per rep")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mesh", default=None,
                   help="mesh label keying the record (a plan tuned on "
                        "one mesh never auto-applies to another)")
    p.add_argument("--parity-steps", type=int, default=6,
                   help="loss-parity gate steps on the winner")
    p.add_argument("--dir", default=None,
                   help="tuning-record directory (default: "
                        "$DL4J_TPU_TUNE_DIR or the user cache)")
    p.add_argument("--cache-dir", default=None,
                   help="persistent compile-cache directory (makes every "
                        "candidate AOT-cached and revisits near-free)")
    p.add_argument("--peak-tflops", type=float, default=None,
                   help="accelerator peak FLOP/s (in TFLOP/s) for the "
                        "MFU estimate")
    p.add_argument("--max-k", type=int, default=16,
                   help="cap the steps_per_dispatch axis")
    p.add_argument("--device-timing", action="store_true",
                   help="measure per-op device time first and seed the "
                        "refinement order from the top offenders")
    p.add_argument("--no-parity", action="store_true",
                   help="skip the loss-parity gate (NOT recommended)")
    p.add_argument("--no-persist", action="store_true",
                   help="search only — do not write a tuning record")
    p.add_argument("--json", action="store_true",
                   help="machine-readable result on stdout")
    return p


def _resolve_model(name: str):
    from deeplearning4j_tpu.models.zoo import ZOO_MODELS
    want = name.replace("_", "").replace("-", "").lower()
    for reg_name, cls in ZOO_MODELS.items():
        if reg_name.lower() == want:
            return reg_name, cls
    raise SystemExit(f"unknown zoo model {name!r}; choose from: "
                     + ", ".join(sorted(ZOO_MODELS)))


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    reg_name, cls = _resolve_model(args.model)

    from deeplearning4j_tpu.nn import compilecache as _cc
    from deeplearning4j_tpu.tune import driver, records
    if args.dir is not None:
        records.configure(args.dir)
    if args.cache_dir is not None:
        _cc.configure(args.cache_dir)

    import numpy as np
    zoo_kw = {"seed": 11}
    if args.classes is not None:
        zoo_kw["num_classes"] = args.classes
    if args.hw is not None:
        zoo_kw["input_shape"] = (3, args.hw, args.hw)

    def factory():
        return cls(**zoo_kw).init()

    probe = factory()
    c, h, w = cls(**zoo_kw).input_shape
    rng = np.random.RandomState(args.seed)
    features = rng.randn(args.batch, c, h, w).astype(np.float32)
    out = probe.output(features[:1])
    if isinstance(out, (list, tuple)):
        out = out[0]
    if getattr(out, "ndim", 0) == 2:        # classifier: one-hot labels
        n = out.shape[1]
        labels = np.eye(n, dtype=np.float32)[rng.randint(0, n, args.batch)]
    else:                                   # detection/dense grid: the
        # numerically-safe empty grid (the bench's YOLO label idiom)
        labels = np.zeros((args.batch,) + tuple(out.shape[1:]), np.float32)
    del probe

    timings = None
    if args.device_timing:
        from deeplearning4j_tpu.profiler import devicetime as _dt
        try:
            timings = _dt.measure(factory(), features, reps=2)
        except Exception as e:
            print(f"device timing unavailable ({type(e).__name__}: {e}); "
                  f"refinement uses the canonical axis order",
                  file=sys.stderr)

    from deeplearning4j_tpu.tune.space import TuningSpace
    space = TuningSpace.for_model(max_steps_per_dispatch=args.max_k)
    result = driver.tune(
        factory, features, labels, budget=args.budget, reps=args.reps,
        base_steps=args.steps, seed=args.seed, space=space,
        mesh=args.mesh, model_name=reg_name,
        persist=not args.no_persist, parity_guard=not args.no_parity,
        parity_steps=args.parity_steps, timings=timings,
        peak_flops=args.peak_tflops * 1e12 if args.peak_tflops else None)

    if args.json:
        payload = {
            "model": reg_name,
            "best_plan": result.best_plan.to_config(),
            "signature": result.best_plan.signature(),
            "best_ms_per_step": result.best_cost_s * 1e3,
            "default_ms_per_step": result.default_cost_s * 1e3,
            "speedup": result.speedup,
            "mfu": result.mfu,
            "trials": len(result.trials),
            "persisted": result.record is not None,
            "record_dir": records.record_dir(),
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(result.summary())
        if result.record is not None:
            print(f"record persisted for {reg_name} "
                  f"(mesh={records.mesh_signature(args.mesh)}) in "
                  f"{records.record_dir()}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
