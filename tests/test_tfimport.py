"""TF-graph conformance tests: frozen TF graphs + TF-computed goldens,
imported into SameDiff and executed as one XLA program.

Reference parity: ``TFGraphTestAllSameDiff`` — thousands of small frozen
TF graphs with golden input/output tensors (SURVEY.md §4 "TF-graph
conformance"). TF is available in this environment, so graphs are frozen
and goldens computed live rather than stored.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
from tensorflow.python.framework.convert_to_constants import (  # noqa: E402
    convert_variables_to_constants_v2)

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TFImportError, importTensorflowGraph)


FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "tfgraphs")


def _persist_fixture(name, gd, feeds, golden, out_names, in_names):
    """Pin the frozen graph + feeds + TF-computed goldens to disk
    (VERDICT r3 #3: a stored conformance corpus, so op semantics stay
    pinned against the recorded goldens even if the in-image TF changes)."""
    os.makedirs(FIXTURE_DIR, exist_ok=True)
    path = os.path.join(FIXTURE_DIR, f"{name}.npz")
    if os.path.exists(path):
        return
    payload = {"graph_def": np.frombuffer(gd.SerializeToString(), np.uint8),
               "in_names": np.asarray(in_names), "out_names": np.asarray(out_names)}
    for i, f in enumerate(feeds):
        payload[f"feed_{i}"] = f
    for i, g in enumerate(golden):
        payload[f"golden_{i}"] = g
    np.savez_compressed(path, **payload)


def _conform(fn, *specs, feeds, fixture=None, lower_cf=True):
    """Freeze fn, compute the TF golden, import + execute, compare.

    ``lower_cf=False`` keeps functional control flow (StatelessWhile/If)
    instead of lowering to v1 Enter/Exit/Merge frames — the same flag
    TF's own XLA bridge requires, and the export path for graphs with
    loops that target XLA."""
    import inspect
    if fixture is None:
        fixture = inspect.stack()[1].function
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(
        conc, lower_control_flow=lower_cf)
    gd = frozen.graph.as_graph_def()
    golden = [np.asarray(t) for t in
              (frozen(*[tf.constant(v) for v in feeds])
               if isinstance(frozen(*[tf.constant(v) for v in feeds]), (list, tuple))
               else [frozen(*[tf.constant(v) for v in feeds])])]
    sd = importTensorflowGraph(gd)
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] if t.name.endswith(":0")
                 else t.name.replace(":", ":") for t in frozen.outputs]
    out_names = [n.split(":")[0] if n.endswith(":0") else n
                 for n in [t.name for t in frozen.outputs]]
    res = sd.output(dict(zip(in_names, feeds)), out_names)
    for name, want in zip(out_names, golden):
        np.testing.assert_allclose(np.asarray(res[name]), want,
                                   rtol=1e-4, atol=1e-5)
    _persist_fixture(fixture, gd, feeds, golden, out_names, in_names)
    return sd


class TestTFGraphConformance:
    def test_mlp_matmul_bias_relu_softmax(self):
        rng = np.random.RandomState(0)
        w1 = tf.constant(rng.randn(6, 8).astype(np.float32))
        b1 = tf.constant(rng.randn(8).astype(np.float32))
        w2 = tf.constant(rng.randn(8, 3).astype(np.float32))

        def f(x):
            h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, w1), b1))
            return tf.nn.softmax(tf.matmul(h, w2))
        x = rng.randn(4, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 6], tf.float32), feeds=[x])

    def test_elementwise_and_reductions(self):
        rng = np.random.RandomState(1)

        def f(x):
            y = tf.exp(x) + tf.sqrt(tf.abs(x)) * 2.0
            z = tf.reduce_mean(y, axis=1, keepdims=True)
            return tf.reduce_sum(tf.square(y - z), axis=-1)
        x = rng.randn(3, 5).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 5], tf.float32), feeds=[x])

    def test_reshape_transpose_concat(self):
        rng = np.random.RandomState(2)

        def f(x):
            a = tf.reshape(x, [2, 3, 4])
            b = tf.transpose(a, [0, 2, 1])
            c = tf.concat([b, b], axis=2)
            return tf.squeeze(tf.expand_dims(c, 0), [0])
        x = rng.randn(2, 12).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 12], tf.float32), feeds=[x])

    def test_conv_pool_nhwc(self):
        rng = np.random.RandomState(3)
        w = tf.constant(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.1)

        def f(x):
            h = tf.nn.relu(tf.nn.conv2d(x, w, strides=1, padding="SAME"))
            p = tf.nn.max_pool2d(h, 2, 2, padding="VALID")
            return tf.nn.avg_pool2d(p, 2, 1, padding="VALID")
        x = rng.randn(2, 8, 8, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 8, 8, 2], tf.float32), feeds=[x])

    def test_bert_style_attention_block(self):
        """The BERT entry-path shape: batched matmuls, masked softmax,
        layernorm from primitives (mean/sqdiff/rsqrt), erf-gelu."""
        rng = np.random.RandomState(4)
        d, h = 8, 2
        wq = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        wk = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        wv = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        g = tf.constant(rng.rand(d).astype(np.float32) + 0.5)
        be = tf.constant(rng.randn(d).astype(np.float32) * 0.1)

        def layernorm(x):
            m = tf.reduce_mean(x, axis=-1, keepdims=True)
            v = tf.reduce_mean(tf.math.squared_difference(x, m), axis=-1,
                               keepdims=True)
            return (x - m) * tf.math.rsqrt(v + 1e-12) * g + be

        def gelu(x):
            return x * 0.5 * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        def f(x, mask):
            B = tf.shape(x)[0]
            q = tf.reshape(tf.matmul(x, wq), [2, 5, h, d // h])
            k = tf.reshape(tf.matmul(x, wk), [2, 5, h, d // h])
            v = tf.reshape(tf.matmul(x, wv), [2, 5, h, d // h])
            q = tf.transpose(q, [0, 2, 1, 3])
            k = tf.transpose(k, [0, 2, 1, 3])
            v = tf.transpose(v, [0, 2, 1, 3])
            scores = tf.matmul(q, k, transpose_b=True) / 2.0
            scores += (1.0 - mask[:, None, None, :]) * -1e9
            ctx = tf.matmul(tf.nn.softmax(scores), v)
            ctx = tf.reshape(tf.transpose(ctx, [0, 2, 1, 3]), [2, 5, d])
            return layernorm(x + gelu(ctx))
        x = rng.randn(2, 5, d).astype(np.float32)
        mask = np.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
        _conform(f, tf.TensorSpec([2, 5, d], tf.float32),
                 tf.TensorSpec([2, 5], tf.float32), feeds=[x, mask])

    def test_gather_slice_select(self):
        rng = np.random.RandomState(5)
        table = tf.constant(rng.randn(10, 4).astype(np.float32))

        def f(ids):
            e = tf.gather(table, ids)
            head = e[:, 0:2]
            return tf.where(head > 0.0, head, tf.zeros_like(head))
        ids = rng.randint(0, 10, (3, 6)).astype(np.int32)
        _conform(f, tf.TensorSpec([None, 6], tf.int32), feeds=[ids])

    def test_fused_batchnorm_inference(self):
        rng = np.random.RandomState(6)
        gamma = tf.constant(rng.rand(3).astype(np.float32) + 0.5)
        beta = tf.constant(rng.randn(3).astype(np.float32))
        mean = tf.constant(rng.randn(3).astype(np.float32))
        var = tf.constant(rng.rand(3).astype(np.float32) + 0.5)

        def f(x):
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                x, gamma, beta, mean=mean, variance=var, is_training=False)
            return y
        x = rng.randn(2, 4, 4, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 4, 4, 3], tf.float32), feeds=[x])

    def test_split_concat_roundtrip(self):
        """Split multi-output naming: downstream ':0' refs must resolve
        (advisor r2: _var_name collapses 'name:0' to bare 'name')."""
        rng = np.random.RandomState(7)

        def f(x):
            a, b, c = tf.split(x, 3, axis=1)
            return tf.concat([c * 2.0, a, b], axis=1)
        x = rng.randn(2, 9).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 9], tf.float32), feeds=[x])

    def test_splitv_unstack(self):
        rng = np.random.RandomState(8)

        def f(x):
            a, b = tf.split(x, [2, 4], axis=1)
            rows = tf.unstack(a, axis=0)
            return b + 1.0, rows[0] + rows[1]
        x = rng.randn(2, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 6], tf.float32), feeds=[x])

    def test_shape_tail_ops(self):
        """ZerosLike/OnesLike/Fill/Tile/Range/Shape — the frozen-graph op
        tail that greened the r2-red suite."""
        rng = np.random.RandomState(9)

        def f(x):
            z = tf.zeros_like(x) + tf.ones_like(x) * 2.0
            t = tf.tile(x[:, :2], [1, 3])
            r = tf.range(0.0, 5.0, 1.0)
            filled = tf.fill([5], 3.0)
            return z + t, r * filled, tf.cast(tf.shape(x)[0], tf.float32) + x[0, 0]
        x = rng.randn(3, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 6], tf.float32), feeds=[x])

    def test_topk_onehot_cumsum(self):
        rng = np.random.RandomState(10)

        def f(x):
            v, i = tf.math.top_k(x, k=3)
            oh = tf.one_hot(i, depth=8, on_value=2.0, off_value=-1.0)
            return v, tf.cumsum(oh, axis=-1), tf.cumsum(x, axis=1, reverse=True,
                                                        exclusive=True)
        x = rng.randn(4, 8).astype(np.float32)
        _conform(f, tf.TensorSpec([4, 8], tf.float32), feeds=[x])

    def test_floor_ceil_round_mod(self):
        rng = np.random.RandomState(11)

        def f(x):
            return (tf.floor(x) + tf.math.ceil(x) + tf.round(x),
                    tf.math.floordiv(x, 2.0), tf.math.floormod(x, 2.0))
        x = (rng.randn(3, 4) * 5).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 4], tf.float32), feeds=[x])

    def test_strided_slice_newaxis_ellipsis(self):
        rng = np.random.RandomState(12)

        def f(x):
            a = x[:, None, :, 1:3]
            b = x[..., ::2]
            return a, b + tf.expand_dims(b, 1)[:, 0]
        x = rng.randn(2, 4, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4, 6], tf.float32), feeds=[x])

    def test_imported_graph_save_load_roundtrip(self, tmp_path):
        """TF-imported nodes serialize via rebuild='tf' (advisor r2 high:
        previously a MatMul(transpose_b) silently lost its transpose)."""
        rng = np.random.RandomState(13)
        w = tf.constant(rng.randn(5, 5).astype(np.float32))

        def f(x):
            h = tf.matmul(x, w, transpose_b=True)
            return tf.nn.softmax(tf.transpose(h, [1, 0]), axis=-1)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([3, 5], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        gd = frozen.graph.as_graph_def()
        sd = importTensorflowGraph(gd)
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        x = rng.randn(3, 5).astype(np.float32)
        res = frozen(tf.constant(x))
        want = np.asarray(res[0] if isinstance(res, (list, tuple)) else res)

        p = str(tmp_path / "tfimport.sdz")
        sd.save(p)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(p)
        got = np.asarray(sd2.output({in_name: x}, [out_name])[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unmapped_op_reported(self):
        def f(x):
            # Where has a data-dependent output shape — out of scope by design
            return tf.raw_ops.Where(condition=x > 0)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([4], tf.float32))
        gd = convert_variables_to_constants_v2(conc).graph.as_graph_def()
        with pytest.raises(TFImportError, match="Where"):
            importTensorflowGraph(gd)


class TestTFGraphConformanceR4:
    """r4 breadth: scatter, image, segment, 3-D conv/pool, linalg, einsum,
    special functions (VERDICT r3 #3 — toward the reference's TF corpus)."""

    def test_scatter_nd_family(self):
        rng = np.random.RandomState(10)
        idx = tf.constant([[0], [2], [4], [2]], tf.int32)

        def f(u, t):
            a = tf.scatter_nd(idx, u, [6, 3])
            b = tf.tensor_scatter_nd_add(t, idx, u)
            c = tf.tensor_scatter_nd_sub(t, idx, u)
            return a, b, c
        u = rng.randn(4, 3).astype(np.float32)
        t = rng.randn(6, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([4, 3], tf.float32),
                 tf.TensorSpec([6, 3], tf.float32), feeds=[u, t])

    def test_special_functions(self):
        rng = np.random.RandomState(11)

        def f(x, y):
            return (tf.math.erfc(x), tf.math.expm1(x), tf.math.lgamma(y),
                    tf.math.digamma(y), tf.math.igamma(y, y),
                    tf.math.zeta(y + 1.5, y))
        x = rng.randn(3, 4).astype(np.float32)
        y = (rng.rand(3, 4) + 0.5).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 4], tf.float32),
                 tf.TensorSpec([3, 4], tf.float32), feeds=[x, y])

    def test_xdivy_xlogy_divnonan(self):
        def f(a, b):
            return (tf.math.xdivy(a, b), tf.math.xlogy(tf.abs(a), tf.abs(b) + 1),
                    tf.math.divide_no_nan(a, b))
        a = np.asarray([[0.0, 1.0, 2.0], [3.0, 0.0, -1.0]], np.float32)
        b = np.asarray([[1.0, 0.0, 4.0], [2.0, 5.0, 0.0]], np.float32)
        _conform(f, tf.TensorSpec([2, 3], tf.float32),
                 tf.TensorSpec([2, 3], tf.float32), feeds=[a, b])

    def test_segment_ops(self):
        rng = np.random.RandomState(12)
        ids = tf.constant([0, 0, 1, 2, 2], tf.int32)

        def f(x):
            return (tf.math.segment_sum(x, ids),
                    tf.math.segment_max(x, ids),
                    tf.math.unsorted_segment_sum(x, ids, 3),
                    tf.math.unsorted_segment_prod(x, ids, 3))
        x = rng.randn(5, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([5, 3], tf.float32), feeds=[x])

    def test_resize_bilinear_nearest(self):
        rng = np.random.RandomState(13)

        def f(x):
            return (tf.image.resize(x, [8, 8], method="bilinear"),
                    tf.image.resize(x, [8, 8], method="nearest"))
        x = rng.rand(2, 4, 4, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4, 4, 3], tf.float32), feeds=[x])

    def test_crop_and_resize(self):
        rng = np.random.RandomState(14)
        boxes = tf.constant([[0.0, 0.0, 1.0, 1.0], [0.2, 0.2, 0.8, 0.8]],
                            tf.float32)
        bi = tf.constant([0, 1], tf.int32)

        def f(x):
            return tf.image.crop_and_resize(x, boxes, bi, [4, 4])
        x = rng.rand(2, 8, 8, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 8, 8, 2], tf.float32), feeds=[x])

    def test_space_depth_roundtrip(self):
        rng = np.random.RandomState(15)

        def f(x):
            y = tf.nn.space_to_depth(x, 2)
            return y, tf.nn.depth_to_space(y, 2)
        x = rng.randn(1, 4, 4, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([1, 4, 4, 3], tf.float32), feeds=[x])

    def test_conv3d_pool3d(self):
        rng = np.random.RandomState(16)
        w = tf.constant(rng.randn(2, 2, 2, 2, 4).astype(np.float32) * 0.2)

        def f(x):
            y = tf.nn.conv3d(x, w, [1, 1, 1, 1, 1], "SAME")
            return (tf.nn.max_pool3d(y, 2, 2, "VALID"),
                    tf.nn.avg_pool3d(y, 2, 2, "VALID"))
        x = rng.randn(1, 4, 4, 4, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([1, 4, 4, 4, 2], tf.float32), feeds=[x])

    def test_conv2d_backprop_input_deconv(self):
        rng = np.random.RandomState(17)
        w = tf.constant(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.2)

        def f(dy):
            return tf.nn.conv2d_transpose(dy, w, [1, 8, 8, 2], [1, 2, 2, 1],
                                          "SAME")
        dy = rng.randn(1, 4, 4, 4).astype(np.float32)
        _conform(f, tf.TensorSpec([1, 4, 4, 4], tf.float32), feeds=[dy])

    def test_dilation2d(self):
        rng = np.random.RandomState(18)
        filt = tf.constant(rng.randn(3, 3, 2).astype(np.float32) * 0.1)

        def f(x):
            return tf.nn.dilation2d(x, filt, [1, 1, 1, 1], "VALID",
                                    "NHWC", [1, 1, 1, 1])
        x = rng.randn(1, 6, 6, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([1, 6, 6, 2], tf.float32), feeds=[x])

    def test_lrn(self):
        rng = np.random.RandomState(19)

        def f(x):
            return tf.nn.local_response_normalization(
                x, depth_radius=2, bias=1.0, alpha=1e-4, beta=0.75)
        x = rng.randn(2, 4, 4, 8).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4, 4, 8], tf.float32), feeds=[x])

    def test_einsum_matmul_form(self):
        rng = np.random.RandomState(20)

        def f(a, b):
            return tf.einsum("bij,bjk->bik", a, b)
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(2, 4, 5).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 3, 4], tf.float32),
                 tf.TensorSpec([2, 4, 5], tf.float32), feeds=[a, b])

    def test_matrix_diag_band_setdiag(self):
        rng = np.random.RandomState(21)
        d = tf.constant(rng.randn(4).astype(np.float32))

        def f(x):
            return (tf.linalg.band_part(x, 1, 1),
                    tf.linalg.set_diag(x, d),
                    tf.linalg.diag_part(x))
        x = rng.randn(4, 4).astype(np.float32)
        _conform(f, tf.TensorSpec([4, 4], tf.float32), feeds=[x])

    def test_cholesky_solve_l2loss(self):
        rng = np.random.RandomState(22)
        a_np = rng.randn(4, 4).astype(np.float32)
        spd = a_np @ a_np.T + 4 * np.eye(4, dtype=np.float32)
        a = tf.constant(spd)

        def f(b):
            return (tf.linalg.cholesky(a), tf.linalg.solve(a, b),
                    tf.nn.l2_loss(b))
        b = rng.randn(4, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([4, 2], tf.float32), feeds=[b])

    def test_roll_broadcast_linspace(self):
        rng = np.random.RandomState(23)

        def f(x):
            # tf decomposes linspace into a BroadcastArgs/Range/arith chain;
            # the const parts fold at import and the rest must map
            return (tf.roll(x, shift=2, axis=1),
                    tf.broadcast_to(x[:1], [3, 6]),
                    tf.linspace(0.0, 1.0, 7) + tf.reduce_min(x))
        x = rng.randn(3, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 6], tf.float32), feeds=[x])

    def test_reverse_sequence(self):
        rng = np.random.RandomState(24)
        lens = tf.constant([3, 5], tf.int32)

        def f(x):
            return tf.reverse_sequence(x, lens, seq_axis=1, batch_axis=0)
        x = rng.randn(2, 5, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 5, 3], tf.float32), feeds=[x])

    def test_image_color_ops(self):
        rng = np.random.RandomState(25)

        def f(x):
            hsv = tf.image.rgb_to_hsv(x)
            return (hsv, tf.image.hsv_to_rgb(hsv),
                    tf.image.adjust_hue(x, 0.1),
                    tf.image.adjust_saturation(x, 1.5))
        x = rng.rand(2, 4, 4, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4, 4, 3], tf.float32), feeds=[x])

    def test_bincount(self):
        vals = tf.constant([0, 1, 1, 3, 5, 5, 5], tf.int32)

        def f(w):
            # const values + weighted DenseBincount: the size chain folds
            return tf.math.bincount(vals, weights=w, minlength=6,
                                    maxlength=6)
        w = np.asarray([1.0, 2.0, 0.5, 1.0, 1.0, 3.0, 1.0], np.float32)
        _conform(f, tf.TensorSpec([7], tf.float32), feeds=[w])

    def test_batch_to_space_nd(self):
        rng = np.random.RandomState(26)

        def f(x):
            y = tf.space_to_batch(x, [2, 2], [[0, 0], [0, 0]])
            return tf.batch_to_space(y, [2, 2], [[0, 0], [0, 0]])
        x = rng.randn(1, 4, 4, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([1, 4, 4, 2], tf.float32), feeds=[x])

    def test_inception_style_block(self):
        """Multi-branch conv block: 1x1 + 3x3 + pool branches, concat."""
        rng = np.random.RandomState(27)
        w1 = tf.constant(rng.randn(1, 1, 4, 8).astype(np.float32) * 0.2)
        w3 = tf.constant(rng.randn(3, 3, 4, 8).astype(np.float32) * 0.2)

        def f(x):
            b1 = tf.nn.relu(tf.nn.conv2d(x, w1, 1, "SAME"))
            b2 = tf.nn.relu(tf.nn.conv2d(x, w3, 1, "SAME"))
            b3 = tf.nn.max_pool2d(x, 3, 1, "SAME")
            return tf.concat([b1, b2, b3], axis=-1)
        x = rng.randn(2, 8, 8, 4).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 8, 8, 4], tf.float32), feeds=[x])

    def test_ctc_loss_against_tf(self):
        """Our registry ctc_loss against tf.nn.ctc_loss (dense labels)."""
        from deeplearning4j_tpu.ops import registry as R
        rng = np.random.RandomState(28)
        B, T, S, C = 2, 10, 4, 6
        logits = rng.randn(B, T, C).astype(np.float32)
        labels = rng.randint(1, C, (B, S)).astype(np.int32)
        lab_len = np.asarray([4, 3], np.int32)
        log_len = np.asarray([10, 8], np.int32)
        want = tf.nn.ctc_loss(labels, logits, lab_len, log_len,
                              logits_time_major=False, blank_index=0).numpy()
        got = np.asarray(R.get("ctc_loss")(labels, logits, lab_len, log_len))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestTFFixtureCorpus:
    """Replay the persisted conformance corpus: imported graphs must match
    the RECORDED goldens (pins semantics independently of the live TF)."""

    def test_corpus_replay(self):
        if not os.path.isdir(FIXTURE_DIR):
            pytest.skip("corpus not yet generated (run the conformance "
                        "tests first)")
        files = sorted(f for f in os.listdir(FIXTURE_DIR)
                       if f.endswith(".npz"))
        assert len(files) >= 30, \
            f"conformance corpus has {len(files)} graphs; expected >= 30"
        from tensorflow.core.framework import graph_pb2
        for fname in files:
            data = np.load(os.path.join(FIXTURE_DIR, fname),
                           allow_pickle=False)
            gd = graph_pb2.GraphDef()
            gd.ParseFromString(data["graph_def"].tobytes())
            sd = importTensorflowGraph(gd)
            in_names = [str(n) for n in data["in_names"]]
            out_names = [str(n) for n in data["out_names"]]
            feeds = [data[f"feed_{i}"] for i in range(len(in_names))]
            res = sd.output(dict(zip(in_names, feeds)), out_names)
            for i, name in enumerate(out_names):
                np.testing.assert_allclose(
                    np.asarray(res[name]), data[f"golden_{i}"],
                    rtol=1e-4, atol=1e-5, err_msg=f"{fname}:{name}")


class TestTFControlFlow:
    """TF2 functional control flow (VERDICT r4 missing #2): StatelessWhile/
    StatelessIf import as lax.while_loop/cond over compiled SameDiff
    subgraph bodies (ref: the interpreted Enter/Exit/Merge frame loop,
    SURVEY.md §3.3)."""

    def test_while_loop(self):
        rng = np.random.RandomState(20)

        def f(x):
            i = tf.constant(0)

            def cond(i, acc):
                return i < 5

            def body(i, acc):
                return i + 1, acc * 0.9 + tf.reduce_mean(acc)
            _, acc = tf.while_loop(cond, body, [i, x])
            return acc
        x = rng.randn(3, 4).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 4], tf.float32), feeds=[x],
                 lower_cf=False)

    def test_while_loop_matmul_carry(self):
        rng = np.random.RandomState(21)
        w = tf.constant(rng.randn(4, 4).astype(np.float32) * 0.3)

        def f(x):
            def cond(i, h):
                return i < 3

            def body(i, h):
                return i + 1, tf.nn.tanh(tf.matmul(h, w))
            _, h = tf.while_loop(cond, body, [tf.constant(0), x])
            return h
        x = rng.randn(2, 4).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4], tf.float32), feeds=[x],
                 lower_cf=False)

    def test_stateless_if(self):
        rng = np.random.RandomState(22)

        def f(x):
            return tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: x * 2.0 + 1.0,
                           lambda: -x)
        x = np.abs(rng.randn(3, 3)).astype(np.float32)      # sum > 0 branch
        _conform(f, tf.TensorSpec([3, 3], tf.float32), feeds=[x],
                 fixture="test_stateless_if_true", lower_cf=False)
        x2 = -np.abs(rng.randn(3, 3)).astype(np.float32)    # else branch
        _conform(f, tf.TensorSpec([3, 3], tf.float32), feeds=[x2],
                 fixture="test_stateless_if_false", lower_cf=False)

    def test_nested_while_in_cond(self):
        rng = np.random.RandomState(23)

        def f(x):
            def loop(z):
                return tf.while_loop(lambda i, a: i < 3,
                                     lambda i, a: (i + 1, a + 1.0),
                                     [tf.constant(0), z])[1]
            return tf.cond(tf.reduce_sum(x) > 0.0, lambda: loop(x),
                           lambda: x)
        x = np.abs(rng.randn(2, 2)).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 2], tf.float32), feeds=[x],
                 lower_cf=False)

    def test_while_roundtrips_through_save_load(self, tmp_path):
        """The imported StatelessWhile serializes (subgraph specs in attrs)
        and reloads to identical outputs — the control-flow serialization
        capability the reference gets from FlatBuffers (VERDICT #10)."""
        rng = np.random.RandomState(24)

        def f(x):
            return tf.while_loop(lambda i, a: i < 4,
                                 lambda i, a: (i + 1, a * 1.1),
                                 [tf.constant(0), x])[1]
        x = rng.randn(3, 2).astype(np.float32)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([3, 2], tf.float32))
        frozen = convert_variables_to_constants_v2(
            conc, lower_control_flow=False)
        sd = importTensorflowGraph(frozen.graph.as_graph_def())
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        want = sd.output({in_name: x}, [out_name])[out_name]
        p = str(tmp_path / "while.sdz")
        sd.save(p)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(p)
        got = sd2.output({in_name: x}, [out_name])[out_name]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)


class TestImportedGraphFinetune:
    """Import a frozen CNN, unfreeze its weights (convertToVariables),
    attach a loss, and SameDiff.fit() it — the reference's
    import-then-train capability (BASELINE config #4 shape; VERDICT r4
    missing #2)."""

    def test_finetune_decreasing_loss(self):
        rng = np.random.RandomState(30)
        w1 = tf.Variable(rng.randn(3, 3, 1, 4).astype(np.float32) * 0.2,
                         name="w1")
        w2 = tf.Variable(rng.randn(64, 3).astype(np.float32) * 0.2,
                         name="w2")

        def f(x):
            h = tf.nn.relu(tf.nn.conv2d(x, w1, strides=2, padding="SAME"))
            h = tf.reshape(h, [-1, 64])
            return tf.matmul(h, w2)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([None, 8, 8, 1], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        sd = importTensorflowGraph(frozen.graph.as_graph_def())
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]

        # the frozen Variables land as constants (the ReadVariableOp names
        # are what downstream ops consume; their '/resource' feeders are
        # dead after folding); find + unfreeze them
        weight_consts = [n for n in list(sd._constants)
                         if sd._constants[n].ndim >= 2
                         and not n.endswith("/resource")]
        assert len(weight_consts) == 2
        sd.convertToVariables(*weight_consts)

        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.train import updaters
        labels = sd.placeHolder("labels", shape=(None, 3), dtype=np.float32)
        loss = sd.loss.softmaxCrossEntropy(labels, sd.getVariable(out_name),
                                           name="loss")
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig(
            updater=updaters.Adam(1e-2),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["labels"]))

        x = rng.randn(16, 8, 8, 1).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
        hist = sd.fit({in_name: x, "labels": y}, epochs=30)
        losses = hist.lossCurve()
        assert losses[-1] < losses[0] * 0.7, losses[::10]
        assert np.isfinite(losses[-1])


def _op_corpus():
    """~65 single-op conformance graphs (VERDICT r4 #3: grow the stored
    corpus toward the reference's golden-graph volume). Each entry:
    (name, fn, specs, feeds)."""
    rng = np.random.RandomState(99)
    f32 = lambda *s: rng.randn(*s).astype(np.float32)
    pos = lambda *s: (rng.rand(*s).astype(np.float32) + 0.5)
    i32 = lambda lo, hi, *s: rng.randint(lo, hi, s).astype(np.int32)
    S = tf.TensorSpec
    C = []

    def add(name, fn, specs, feeds):
        C.append((name, fn, specs, feeds))

    x34 = f32(3, 4)
    for nm, tfn in [
            ("abs", tf.abs), ("acos", lambda x: tf.acos(x * 0.3)),
            ("acosh", lambda x: tf.acosh(x + 2.0)), ("asin", lambda x: tf.asin(x * 0.3)),
            ("asinh", tf.asinh), ("atan", tf.atan), ("atanh", lambda x: tf.atanh(x * 0.3)),
            ("ceil", tf.math.ceil), ("cos", tf.cos), ("cosh", tf.cosh),
            ("digamma", lambda x: tf.math.digamma(tf.abs(x) + 1.0)),
            ("erf", tf.math.erf), ("erfc", tf.math.erfc),
            ("expm1", tf.math.expm1), ("floor", tf.floor),
            ("inv", tf.math.reciprocal),
            ("is_finite", lambda x: tf.cast(tf.math.is_finite(x), tf.float32)),
            ("lgamma", lambda x: tf.math.lgamma(tf.abs(x) + 1.0)),
            ("log1p", tf.math.log1p), ("neg", tf.negative),
            ("rint", tf.math.rint), ("round", tf.round),
            ("rsqrt", lambda x: tf.math.rsqrt(tf.abs(x) + 0.5)),
            ("sign", tf.sign), ("sin", tf.sin), ("sinh", tf.sinh),
            ("softplus", tf.math.softplus), ("softsign", tf.math.softsign),
            ("tan", tf.tan), ("selu", tf.nn.selu), ("elu", tf.nn.elu),
            ("leaky_relu", lambda x: tf.nn.leaky_relu(x, 0.1)),
            ("sigmoid", tf.sigmoid),
    ]:
        add(nm, (lambda t: lambda x: t(x))(tfn), [S([3, 4], tf.float32)],
            [x34])

    for nm, tfn in [
            ("atan2", tf.atan2), ("xdivy", tf.math.xdivy),
            ("xlogy", lambda a, b: tf.math.xlogy(a, tf.abs(b) + 0.5)),
            ("xlog1py", lambda a, b: tf.math.xlog1py(a, tf.abs(b))),
            ("squared_difference", tf.math.squared_difference),
            ("floordiv", lambda a, b: tf.math.floordiv(a, tf.abs(b) + 0.5)),
            ("truncatemod", lambda a, b: tf.math.mod(tf.abs(a), tf.abs(b) + 0.5)),
            ("div_no_nan", tf.math.divide_no_nan),
            ("pow", lambda a, b: tf.pow(tf.abs(a) + 0.5, b)),
            ("maximum", tf.maximum), ("minimum", tf.minimum),
    ]:
        add(nm, (lambda t: lambda a, b: t(a, b))(tfn),
            [S([3, 4], tf.float32), S([3, 4], tf.float32)],
            [f32(3, 4), f32(3, 4)])

    for nm, tfn in [
            ("igamma", tf.math.igamma), ("igammac", tf.math.igammac),
            ("polygamma", lambda a, x: tf.math.polygamma(
                tf.ones_like(a), tf.abs(x) + 0.5)),
            ("zeta", lambda a, x: tf.math.zeta(tf.abs(a) + 2.0,
                                               tf.abs(x) + 1.0)),
    ]:
        add(nm, (lambda t: lambda a, b: t(a, b))(tfn),
            [S([3, 3], tf.float32), S([3, 3], tf.float32)],
            [pos(3, 3), pos(3, 3)])

    # reductions / argminmax / logic
    add("reduce_all_any", lambda x: (
        tf.cast(tf.reduce_all(x > -10.0, axis=1), tf.float32),
        tf.cast(tf.reduce_any(x > 1.0, axis=1), tf.float32)),
        [S([3, 4], tf.float32)], [x34])
    add("argmax_argmin", lambda x: (tf.argmax(x, 1), tf.argmin(x, 1)),
        [S([3, 4], tf.float32)], [x34])
    add("reduce_prod_min_max", lambda x: (
        tf.reduce_prod(x, 1), tf.reduce_min(x, 1), tf.reduce_max(x, 1)),
        [S([3, 4], tf.float32)], [x34])
    add("logical_ops", lambda x: tf.cast(
        tf.logical_or(tf.logical_and(x > 0.0, x < 1.0),
                      tf.logical_not(x > -1.0)), tf.float32),
        [S([3, 4], tf.float32)], [x34])
    add("cumsum_cumprod", lambda x: (tf.cumsum(x, 1),
                                     tf.math.cumprod(x, 1)),
        [S([3, 4], tf.float32)], [x34])
    add("l2_loss", tf.nn.l2_loss, [S([3, 4], tf.float32)], [x34])

    # shape / slicing / scatter
    add("strided_slice", lambda x: x[1:, ::2], [S([3, 6], tf.float32)],
        [f32(3, 6)])
    add("slice_op", lambda x: tf.slice(x, [0, 1], [2, 3]),
        [S([3, 6], tf.float32)], [f32(3, 6)])
    add("tile_op", lambda x: tf.tile(x, [2, 3]), [S([2, 2], tf.float32)],
        [f32(2, 2)])
    add("reverse_v2", lambda x: tf.reverse(x, [1]), [S([3, 4], tf.float32)],
        [x34])
    add("roll_op", lambda x: tf.roll(x, 2, 1), [S([3, 6], tf.float32)],
        [f32(3, 6)])
    add("one_hot", lambda i: tf.one_hot(i, 5), [S([4], tf.int32)],
        [i32(0, 5, 4)])
    add("pack_unpack", lambda x: tf.stack(tf.unstack(x, axis=0)[::-1]),
        [S([3, 4], tf.float32)], [x34])
    add("split_concat", lambda x: tf.concat(tf.split(x, 2, axis=1)[::-1], 1),
        [S([3, 4], tf.float32)], [x34])
    add("gather_nd", lambda x: tf.gather_nd(x, [[0, 1], [2, 3]]),
        [S([3, 4], tf.float32)], [x34])
    add("tensor_scatter", lambda x: tf.tensor_scatter_nd_update(
        x, [[0], [2]], tf.zeros([2, 4])), [S([3, 4], tf.float32)], [x34])
    add("scatter_nd_op", lambda i: tf.scatter_nd(
        tf.reshape(i, [-1, 1]), tf.ones([4, 2]), [6, 2]),
        [S([4], tf.int32)], [i32(0, 6, 4)])
    add("mirror_pad", lambda x: tf.pad(x, [[1, 1], [2, 2]], "REFLECT"),
        [S([3, 4], tf.float32)], [x34])
    add("pad_v2", lambda x: tf.pad(x, [[1, 0], [0, 2]],
                                   constant_values=7.0),
        [S([3, 4], tf.float32)], [x34])
    add("sequence_ops", lambda x: tf.reverse_sequence(
        x, [2, 3, 1], seq_axis=1), [S([3, 4], tf.float32)], [x34])
    add("top_k", lambda x: tf.math.top_k(x, 2), [S([3, 6], tf.float32)],
        [f32(3, 6)])
    add("in_shape_ops", lambda x: (tf.reshape(
        x, tf.concat([tf.shape(x)[:1], [-1]], 0)),
        tf.cast(tf.size(x), tf.float32), tf.cast(tf.rank(x), tf.float32)),
        [S([2, 3, 4], tf.float32)], [f32(2, 3, 4)])
    add("broadcast_to_op", lambda x: tf.broadcast_to(x, [4, 3]),
        [S([1, 3], tf.float32)], [f32(1, 3)])
    add("invert_permutation", lambda p: tf.math.invert_permutation(p),
        [S([5], tf.int32)], [np.asarray([2, 0, 1, 4, 3], np.int32)])

    # segments
    seg_ids = np.asarray([0, 0, 1, 2, 2], np.int32)
    add("segment_sum_mean", lambda x: (
        tf.math.segment_sum(x, seg_ids), tf.math.segment_mean(x, seg_ids)),
        [S([5, 3], tf.float32)], [f32(5, 3)])
    add("unsorted_segment", lambda x: tf.math.unsorted_segment_sum(
        x, tf.constant([2, 0, 1, 0, 2]), 3),
        [S([5, 3], tf.float32)], [f32(5, 3)])

    # linalg
    spd = f32(4, 4)
    spd = spd @ spd.T + 4 * np.eye(4, dtype=np.float32)
    add("cholesky_op", tf.linalg.cholesky, [S([4, 4], tf.float32)], [spd])
    add("matrix_solve", lambda a: tf.linalg.solve(
        tf.constant(spd), a), [S([4, 2], tf.float32)], [f32(4, 2)])
    add("matrix_diag_ops", lambda x: (
        tf.linalg.diag(x), tf.linalg.diag_part(tf.linalg.diag(x))),
        [S([3], tf.float32)], [f32(3)])
    add("band_part", lambda x: tf.linalg.band_part(x, 1, 1),
        [S([4, 4], tf.float32)], [f32(4, 4)])
    add("einsum_op", lambda a, b: tf.einsum("ij,jk->ik", a, b),
        [S([3, 4], tf.float32), S([4, 5], tf.float32)],
        [f32(3, 4), f32(4, 5)])

    # nn
    add("log_softmax", tf.nn.log_softmax, [S([3, 4], tf.float32)], [x34])
    add("bias_add_nhwc", lambda x: tf.nn.bias_add(
        x, tf.constant([1.0, -1.0], tf.float32)),
        [S([2, 3, 3, 2], tf.float32)], [f32(2, 3, 3, 2)])
    add("lrn_op", lambda x: tf.nn.local_response_normalization(
        x, depth_radius=2), [S([1, 4, 4, 8], tf.float32)], [f32(1, 4, 4, 8)])
    add("space_depth_ops", lambda x: tf.nn.depth_to_space(
        tf.nn.space_to_depth(x, 2), 2), [S([1, 4, 4, 4], tf.float32)],
        [f32(1, 4, 4, 4)])
    add("dilation2d_op", lambda x: tf.nn.dilation2d(
        x, tf.zeros([2, 2, 3]), [1, 1, 1, 1], "VALID", "NHWC",
        [1, 1, 1, 1]), [S([1, 5, 5, 3], tf.float32)], [f32(1, 5, 5, 3)])
    add("clip_by_value", lambda x: tf.clip_by_value(x, -0.5, 0.5),
        [S([3, 4], tf.float32)], [x34])
    add("select_v2", lambda x: tf.where(x > 0.0, x * 2.0, x - 1.0),
        [S([3, 4], tf.float32)], [x34])
    add("prevent_gradient_identity", lambda x: tf.identity(
        tf.stop_gradient(x)) + 1.0, [S([3, 4], tf.float32)], [x34])

    # image
    add("adjust_contrast_v2_op", lambda x: tf.image.adjust_contrast(x, 1.7),
        [S([1, 4, 4, 3], tf.float32)], [pos(1, 4, 4, 3)])
    add("rgb_hsv_roundtrip", lambda x: tf.image.hsv_to_rgb(
        tf.image.rgb_to_hsv(x)), [S([1, 4, 4, 3], tf.float32)],
        [pos(1, 4, 4, 3) / 2.0])

    # casts
    add("cast_chain", lambda x: tf.cast(tf.cast(x, tf.int32), tf.float32),
        [S([3, 4], tf.float32)], [x34 * 3.0])
    return C


class TestTFOpCorpus:
    @pytest.mark.parametrize(
        "name,fn,specs,feeds",
        [pytest.param(*e, id=e[0]) for e in _op_corpus()])
    def test_op_conformance(self, name, fn, specs, feeds):
        _conform(fn, *specs, feeds=feeds, fixture=f"op_{name}")


class TestTFv1FrameDeframing:
    """Default-frozen graphs lower functional loops to v1 Enter/Exit/
    Merge/Switch frames; the deframer reconstructs cond/body subgraphs
    and imports them as one functional while (VERDICT r4 #2: 'the
    frozen-graph Switch/Merge loop idiom')."""

    def test_lowered_while_imports(self):
        rng = np.random.RandomState(40)

        def f(x):
            return tf.while_loop(
                lambda i, a: i < 3,
                lambda i, a: (i + 1, a * 1.5 + tf.reduce_mean(a)),
                [tf.constant(0), x])[1]
        x = rng.randn(2, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 2], tf.float32), feeds=[x],
                 lower_cf=True)

    def test_lowered_while_with_invariant_capture(self):
        rng = np.random.RandomState(41)
        w = tf.constant(rng.randn(3, 3).astype(np.float32) * 0.3)

        def f(x):
            # w enters the frame as a loop-invariant capture
            return tf.while_loop(
                lambda i, h: i < 4,
                lambda i, h: (i + 1, tf.nn.tanh(tf.matmul(h, w))),
                [tf.constant(0), x])[1]
        x = rng.randn(2, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 3], tf.float32), feeds=[x],
                 lower_cf=True)

    def test_lowered_while_roundtrips_save_load(self, tmp_path):
        rng = np.random.RandomState(42)

        def f(x):
            return tf.while_loop(lambda i, a: i < 5,
                                 lambda i, a: (i + 1, a * 1.1),
                                 [tf.constant(0), x])[1]
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([2, 2], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        sd = importTensorflowGraph(frozen.graph.as_graph_def())
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        x = rng.randn(2, 2).astype(np.float32)
        want = np.asarray(sd.output({in_name: x}, [out_name])[out_name])
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        p = str(tmp_path / "v1while.sdz")
        sd.save(p)
        got = np.asarray(SameDiff.load(p).output(
            {in_name: x}, [out_name])[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_v1_cond_still_rejected_with_guidance(self):
        def f(x):
            return tf.cond(tf.reduce_sum(x) > 0.0, lambda: x * 2.0,
                           lambda: -x)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([2, 2], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)  # lowers the If
        with pytest.raises(TFImportError, match="lower_control_flow=False"):
            importTensorflowGraph(frozen.graph.as_graph_def())


class TestGraphRunnerInterop:
    """Interop runtime (SURVEY §2.2 row 'Interop runtimes'): run a frozen
    GraphDef with TF itself, cross-checked against our XLA import — the
    reference's GraphRunner usage pattern."""

    def test_graph_runner_matches_import(self):
        from deeplearning4j_tpu.modelimport.interop import GraphRunner
        rng = np.random.RandomState(50)
        w = tf.constant(rng.randn(4, 3).astype(np.float32))

        def f(x):
            return tf.nn.softmax(tf.matmul(tf.nn.relu(x), w))
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([2, 4], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        gd = frozen.graph.as_graph_def()
        x = rng.randn(2, 4).astype(np.float32)
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]

        runner = GraphRunner(gd, input_names=[in_name])
        via_tf = runner.run({in_name: x}, [out_name])[out_name]

        sd = importTensorflowGraph(gd)
        via_xla = np.asarray(sd.output({in_name: x}, [out_name])[out_name])
        np.testing.assert_allclose(via_xla, via_tf, rtol=1e-4, atol=1e-5)

    def test_onnxruntime_runner_gated(self):
        from deeplearning4j_tpu.modelimport.interop import (
            GraphRunnerError, OnnxRuntimeRunner)
        try:
            import onnxruntime  # noqa: F401
            pytest.skip("onnxruntime present; gate not exercised")
        except ImportError:
            pass
        with pytest.raises(GraphRunnerError, match="onnxruntime"):
            OnnxRuntimeRunner("/nonexistent.onnx")


class TestTFImportReport:
    """ISSUE 18: importGraphDef attaches an import_report — E163 for
    narrowed consts, W161 for dynamic-dim placeholders, and a clean
    bill for well-formed frozen graphs."""

    def _frozen(self, fn, *specs):
        conc = tf.function(fn).get_concrete_function(*specs)
        return convert_variables_to_constants_v2(
            conc).graph.as_graph_def()

    def test_e163_float64_const(self):
        def f(x):
            return x + tf.cast(tf.constant(np.pi, tf.float64), tf.float32)
        gd = self._frozen(f, tf.TensorSpec([2], tf.float32))
        sd = importTensorflowGraph(gd)
        codes = [d.code for d in sd.import_report]
        assert "DL4J-E163" in codes, sd.import_report.format()

    def test_w161_dynamic_non_batch_dim(self):
        def f(x):
            return tf.nn.relu(x)
        gd = self._frozen(f, tf.TensorSpec([None, None, 8], tf.float32))
        sd = importTensorflowGraph(gd)
        codes = [d.code for d in sd.import_report]
        assert "DL4J-W161" in codes, sd.import_report.format()

    def test_clean_graph_attaches_empty_report(self):
        def f(x):
            return tf.nn.relu(tf.matmul(x, tf.ones((4, 2))))
        gd = self._frozen(f, tf.TensorSpec([None, 4], tf.float32))
        sd = importTensorflowGraph(gd)
        assert hasattr(sd, "import_report")
        assert not sd.import_report.diagnostics, \
            sd.import_report.format()
