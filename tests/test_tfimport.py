"""TF-graph conformance tests: frozen TF graphs + TF-computed goldens,
imported into SameDiff and executed as one XLA program.

Reference parity: ``TFGraphTestAllSameDiff`` — thousands of small frozen
TF graphs with golden input/output tensors (SURVEY.md §4 "TF-graph
conformance"). TF is available in this environment, so graphs are frozen
and goldens computed live rather than stored.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
from tensorflow.python.framework.convert_to_constants import (  # noqa: E402
    convert_variables_to_constants_v2)

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TFImportError, importTensorflowGraph)


def _conform(fn, *specs, feeds):
    """Freeze fn, compute the TF golden, import + execute, compare."""
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    golden = [np.asarray(t) for t in
              (frozen(*[tf.constant(v) for v in feeds])
               if isinstance(frozen(*[tf.constant(v) for v in feeds]), (list, tuple))
               else [frozen(*[tf.constant(v) for v in feeds])])]
    sd = importTensorflowGraph(gd)
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] if t.name.endswith(":0")
                 else t.name.replace(":", ":") for t in frozen.outputs]
    out_names = [n.split(":")[0] if n.endswith(":0") else n
                 for n in [t.name for t in frozen.outputs]]
    res = sd.output(dict(zip(in_names, feeds)), out_names)
    for name, want in zip(out_names, golden):
        np.testing.assert_allclose(np.asarray(res[name]), want,
                                   rtol=1e-4, atol=1e-5)
    return sd


class TestTFGraphConformance:
    def test_mlp_matmul_bias_relu_softmax(self):
        rng = np.random.RandomState(0)
        w1 = tf.constant(rng.randn(6, 8).astype(np.float32))
        b1 = tf.constant(rng.randn(8).astype(np.float32))
        w2 = tf.constant(rng.randn(8, 3).astype(np.float32))

        def f(x):
            h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, w1), b1))
            return tf.nn.softmax(tf.matmul(h, w2))
        x = rng.randn(4, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 6], tf.float32), feeds=[x])

    def test_elementwise_and_reductions(self):
        rng = np.random.RandomState(1)

        def f(x):
            y = tf.exp(x) + tf.sqrt(tf.abs(x)) * 2.0
            z = tf.reduce_mean(y, axis=1, keepdims=True)
            return tf.reduce_sum(tf.square(y - z), axis=-1)
        x = rng.randn(3, 5).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 5], tf.float32), feeds=[x])

    def test_reshape_transpose_concat(self):
        rng = np.random.RandomState(2)

        def f(x):
            a = tf.reshape(x, [2, 3, 4])
            b = tf.transpose(a, [0, 2, 1])
            c = tf.concat([b, b], axis=2)
            return tf.squeeze(tf.expand_dims(c, 0), [0])
        x = rng.randn(2, 12).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 12], tf.float32), feeds=[x])

    def test_conv_pool_nhwc(self):
        rng = np.random.RandomState(3)
        w = tf.constant(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.1)

        def f(x):
            h = tf.nn.relu(tf.nn.conv2d(x, w, strides=1, padding="SAME"))
            p = tf.nn.max_pool2d(h, 2, 2, padding="VALID")
            return tf.nn.avg_pool2d(p, 2, 1, padding="VALID")
        x = rng.randn(2, 8, 8, 2).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 8, 8, 2], tf.float32), feeds=[x])

    def test_bert_style_attention_block(self):
        """The BERT entry-path shape: batched matmuls, masked softmax,
        layernorm from primitives (mean/sqdiff/rsqrt), erf-gelu."""
        rng = np.random.RandomState(4)
        d, h = 8, 2
        wq = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        wk = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        wv = tf.constant(rng.randn(d, d).astype(np.float32) * 0.3)
        g = tf.constant(rng.rand(d).astype(np.float32) + 0.5)
        be = tf.constant(rng.randn(d).astype(np.float32) * 0.1)

        def layernorm(x):
            m = tf.reduce_mean(x, axis=-1, keepdims=True)
            v = tf.reduce_mean(tf.math.squared_difference(x, m), axis=-1,
                               keepdims=True)
            return (x - m) * tf.math.rsqrt(v + 1e-12) * g + be

        def gelu(x):
            return x * 0.5 * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        def f(x, mask):
            B = tf.shape(x)[0]
            q = tf.reshape(tf.matmul(x, wq), [2, 5, h, d // h])
            k = tf.reshape(tf.matmul(x, wk), [2, 5, h, d // h])
            v = tf.reshape(tf.matmul(x, wv), [2, 5, h, d // h])
            q = tf.transpose(q, [0, 2, 1, 3])
            k = tf.transpose(k, [0, 2, 1, 3])
            v = tf.transpose(v, [0, 2, 1, 3])
            scores = tf.matmul(q, k, transpose_b=True) / 2.0
            scores += (1.0 - mask[:, None, None, :]) * -1e9
            ctx = tf.matmul(tf.nn.softmax(scores), v)
            ctx = tf.reshape(tf.transpose(ctx, [0, 2, 1, 3]), [2, 5, d])
            return layernorm(x + gelu(ctx))
        x = rng.randn(2, 5, d).astype(np.float32)
        mask = np.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
        _conform(f, tf.TensorSpec([2, 5, d], tf.float32),
                 tf.TensorSpec([2, 5], tf.float32), feeds=[x, mask])

    def test_gather_slice_select(self):
        rng = np.random.RandomState(5)
        table = tf.constant(rng.randn(10, 4).astype(np.float32))

        def f(ids):
            e = tf.gather(table, ids)
            head = e[:, 0:2]
            return tf.where(head > 0.0, head, tf.zeros_like(head))
        ids = rng.randint(0, 10, (3, 6)).astype(np.int32)
        _conform(f, tf.TensorSpec([None, 6], tf.int32), feeds=[ids])

    def test_fused_batchnorm_inference(self):
        rng = np.random.RandomState(6)
        gamma = tf.constant(rng.rand(3).astype(np.float32) + 0.5)
        beta = tf.constant(rng.randn(3).astype(np.float32))
        mean = tf.constant(rng.randn(3).astype(np.float32))
        var = tf.constant(rng.rand(3).astype(np.float32) + 0.5)

        def f(x):
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                x, gamma, beta, mean=mean, variance=var, is_training=False)
            return y
        x = rng.randn(2, 4, 4, 3).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 4, 4, 3], tf.float32), feeds=[x])

    def test_split_concat_roundtrip(self):
        """Split multi-output naming: downstream ':0' refs must resolve
        (advisor r2: _var_name collapses 'name:0' to bare 'name')."""
        rng = np.random.RandomState(7)

        def f(x):
            a, b, c = tf.split(x, 3, axis=1)
            return tf.concat([c * 2.0, a, b], axis=1)
        x = rng.randn(2, 9).astype(np.float32)
        _conform(f, tf.TensorSpec([None, 9], tf.float32), feeds=[x])

    def test_splitv_unstack(self):
        rng = np.random.RandomState(8)

        def f(x):
            a, b = tf.split(x, [2, 4], axis=1)
            rows = tf.unstack(a, axis=0)
            return b + 1.0, rows[0] + rows[1]
        x = rng.randn(2, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 6], tf.float32), feeds=[x])

    def test_shape_tail_ops(self):
        """ZerosLike/OnesLike/Fill/Tile/Range/Shape — the frozen-graph op
        tail that greened the r2-red suite."""
        rng = np.random.RandomState(9)

        def f(x):
            z = tf.zeros_like(x) + tf.ones_like(x) * 2.0
            t = tf.tile(x[:, :2], [1, 3])
            r = tf.range(0.0, 5.0, 1.0)
            filled = tf.fill([5], 3.0)
            return z + t, r * filled, tf.cast(tf.shape(x)[0], tf.float32) + x[0, 0]
        x = rng.randn(3, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 6], tf.float32), feeds=[x])

    def test_topk_onehot_cumsum(self):
        rng = np.random.RandomState(10)

        def f(x):
            v, i = tf.math.top_k(x, k=3)
            oh = tf.one_hot(i, depth=8, on_value=2.0, off_value=-1.0)
            return v, tf.cumsum(oh, axis=-1), tf.cumsum(x, axis=1, reverse=True,
                                                        exclusive=True)
        x = rng.randn(4, 8).astype(np.float32)
        _conform(f, tf.TensorSpec([4, 8], tf.float32), feeds=[x])

    def test_floor_ceil_round_mod(self):
        rng = np.random.RandomState(11)

        def f(x):
            return (tf.floor(x) + tf.math.ceil(x) + tf.round(x),
                    tf.math.floordiv(x, 2.0), tf.math.floormod(x, 2.0))
        x = (rng.randn(3, 4) * 5).astype(np.float32)
        _conform(f, tf.TensorSpec([3, 4], tf.float32), feeds=[x])

    def test_strided_slice_newaxis_ellipsis(self):
        rng = np.random.RandomState(12)

        def f(x):
            a = x[:, None, :, 1:3]
            b = x[..., ::2]
            return a, b + tf.expand_dims(b, 1)[:, 0]
        x = rng.randn(2, 4, 6).astype(np.float32)
        _conform(f, tf.TensorSpec([2, 4, 6], tf.float32), feeds=[x])

    def test_imported_graph_save_load_roundtrip(self, tmp_path):
        """TF-imported nodes serialize via rebuild='tf' (advisor r2 high:
        previously a MatMul(transpose_b) silently lost its transpose)."""
        rng = np.random.RandomState(13)
        w = tf.constant(rng.randn(5, 5).astype(np.float32))

        def f(x):
            h = tf.matmul(x, w, transpose_b=True)
            return tf.nn.softmax(tf.transpose(h, [1, 0]), axis=-1)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([3, 5], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        gd = frozen.graph.as_graph_def()
        sd = importTensorflowGraph(gd)
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        x = rng.randn(3, 5).astype(np.float32)
        res = frozen(tf.constant(x))
        want = np.asarray(res[0] if isinstance(res, (list, tuple)) else res)

        p = str(tmp_path / "tfimport.sdz")
        sd.save(p)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(p)
        got = np.asarray(sd2.output({in_name: x}, [out_name])[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unmapped_op_reported(self):
        def f(x):
            return tf.raw_ops.Betainc(a=x, b=x, x=x)
        conc = tf.function(f).get_concrete_function(
            tf.TensorSpec([2], tf.float32))
        gd = convert_variables_to_constants_v2(conc).graph.as_graph_def()
        with pytest.raises(TFImportError, match="Betainc"):
            importTensorflowGraph(gd)
