"""ISSUE 14 coverage: the per-op device-timing bridge, the NHWC compute
layout seam, the fused Pallas epilogues, the Rotate/Resize device
augment kernels, and the ParallelWrapper replication-path warmup."""

import struct
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import profiler as prof
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (ActivationLayer, BatchNormalization,
                                          ConvolutionLayer, DenseLayer,
                                          GlobalPoolingLayer, OutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.profiler import devicetime as dt


def conv_fixture(hw=12, bn=True, act="relu", seed=9, layout=None,
                 fused=False):
    b = (NeuralNetConfiguration.Builder().seed(seed).weightInit("relu")
         .list()
         .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1), nOut=8,
                                 activation="identity")))
    if bn:
        b = b.layer(BatchNormalization()).layer(ActivationLayer(act))
    b = (b.layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                  stride=(2, 2)))
         .layer(DenseLayer(nOut=16, activation="relu"))
         .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                            activation="softmax"))
         .setInputType(InputType.convolutional(hw, hw, 3)))
    net = MultiLayerNetwork(b.build()).init()
    if layout:
        net.setComputeLayout(layout)
    if fused:
        net.setEpilogueFusion(True)
    return net


def small_data(hw=12, n=6, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 3, hw, hw).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.randint(0, classes, n)]
    return x, y


# ----------------------------------------------------- xplane wire parser
def _varint(v: int) -> bytes:
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _field(fno: int, wt: int, payload) -> bytes:
    tag = _varint((fno << 3) | wt)
    if wt == 0:
        return tag + _varint(payload)
    return tag + _varint(len(payload)) + payload


def _make_xspace(plane_name: str, events, extra_meta=()) -> bytes:
    """Hand-encode XSpace{planes=[XPlane{name, lines=[XLine{events}],
    event_metadata}]} with ``events`` = [(metadata_id, name, dur_ps)]."""
    metas = b""
    evs = b""
    for mid, name, dur in events:
        meta = _field(1, 0, mid) + _field(2, 2, name.encode())
        metas += _field(4, 2, _field(1, 0, mid) + _field(2, 2, meta))
        evs += _field(4, 2, _field(1, 0, mid) + _field(3, 0, dur))
    for mid, name in extra_meta:
        meta = _field(1, 0, mid) + _field(2, 2, name.encode())
        metas += _field(4, 2, _field(1, 0, mid) + _field(2, 2, meta))
    line = _field(2, 2, b"XLA Ops") + evs
    plane = _field(2, 2, plane_name.encode()) + _field(3, 2, line) + metas
    return _field(1, 2, plane)


class TestXspaceParser:
    def test_roundtrip_and_scope_aggregation(self):
        data = _make_xspace(
            "/device:TPU:0",
            [(1, "fusion.7 dl4j_L0_conv/conv_general_dilated", 2_000_000),
             (2, "dl4j_L1_bn/add", 500_000),
             (3, "copy.3", 250_000),
             (1, "fusion.7 dl4j_L0_conv/conv_general_dilated", 1_000_000)])
        planes = dt.parse_xspace(data)
        assert len(planes) == 1
        assert planes[0]["name"] == "/device:TPU:0"
        (line_name, events), = planes[0]["lines"]
        assert line_name == "XLA Ops"
        assert len(events) == 4
        per = dt.scope_seconds_from_xspace(planes)
        assert per[0] == pytest.approx(3e-6)      # 3ms of ps -> seconds
        assert per[1] == pytest.approx(0.5e-6)
        assert 3 not in per                       # unscoped op dropped

    def test_host_plane_ignored(self):
        data = _make_xspace("/host:CPU", [(1, "dl4j_L0_x/op", 1_000_000)])
        assert dt.scope_seconds_from_xspace(dt.parse_xspace(data)) == {}

    def test_unknown_fields_skipped(self):
        # prepend an unknown varint field + a fixed64 field at XSpace level
        junk = _varint((9 << 3) | 0) + _varint(12345) \
            + _varint((10 << 3) | 1) + struct.pack("<Q", 7)
        data = junk + _make_xspace(
            "/device:TPU:0", [(1, "dl4j_L2_y/op", 4_000_000)])
        per = dt.scope_seconds_from_xspace(dt.parse_xspace(data))
        assert per == {2: pytest.approx(4e-6)}

    def test_parse_from_file(self, tmp_path):
        p = tmp_path / "t.xplane.pb"
        p.write_bytes(_make_xspace("/device:TPU:0",
                                   [(5, "dl4j_L3_z/op", 1_000)]))
        per = dt.scope_seconds_from_xspace(dt.parse_xspace(str(p)))
        assert per == {3: pytest.approx(1e-9)}


# --------------------------------------------------------- the sync bridge
class TestDeviceTimer:
    def test_off_mode_records_nothing(self):
        """A plain fit under ProfilingMode.OFF never creates the
        dl4j_op_device_seconds series (the bridge is pull-based), and an
        explicit export under OFF is refused."""
        prof.set_profiling_mode(prof.ProfilingMode.OFF)
        net = conv_fixture()
        x, y = small_data()
        net.fit(DataSet(x, y))
        reg = prof.get_registry()
        assert reg.get("dl4j_op_device_seconds") is None
        table = dt.measure(net, x, reps=1, mode="sync")
        assert table.export_metrics("fixture") is False
        assert reg.get("dl4j_op_device_seconds") is None

    def test_basic_mode_exports_labeled_series(self):
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        try:
            net = conv_fixture()
            x, _ = small_data()
            table = dt.measure(net, x, reps=1, mode="sync")
            assert table.export_metrics("fixture") is True
            m = prof.get_registry().get("dl4j_op_device_seconds")
            assert m is not None
            labels = set(m.children().keys())
            assert any("conv2d" in lbl for lbl in labels)
        finally:
            prof.set_profiling_mode(prof.ProfilingMode.OFF)

    def test_attribution_matches_flop_model(self):
        """Three-layer fixture: every table row's FLOPs equal the
        analyzer's declared-shape model x batch x train factor, and the
        time shares sum to 1."""
        net = conv_fixture(bn=False)         # conv -> pool -> dense -> out
        x, _ = small_data()
        table = dt.measure(net, x, reps=1, mode="sync")
        assert len(table.rows) == len(net.layers)
        assert sum(r.share for r in table.rows) == pytest.approx(1.0)
        model = {name: f for name, _op, f in dt.layer_flop_model(net.conf)}
        assert any(f > 0 for f in model.values())
        for r in table.rows:
            assert r.flops == model[r.layer] * x.shape[0] * 3.0
            if r.flops:
                assert r.mfu is not None and 0 <= r.mfu <= 1.0
        assert table.top_offenders(2)[0]["device_ms"] >= \
            table.top_offenders(2)[1]["device_ms"]

    def test_graph_attribution(self):
        from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                                 ElementWiseVertex)
        g = (NeuralNetConfiguration.Builder().seed(3).weightInit("relu")
             .graphBuilder().addInputs("in")
             .setInputTypes(InputType.convolutional(8, 8, 3)))
        g.addLayer("c1", ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1),
                                          nOut=8, activation="relu"), "in")
        g.addLayer("c2", ConvolutionLayer(kernelSize=(1, 1), nOut=8,
                                          activation="identity"), "c1")
        g.addVertex("add", ElementWiseVertex("Add"), "c2", "c1")
        g.addLayer("gp", GlobalPoolingLayer("avg"), "add")
        g.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                      activation="softmax"), "gp")
        g.setOutputs("out")
        net = ComputationGraph(g.build()).init()
        x = np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32)
        table = dt.measure(net, x, reps=1, mode="sync")
        names = {r.layer for r in table.rows}
        assert {"c1", "c2", "gp", "out"} <= names
        assert table.total_seconds > 0

    def test_trace_mode_raises_cleanly_off_tpu(self):
        net = conv_fixture()
        x, _ = small_data()
        # auto mode must fall back to sync on the CPU backend
        table = dt.measure(net, x, reps=1, mode="auto")
        assert table.source == "sync"


# ------------------------------------------------------------- NHWC seam
class TestNhwcLayout:
    def test_op_level_bit_exact_fp32(self):
        """conv / pool / BN: NHWC vs NCHW bit-exact in fp32 (jitted)."""
        from deeplearning4j_tpu.ops import convolution as conv_ops
        from deeplearning4j_tpu.ops import normalization as norm_ops
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 8, 12, 12).astype(np.float32))
        w = jnp.asarray(rng.randn(16, 8, 3, 3).astype(np.float32))
        b = jnp.asarray(rng.randn(16).astype(np.float32))
        xt = jnp.transpose(x, (0, 2, 3, 1))

        conv_n = jax.jit(lambda a: conv_ops.conv2d(
            a, w, b, stride=1, pad=1))(x)
        conv_t = jax.jit(lambda a: conv_ops.conv2d(
            a, w, b, stride=1, pad=1, data_format="NHWC"))(xt)
        assert (np.asarray(conv_n)
                == np.asarray(jnp.transpose(conv_t, (0, 3, 1, 2)))).all()

        pool_n = jax.jit(lambda a: conv_ops.maxpool2d(
            a, kernel=2, stride=2))(x)
        pool_t = jax.jit(lambda a: conv_ops.maxpool2d(
            a, kernel=2, stride=2, data_format="NHWC"))(xt)
        assert (np.asarray(pool_n)
                == np.asarray(jnp.transpose(pool_t, (0, 3, 1, 2)))).all()

        g = jnp.asarray(rng.randn(8).astype(np.float32))
        be = jnp.asarray(rng.randn(8).astype(np.float32))
        bn_n = jax.jit(lambda a: norm_ops.batch_norm_train(
            a, g, be, jnp.zeros(8), jnp.ones(8), axis=1))(x)
        bn_t = jax.jit(lambda a: norm_ops.batch_norm_train(
            a, g, be, jnp.zeros(8), jnp.ones(8), axis=3))(xt)
        assert (np.asarray(bn_n[0])
                == np.asarray(jnp.transpose(bn_t[0], (0, 3, 1, 2)))).all()
        assert (np.asarray(bn_n[1]) == np.asarray(bn_t[1])).all()

    def test_small_net_fit_bit_exact_fp32(self):
        """A conv/BN/pool stack under the NHWC seam: the FORWARD is
        bit-exact (same seed, same data; public API unchanged); training
        tracks to fp rounding — the backward's weight-gradient
        reductions legally reassociate per layout, so the params pin is
        a tight allclose, not equality."""
        x, y = small_data()
        ds = DataSet(x, y)
        a = conv_fixture()
        b = conv_fixture(layout="NHWC")
        oa, ob = np.asarray(a.output(x)), np.asarray(b.output(x))
        assert (oa == ob).all()
        for _ in range(3):
            a.fit(ds)
            b.fit(ds)
        assert a.score() == pytest.approx(b.score(), rel=1e-5, abs=1e-6)
        pa = np.asarray(a.params())
        pb = np.asarray(b.params())
        np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-5)

    def test_feedforward_public_layout(self):
        net = conv_fixture(layout="NHWC")
        x, _ = small_data()
        acts = net.feedForward(x)
        assert acts[1].shape[1] == 8          # conv activation is NCHW

    def test_layout_roundtrips_config(self):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        net = conv_fixture()
        net.conf.base.compute_layout = "NHWC"
        conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert conf2.base.compute_layout == "NHWC"
        net2 = MultiLayerNetwork(conf2).init()
        assert net2._compute_layout == "NHWC"
        assert net2.layers[0].data_format == "NHWC"

    def test_save_load_roundtrips_nhwc(self, tmp_path):
        """A saved NHWC net reloads with the seam ACTIVE (config records
        the layout; stamped layers alone would corrupt the forward)."""
        net = conv_fixture(layout="NHWC")
        x, _ = small_data()
        ref = np.asarray(net.output(x))
        p = str(tmp_path / "nhwc.zip")
        net.save(p)
        loaded = MultiLayerNetwork.load(p)
        assert loaded._compute_layout == "NHWC"
        assert (np.asarray(loaded.output(x)) == ref).all()

    def test_invalid_layout_rejected(self):
        net = conv_fixture()
        with pytest.raises(ValueError):
            net.setComputeLayout("NCWH")
        with pytest.raises(ValueError):
            NeuralNetConfiguration.Builder().computeLayout("bogus")

    def test_w101_layout_extension(self):
        """Conv W101 points at the NHWC seam under NCHW and detects the
        layout fix when active; firing behaviour itself is unchanged."""
        def wasteful(fmt=None):
            b = (NeuralNetConfiguration.Builder().seed(1).list()
                 .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=300,
                                         activation="relu"))
                 .layer(GlobalPoolingLayer("avg"))
                 .layer(OutputLayer(nOut=4, lossFunction="mcxent",
                                    activation="softmax"))
                 .setInputType(InputType.convolutional(8, 8, 3)))
            net = MultiLayerNetwork(b.build())
            if fmt:
                net.setComputeLayout(fmt)
            return net
        rep = wasteful().validate()
        w101 = [d for d in rep.diagnostics if d.code == "DL4J-W101"]
        assert w101 and "NHWC" in (w101[0].fix_hint or "")
        rep2 = wasteful("NHWC").validate()
        w101b = [d for d in rep2.diagnostics if d.code == "DL4J-W101"]
        assert w101b and "NHWC compute layout is active" in w101b[0].message

    def test_zero_steady_state_recompiles(self):
        from deeplearning4j_tpu.analysis.churn import get_churn_detector
        net = conv_fixture(layout="NHWC", fused=True)
        x, y = small_data()
        ds = DataSet(x, y)
        for _ in range(5):
            net.fit(ds)
        assert get_churn_detector().signature_count(
            "MultiLayerNetwork.fit", owner=net) == 1


# ------------------------------------------------------- fused epilogues
class TestFusedEpilogue:
    def test_generic_fusion_bit_identical_fp32(self):
        x, y = small_data()
        ds = DataSet(x, y)
        a = conv_fixture()
        b = conv_fixture(fused=True)
        assert (np.asarray(a.output(x)) == np.asarray(b.output(x))).all()
        a.fit(ds)
        b.fit(ds)
        assert a.score() == b.score()

    def test_leaky_head_fusion(self):
        x, y = small_data()
        a = conv_fixture(act="leakyrelu")
        b = conv_fixture(act="leakyrelu", fused=True)
        assert (np.asarray(a.output(x)) == np.asarray(b.output(x))).all()
        plan = b._ensure_epilogue_plan()
        assert plan and list(plan.values())[0][2] == pytest.approx(0.01)

    def test_conv_bias_folds(self):
        """The conv+BN+act triple folds the conv bias into the epilogue
        shift: the plan consumes 3 layers and training stays bit-close."""
        b = conv_fixture(fused=True)
        plan = b._ensure_epilogue_plan()
        assert plan.get(0, (0,))[0] == 3      # conv + BN + act
        x, y = small_data()
        ds = DataSet(x, y)
        a = conv_fixture()
        for _ in range(3):
            a.fit(ds)
            b.fit(ds)
        assert abs(a.score() - b.score()) < 1e-5

    def test_interior_preprocessor_blocks_fusion(self):
        """A preprocessor at an INTERIOR index of a fusable block must
        veto the fusion — the fused dispatch jumps straight through the
        block and would silently drop it. One at the block's START is
        applied before the block either way and keeps the fusion."""
        from deeplearning4j_tpu.nn.layers import build_epilogue_plan

        class _Scale:
            def __call__(self, x):
                return x * 2.0

        a = conv_fixture()
        b = conv_fixture(fused=True)
        a.conf.preprocessors[2] = _Scale()   # interior: the act layer
        b.conf.preprocessors[2] = _Scale()
        assert b._ensure_epilogue_plan() == {}
        x, _ = small_data()
        assert (np.asarray(a.output(x)) == np.asarray(b.output(x))).all()
        plan = build_epilogue_plan(b.layers, {0})   # start index: fine
        assert plan.get(0, (0,))[0] == 3

    def test_sanitizer_walker_mirrors_fused_forward(self):
        """The nonfinite-provenance eager walkers consume the epilogue
        plan: with fusion active the replay reproduces the compiled
        fused step BIT-EXACTLY (same bias fold, same split count) so
        attribution cannot land on an ulp-different op."""
        from deeplearning4j_tpu.profiler import sanitizer as san
        net = conv_fixture(fused=True)
        assert net._ensure_epilogue_plan()
        x, _ = small_data()
        xj = jnp.asarray(x)
        key = jax.random.fold_in(
            jax.random.PRNGKey(net.conf.base.seed), jnp.asarray(0, jnp.int32))
        out_c, _ = net._forward(net._params, net._states, xj, True, key)
        walk = list(san._walk_multilayer(net, net._params, net._states,
                                         xj, None, 0, True))
        assert len(walk) == len(net.layers)
        assert (np.asarray(out_c) == np.asarray(walk[-1][3])).all()

    def test_custom_trace_run_not_divided_by_reps(self, monkeypatch):
        """Trace seconds are normalized by ``reps`` only for the default
        run (the only run_fn that loops ``reps`` times) — a caller's
        ``trace_run`` owns its own iteration count."""
        monkeypatch.setattr(dt, "_trace_layer_seconds",
                            lambda run: {0: 0.9, 1: 0.1})
        net = conv_fixture()
        x, _ = small_data()
        custom = dt.measure(net, x, mode="trace", reps=3,
                            trace_run=lambda: None)
        assert custom.rows[0].seconds == pytest.approx(0.9)
        default = dt.measure(net, x, mode="trace", reps=3)
        assert default.rows[0].seconds == pytest.approx(0.3)

    def test_pallas_kernel_matches_generic(self):
        from deeplearning4j_tpu.ops import normalization as norm_ops
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        rng = np.random.RandomState(1)
        ssa = pk.make_scale_shift_act_override(interpret=True)
        x = jnp.asarray(rng.randn(32, 128).astype(np.float32))
        sc = jnp.asarray(rng.randn(128).astype(np.float32))
        sh = jnp.asarray(rng.randn(128).astype(np.float32))
        for alpha in (0.0, 0.01):
            ref = norm_ops.scale_shift_act(x, sc, sh, alpha=alpha, axis=1)
            got = ssa(x, sc, sh, alpha=alpha, axis=1)
            assert float(jnp.abs(ref - got).max()) < 1e-5
        # gradient flows through the custom_vjp
        g1 = jax.grad(lambda q: jnp.sum(
            ssa(q, sc, sh, alpha=0.01, axis=1) ** 2))(x)
        g2 = jax.grad(lambda q: jnp.sum(
            norm_ops.scale_shift_act(q, sc, sh, alpha=0.01, axis=1) ** 2))(x)
        assert float(jnp.abs(g1 - g2).max()) < 1e-4

    def test_pallas_unsupported_shape_falls_back(self):
        from deeplearning4j_tpu.ops import normalization as norm_ops
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        ssa = pk.make_scale_shift_act_override(interpret=True)
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(4, 8, 3, 3).astype(np.float32))  # NCHW axis 1
        sc = jnp.asarray(rng.randn(8).astype(np.float32))
        sh = jnp.asarray(rng.randn(8).astype(np.float32))
        ref = norm_ops.scale_shift_act(x, sc, sh, alpha=0.0, axis=1)
        got = ssa(x, sc, sh, alpha=0.0, axis=1)
        assert (np.asarray(ref) == np.asarray(got)).all()

    def test_bf16_loss_parity_fused_nhwc(self):
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        pk.install_platform_overrides(interpret=True)
        try:
            x, y = small_data()
            ds = DataSet(x, y)
            a = conv_fixture().setPrecisionPolicy("bf16")
            b = conv_fixture(layout="NHWC", fused=True)
            b.setPrecisionPolicy("bf16")
            la, lb = [], []
            for _ in range(4):
                a.fit(ds)
                la.append(a.score())
                b.fit(ds)
                lb.append(b.score())
            scale = max(abs(la[0]), 1e-6)
            assert max(abs(p - q) / scale
                       for p, q in zip(la, lb)) < 0.10
        finally:
            pk.uninstall_platform_overrides()

    def test_graph_fusion_plan_and_equality(self):
        from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                                 ElementWiseVertex)

        def build():
            g = (NeuralNetConfiguration.Builder().seed(3).weightInit("relu")
                 .graphBuilder().addInputs("in")
                 .setInputTypes(InputType.convolutional(8, 8, 3)))
            g.addLayer("c1", ConvolutionLayer(kernelSize=(3, 3),
                                              padding=(1, 1), nOut=8,
                                              activation="identity"), "in")
            g.addLayer("bn1", BatchNormalization(), "c1")
            g.addLayer("r1", ActivationLayer("relu"), "bn1")
            g.addLayer("c2", ConvolutionLayer(kernelSize=(1, 1), nOut=8,
                                              activation="identity"), "r1")
            g.addLayer("bn2", BatchNormalization(), "c2")
            g.addVertex("add", ElementWiseVertex("Add"), "bn2", "r1")
            g.addLayer("r2", ActivationLayer("relu"), "add")
            g.addLayer("gp", GlobalPoolingLayer("avg"), "r2")
            g.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                          activation="softmax"), "gp")
            g.setOutputs("out")
            return ComputationGraph(g.build()).init()

        b = build().setEpilogueFusion(True)
        plan = b._ensure_epilogue_plan()
        # bn1 -> r1 fuses (conv c1 folds); bn2 feeds the add vertex and
        # must NOT fuse
        assert "bn1" in plan and plan["bn1"][1] == "c1"
        assert "bn2" not in plan
        x = np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.RandomState(0).randint(0, 3, 4)]
        a = build()
        assert np.abs(np.asarray(a.output(x))
                      - np.asarray(b.output(x))).max() < 1e-5
        ds = DataSet(x, y)
        a.fit(ds)
        b.fit(ds)
        assert abs(a.score() - b.score()) < 1e-5

    def test_multi_consumer_conv_fold_bit_exact(self):
        """ISSUE 17 satellite (PR-14 carry): a conv output feeding >1
        consumer no longer blocks the bias fold — the anchor BN takes
        the bias-less output, every OTHER consumer (here a residual Add
        and a graph output tap) reads a re-biased copy that must be
        BIT-IDENTICAL to the unfused conv."""
        from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                                 ElementWiseVertex)

        def build(tap=False):
            g = (NeuralNetConfiguration.Builder().seed(3).weightInit("relu")
                 .graphBuilder().addInputs("in")
                 .setInputTypes(InputType.convolutional(8, 8, 3)))
            g.addLayer("c1", ConvolutionLayer(kernelSize=(3, 3),
                                              padding=(1, 1), nOut=8,
                                              activation="identity"), "in")
            g.addLayer("bn1", BatchNormalization(), "c1")
            g.addLayer("r1", ActivationLayer("relu"), "bn1")
            g.addVertex("add", ElementWiseVertex("Add"), "c1", "r1")
            g.addLayer("gp", GlobalPoolingLayer("avg"), "add")
            g.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                          activation="softmax"), "gp")
            g.setOutputs(*(("out", "c1") if tap else ("out",)))
            return ComputationGraph(g.build()).init()

        x = np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.RandomState(0).randint(0, 3, 4)]
        # c1 has THREE consumers (bn1, add, the output tap) and still
        # folds; the tapped conv output is bit-exact vs the unfused net
        a, b = build(tap=True), build(tap=True).setEpilogueFusion(True)
        plan = b._ensure_epilogue_plan()
        assert plan["bn1"][1] == "c1"
        assert "c1" in b._epilogue_shared
        oa, ob = a.output(x), b.output(x)
        assert np.array_equal(np.asarray(oa[1]), np.asarray(ob[1]))
        assert np.abs(np.asarray(oa[0]) - np.asarray(ob[0])).max() < 1e-5
        # train-path loss parity through the residual reader
        a, b = build(), build().setEpilogueFusion(True)
        ds = DataSet(x, y)
        la, lb = [], []
        for _ in range(4):
            a.fit(ds)
            la.append(a.score())
            b.fit(ds)
            lb.append(b.score())
        scale = max(abs(la[0]), 1e-6)
        assert max(abs(p - q) / scale for p, q in zip(la, lb)) < 0.10

    def test_conv_folds_into_one_bn_only(self):
        """A conv feeding TWO fusable BN+relu chains folds into exactly
        one (first in topo order); the other BN reads the re-biased
        conv output so its statistics match the unfused net."""
        from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                                 ElementWiseVertex)

        def build():
            g = (NeuralNetConfiguration.Builder().seed(5).weightInit("relu")
                 .graphBuilder().addInputs("in")
                 .setInputTypes(InputType.convolutional(8, 8, 3)))
            g.addLayer("c1", ConvolutionLayer(kernelSize=(3, 3),
                                              padding=(1, 1), nOut=8,
                                              activation="identity"), "in")
            g.addLayer("bnA", BatchNormalization(), "c1")
            g.addLayer("rA", ActivationLayer("relu"), "bnA")
            g.addLayer("bnB", BatchNormalization(), "c1")
            g.addLayer("rB", ActivationLayer("relu"), "bnB")
            g.addVertex("add", ElementWiseVertex("Add"), "rA", "rB")
            g.addLayer("gp", GlobalPoolingLayer("avg"), "add")
            g.addLayer("out", OutputLayer(nOut=3, lossFunction="mcxent",
                                          activation="softmax"), "gp")
            g.setOutputs("out")
            return ComputationGraph(g.build()).init()

        b = build().setEpilogueFusion(True)
        plan = b._ensure_epilogue_plan()
        folded = [c for _a, c, _al in plan.values() if c]
        assert folded == ["c1"]          # exactly one BN claimed the conv
        assert "c1" in b._epilogue_shared
        x = np.random.RandomState(1).randn(4, 3, 8, 8).astype(np.float32)
        a = build()
        assert np.abs(np.asarray(a.output(x))
                      - np.asarray(b.output(x))).max() < 1e-5


# --------------------------------------------------- augment device kernels
class TestAugmentKernels:
    def test_resize_shape_and_output_hw(self):
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, 255, (2, 3, 16, 16)).astype(np.uint8))
        aug = DeviceAugmentation(seed=1).resize(8, 10)
        y = aug.apply(x, aug.step_key(jnp.asarray(0)))
        assert y.shape == (2, 3, 8, 10)
        assert aug.output_hw(16, 16) == (8, 10)

    def test_rotate_zero_is_identity(self):
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, 255, (2, 3, 12, 12)).astype(np.uint8))
        aug = DeviceAugmentation(seed=1).rotate(0.0)
        y = aug.apply(x, aug.step_key(jnp.asarray(0)))
        assert float(jnp.abs(y - x.astype(jnp.float32)).max()) == 0.0

    def test_rotate_matches_pil_at_90(self):
        from PIL import Image
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        rng = np.random.RandomState(0)
        img = rng.randint(0, 255, (16, 16)).astype(np.uint8)
        x = jnp.asarray(img[None, None])
        aug = DeviceAugmentation(seed=1).rotate(90.0)
        y = np.asarray(aug.apply(x, aug.step_key(jnp.asarray(0))))[0, 0]
        ref = np.asarray(Image.fromarray(img).rotate(90, Image.BILINEAR),
                         np.float32)
        assert np.abs(y - ref).max() < 1e-2

    def test_random_rotate_deterministic_per_step(self):
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, 255, (2, 3, 12, 12)).astype(np.uint8))
        aug = DeviceAugmentation(seed=5).rotate(30.0, random=True)
        y1 = aug.apply(x, aug.step_key(jnp.asarray(3)))
        y2 = aug.apply(x, aug.step_key(jnp.asarray(3)))
        y3 = aug.apply(x, aug.step_key(jnp.asarray(4)))
        assert (np.asarray(y1) == np.asarray(y2)).all()
        assert not (np.asarray(y1) == np.asarray(y3)).all()

    def test_from_transforms_maps_rotate_resize(self):
        from deeplearning4j_tpu.data.image import (ResizeImageTransform,
                                                   RotateImageTransform)
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        aug = DeviceAugmentation.from_transforms(
            [ResizeImageTransform(8, 8), RotateImageTransform(15.0)], seed=2)
        sigs = [s[0] for s in aug.signature()[1:]]
        assert sigs == ["resize", "rotate"]

    def test_fit_with_device_rotate_resize(self):
        """End-to-end: augmented conv fit stays on-device (no host
        fallback) with a fixed compiled signature."""
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        net = conv_fixture(hw=8)
        rng = np.random.RandomState(0)
        x = rng.randint(0, 255, (6, 3, 12, 12)).astype(np.uint8)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 6)]
        aug = (DeviceAugmentation(seed=3).rotate(10.0, random=True)
               .resize(8, 8).scale_to(0.0, 1.0))
        assert aug.output_hw(12, 12) == (8, 8)
        net.fit(DataSet(x, y), augment=aug)
        net.fit(DataSet(x, y), augment=aug)
        assert np.isfinite(net.score())


# ----------------------------------------- wrapper replication-path warmup
class TestWrapperWarmup:
    def test_warmup_then_fit_zero_new_compiles(self):
        from deeplearning4j_tpu.data.dataset import ListDataSetIterator
        from deeplearning4j_tpu.nn import compilecache as cc
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        d = tempfile.mkdtemp()
        cc.configure(d)
        try:
            net = conv_fixture(hw=8)
            x, y = small_data(hw=8, n=16)
            w = ParallelWrapper(net, DeviceMesh.create(data=8))
            w.warmup([((16, 3, 8, 8), (16, 4))])
            cold = cc.cache_stats()["compile_seconds"]["cold_compiles"]
            assert cold >= 1
            w.fit(ListDataSetIterator(DataSet(x, y), batch_size=16),
                  epochs=1)
            assert cc.cache_stats()["compile_seconds"]["cold_compiles"] \
                == cold
        finally:
            cc.reset_configuration()

    def test_warmup_pads_ragged_batch(self):
        from deeplearning4j_tpu.nn import compilecache as cc
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        d = tempfile.mkdtemp()
        cc.configure(d)
        try:
            net = conv_fixture(hw=8)
            w = ParallelWrapper(net, DeviceMesh.create(data=8))
            # batch 12 pads to 16 (the fit-path _pad rule)
            w.warmup([((12, 3, 8, 8), (12, 4))])
            assert cc.cache_stats()["compile_seconds"]["cold_compiles"] >= 1
        finally:
            cc.reset_configuration()

    def test_megastep_warmup_rejects_bare_shapes(self):
        from deeplearning4j_tpu.parallel.mesh import DeviceMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        net = conv_fixture(hw=8)
        w = ParallelWrapper(net, DeviceMesh.create(data=8))
        with pytest.raises(ValueError):
            w.warmup([(16, 3, 8, 8)], steps_per_dispatch=2)
