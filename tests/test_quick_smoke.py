"""Quick-tier smoke coverage for subsystems whose full suites are slow
(VERDICT r4 weak #2 / #7: a `pytest -m quick` gate under 120 s touching
every subsystem). Each test is one minimal end-to-end pass — the full
suites stay the source of truth for depth."""

import numpy as np
import pytest

pytestmark = pytest.mark.quick


def test_nn_tiny_fit_and_predict():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder().seed(0).list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    ds = DataSet(rng.rand(16, 4).astype(np.float32),
                 np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
    net.fit(ds)
    assert np.isfinite(float(net.score()))
    assert net.output(ds.features).shape == (16, 3)


def test_graph_vertex_forward():
    from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph, MergeVertex
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    g = (NeuralNetConfiguration.Builder().seed(0).graphBuilder()
         .addInputs("in").setInputTypes(InputType.feedForward(4)))
    g.addLayer("a", DenseLayer(nOut=4), "in")
    g.addLayer("b", DenseLayer(nOut=4), "in")
    g.addVertex("m", MergeVertex(), "a", "b")
    g.addLayer("out", OutputLayer(nOut=2, lossFunction="mcxent",
                                  activation="softmax"), "m")
    g.setOutputs("out")
    net = ComputationGraph(g.build()).init()
    x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    assert np.asarray(net.output(x)).shape == (3, 2)


def test_datavec_transform_process():
    from deeplearning4j_tpu.data.records import Schema, TransformProcess
    schema = (Schema.Builder().addColumnString("name")
              .addColumnDouble("x").addColumnDouble("y").build())
    tp = (TransformProcess.Builder(schema).removeColumns("name").build())
    rows = tp.execute([["a", 1.0, 2.0], ["b", 3.0, 4.0]])
    assert rows == [[1.0, 2.0], [3.0, 4.0]]
    assert tp.final_schema.getColumnNames() == ["x", "y"]


def test_evaluation_basic():
    from deeplearning4j_tpu.evaluation.evaluation import Evaluation
    ev = Evaluation(2)
    ev.eval(np.eye(2, dtype=np.float32)[[0, 1, 0, 1]],
            np.asarray([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.4, 0.6]],
                       np.float32))
    assert 0.0 <= ev.accuracy() <= 1.0


def test_updaters_and_schedules():
    from deeplearning4j_tpu.train import schedules, updaters
    import jax.numpy as jnp
    u = updaters.Adam(1e-3)
    s = u.init_state(jnp.ones((3,)))
    upd, s2 = u.apply(jnp.ones((3,)), s, 1e-3, jnp.asarray(0.0))
    assert upd.shape == (3,)
    sched = schedules.ExponentialSchedule("iteration", 0.1, 0.9)
    assert sched(10) < 0.1


def test_serializer_roundtrip(tmp_path):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.serializer import ModelSerializer
    conf = (NeuralNetConfiguration.Builder().seed(1).list()
            .layer(DenseLayer(nOut=4))
            .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(3)).build())
    net = MultiLayerNetwork(conf).init()
    p = str(tmp_path / "m.zip")
    ModelSerializer.writeModel(net, p, True)
    net2 = ModelSerializer.restoreMultiLayerNetwork(p)
    x = np.random.RandomState(0).rand(2, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net2.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)


def test_rl_mdp_step():
    from deeplearning4j_tpu.rl.mdp import CartPole
    env = CartPole(seed=0)
    obs = env.reset()
    obs2, r, done = env.step(0)
    assert len(np.asarray(obs2)) == 4 and np.isfinite(r)


def test_arbiter_space_sample():
    from deeplearning4j_tpu.arbiter.space import ContinuousSpace, IntegerSpace
    rng = np.random.RandomState(0)
    c = ContinuousSpace(0.0, 1.0)
    i = IntegerSpace(1, 5)
    assert 0.0 <= c.sample(rng) <= 1.0
    assert 1 <= i.sample(rng) <= 5


def test_nlp_tokenizer():
    from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
    toks = DefaultTokenizerFactory().create("Hello world foo").getTokens()
    assert toks == ["Hello", "world", "foo"]


def test_ndarray_core():
    from deeplearning4j_tpu.linalg import nd
    a = nd.create(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
    assert float(a.sumNumber()) == 10.0
    b = a.add(1.0)
    assert float(b.maxNumber()) == 5.0


def test_registry_dispatch_and_validation_sample():
    from deeplearning4j_tpu.ops import registry
    out = registry.get("softmax")(np.asarray([[1.0, 2.0]], np.float32))
    np.testing.assert_allclose(np.asarray(out).sum(), 1.0, rtol=1e-5)
    assert registry.has("conv2d") and registry.has("scatter_nd")


def test_samediff_minimal_graph():
    from deeplearning4j_tpu.autodiff.samediff import SameDiff
    sd = SameDiff.create()
    x = sd.placeHolder("x", shape=(None, 3), dtype=np.float32)
    w = sd.var("w", np.ones((3, 2), np.float32))
    y = x.mmul(w)
    out = sd.output({"x": np.ones((2, 3), np.float32)}, [y.name])[y.name]
    np.testing.assert_allclose(np.asarray(out), np.full((2, 2), 3.0))


def test_ui_stats_storage():
    from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
    s = InMemoryStatsStorage()
    s.putStaticInfo({"session_id": "sess", "worker_id": "w0",
                     "model": "test"})
    assert "sess" in s.listSessionIDs()


def test_parallel_mesh_construction():
    import jax
    from deeplearning4j_tpu.parallel.mesh import DeviceMesh, ShardingRule
    mesh = DeviceMesh.create(data=-1, model=1, seq=1)
    assert mesh.size() == len(jax.devices())
    rule = ShardingRule({r".*wqkv.*": (None, "model")})
    assert rule.spec_for("layer0/wqkv", 2) is not None


def test_emnist_tinyimagenet_iterators():
    """Row-34 iterators (EMNIST splits + TinyImageNet) yield sane batches
    and a small model learns the synthetic letters task above chance."""
    from deeplearning4j_tpu.data.iterators import (
        EmnistDataSetIterator, TinyImageNetDataSetIterator)
    it = EmnistDataSetIterator("LETTERS", 64, True, num_examples=256)
    ds = it.next()
    assert ds.features.shape == (64, 784) and ds.labels.shape == (64, 26)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    ti = TinyImageNetDataSetIterator(32, True, num_examples=64)
    ds2 = ti.next()
    assert ds2.features.shape == (32, 3, 64, 64)
    assert ds2.labels.shape == (32, 200)
    import pytest
    with pytest.raises(ValueError, match="unknown EMNIST split"):
        EmnistDataSetIterator("NOPE", 8, True)


def test_threshold_bitmap_codec_roundtrip():
    """Gradient-compression codecs (ref: EncodedGradientsAccumulator wire
    format): decode(encode(x)) + residual == x for both codecs."""
    from deeplearning4j_tpu.ops.registry import get
    rng = np.random.RandomState(0)
    x = rng.randn(6, 7).astype(np.float32)
    idx, signs, count, residual = get("encode_threshold")(x, 1.0)
    dec = np.asarray(get("decode_threshold")(idx, signs, 1.0, x.shape))
    np.testing.assert_allclose(dec + np.asarray(residual), x,
                               rtol=1e-5, atol=1e-6)
    assert int(count) == int((np.abs(x) >= 1.0).sum())
    codes, res2 = get("encode_bitmap")(x, 0.7)
    dec2 = np.asarray(get("decode_bitmap")(codes, 0.7, x.shape))
    np.testing.assert_allclose(dec2 + np.asarray(res2), x,
                               rtol=1e-5, atol=1e-6)


def test_tinyimagenet_real_dir_split(tmp_path, monkeypatch):
    """Real-data path: deterministic 90/10 train/test split with NO file
    overlap, labels spanning all classes."""
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in ("alpha", "beta"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(10):
            Image.fromarray(rng.randint(0, 255, (64, 64, 3),
                                        dtype=np.uint8)).save(
                d / f"{i}.png")
    monkeypatch.setenv("DL4J_TPU_TINYIMAGENET_DIR", str(tmp_path))
    from deeplearning4j_tpu.data.iterators import TinyImageNetDataSetIterator
    tr = TinyImageNetDataSetIterator(8, train=True)
    te = TinyImageNetDataSetIterator(8, train=False)
    assert not tr.synthetic and not te.synthetic
    n_tr = tr.data.features.shape[0]
    n_te = te.data.features.shape[0]
    assert n_tr == 18 and n_te == 2         # 90/10 of 20
    assert tr.data.labels.shape[1] == 2     # both classes in the label map
    # disjointness: pixel sums of train vs test images never collide
    s_tr = {float(tr.data.features[i].sum()) for i in range(n_tr)}
    s_te = {float(te.data.features[i].sum()) for i in range(n_te)}
    assert not (s_tr & s_te)
