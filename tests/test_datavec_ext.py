"""DataVec r4 breadth: new transforms, sequence ops, Reducer, Join, and
the end-to-end CSV → join → sequence window → iterator → fit pipeline
(VERDICT r3 #6; ref: org.datavec.api.transform.*)."""


import numpy as np
import pytest

from deeplearning4j_tpu.data.records import (CollectionSequenceRecordReader,
                                             CSVRecordReader, Join, Reducer,
                                             Schema,
                                             SequenceRecordReaderDataSetIterator,
                                             TransformProcess, executeJoin)


def _schema(*cols):
    b = Schema.Builder()
    for name, kind in cols:
        getattr(b, f"addColumn{kind}")(name)
    return b.build()


class TestNewColumnTransforms:
    def test_numeric_additions(self):
        sch = _schema(("x", "Double"))
        tp = (TransformProcess.Builder(sch)
              .absValueColumn("x")
              .roundDoubleColumn("x", 1)
              .build())
        rows = tp.execute([[-1.26], [2.71]])
        assert rows == [[1.3], [2.7]]

    def test_subtract_mean_and_replace_empty(self):
        sch = _schema(("x", "Double"))
        tp = (TransformProcess.Builder(sch).subtractMean("x").build())
        rows = tp.execute([[1.0], [3.0]])
        assert rows == [[-1.0], [1.0]]
        sch2 = _schema(("s", "String"))
        tp2 = (TransformProcess.Builder(sch2)
               .replaceEmptyWithValue("s", "missing").build())
        assert tp2.execute([[""], ["a"]]) == [["missing"], ["a"]]

    def test_string_additions(self):
        sch = _schema(("s", "String"))
        tp = (TransformProcess.Builder(sch)
              .trimStringTransform("s")
              .padStringTransform("s", 5, "0", "LEFT")
              .substringTransform("s", 1, 4)
              .stringLengthColumn("s", "len")
              .build())
        rows = tp.execute([[" 42 "], ["abcdef"]])
        # " 42 " -> trim "42" -> left-pad "00042" -> substring(1,4) "004"
        assert rows[0][0] == "004"
        assert rows[0][1] == 3
        assert tp.getFinalSchema().getColumnNames() == ["s", "len"]

    def test_map_all_strings_except(self):
        sch = _schema(("s", "String"))
        tp = (TransformProcess.Builder(sch)
              .mapAllStringsExceptList("s", "OTHER", ["a", "b"]).build())
        assert tp.execute([["a"], ["z"], ["b"]]) == [["a"], ["OTHER"], ["b"]]

    def test_onehot_roundtrip(self):
        sch = _schema(("pre", "Integer"), ("color", "Categorical"),
                      ("post", "Integer"))
        sch.columns[1]["states"] = ["blue", "green", "red"]
        tp = (TransformProcess.Builder(sch)
              .categoricalToOneHot("color")
              .oneHotToCategorical("color", "color[blue]", "color[green]",
                                   "color[red]")
              .build())
        rows = tp.execute([[7, "green", 1], [8, "red", 2]])
        assert rows == [[7, "green", 1], [8, "red", 2]]

    def test_filter_invalid_and_cond_copy(self):
        sch = _schema(("a", "Double"), ("b", "Double"))
        tp = (TransformProcess.Builder(sch)
              .filterInvalidValues("a")
              .conditionalCopyValueTransform("b", "a", lambda v: v < 0)
              .build())
        rows = tp.execute([[1.0, -5.0], ["bad", 2.0], [3.0, 4.0]])
        assert rows == [[1.0, 1.0], [3.0, 4.0]]


class TestReducer:
    def test_group_by_aggregation(self):
        sch = _schema(("key", "String"), ("v", "Double"), ("w", "Double"))
        red = (Reducer.Builder("key")
               .sumColumns("v").meanColumns("w").countColumns("v")
               .build())
        tp = TransformProcess.Builder(sch).reduce(red).build()
        rows = tp.execute([["a", 1.0, 10.0], ["b", 5.0, 2.0],
                           ["a", 2.0, 20.0]])
        assert rows == [["a", 3.0, 15.0, 2], ["b", 5.0, 2.0, 1]]
        assert tp.getFinalSchema().getColumnNames() == \
            ["key", "sum(v)", "mean(w)", "count(v)"]


class TestJoin:
    L = _schema(("id", "Integer"), ("x", "Double"))
    R = _schema(("id", "Integer"), ("y", "Double"))

    def test_inner(self):
        j = (Join.Builder("Inner").setJoinColumns("id")
             .setSchemas(self.L, self.R).build())
        out = executeJoin(j, [[1, 0.5], [2, 1.5]], [[2, 9.0], [3, 8.0]])
        assert out == [[2, 1.5, 9.0]]
        assert j.outputSchema().getColumnNames() == ["id", "x", "y"]

    def test_left_right_full(self):
        left = [[1, 0.5], [2, 1.5]]
        right = [[2, 9.0], [3, 8.0]]
        j = (Join.Builder("LeftOuter").setJoinColumns("id")
             .setSchemas(self.L, self.R).build())
        assert executeJoin(j, left, right) == [[1, 0.5, None], [2, 1.5, 9.0]]
        j = (Join.Builder("RightOuter").setJoinColumns("id")
             .setSchemas(self.L, self.R).build())
        assert executeJoin(j, left, right) == [[2, 1.5, 9.0], [3, None, 8.0]]
        j = (Join.Builder("FullOuter").setJoinColumns("id")
             .setSchemas(self.L, self.R).build())
        assert executeJoin(j, left, right) == \
            [[1, 0.5, None], [2, 1.5, 9.0], [3, None, 8.0]]


class TestSequenceOps:
    SCH = _schema(("dev", "String"), ("t", "Integer"), ("v", "Double"))

    ROWS = [["a", 2, 3.0], ["a", 0, 1.0], ["b", 0, 10.0],
            ["a", 1, 2.0], ["b", 1, 20.0]]

    def test_convert_to_sequence_sorts(self):
        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").build())
        seqs = tp.execute(self.ROWS)
        assert [[r[2] for r in s] for s in seqs] == [[1.0, 2.0, 3.0],
                                                     [10.0, 20.0]]

    def test_window_pad_trim_offset_reverse(self):
        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t")
              .padSequenceToLength(4, 0)
              .build())
        seqs = tp.execute(self.ROWS)
        assert all(len(s) == 4 for s in seqs)

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").window(2, 1).build())
        wins = tp.execute(self.ROWS)
        assert [[r[2] for r in w] for w in wins] == \
            [[1.0, 2.0], [2.0, 3.0], [10.0, 20.0]]

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").trimSequence(1).build())
        assert [[r[2] for r in s] for s in tp.execute(self.ROWS)] == \
            [[2.0, 3.0], [20.0]]

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").reverseSequence().build())
        assert [r[2] for r in tp.execute(self.ROWS)[0]] == [3.0, 2.0, 1.0]

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t")
              .offsetSequence("v", -1, pad_value=-1.0).build())
        # offset -1: v_t <- v_{t+1} (next-step label); last step padded
        assert [r[2] for r in tp.execute(self.ROWS)[0]] == [2.0, 3.0, -1.0]

    def test_diff_moving_split(self):
        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").sequenceDifference("v").build())
        assert [r[2] for r in tp.execute(self.ROWS)[0]] == [0.0, 1.0, 1.0]

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t")
              .sequenceMovingWindowReduce("v", 2, "Mean").build())
        seqs = tp.execute(self.ROWS)
        assert [r[-1] for r in seqs[0]] == [1.0, 1.5, 2.5]
        assert "mean(2)(v)" in tp.getFinalSchema().getColumnNames()

        tp = (TransformProcess.Builder(self.SCH)
              .convertToSequence("dev", "t").splitSequenceMaxLength(2)
              .build())
        assert [len(s) for s in tp.execute(self.ROWS)] == [2, 1, 2]

    def test_execute_sequence_entry(self):
        tp = (TransformProcess.Builder(self.SCH)
              .doubleMathOp("v", "Multiply", 2.0)
              .trimSequenceToLength(1)
              .build())
        seqs = tp.executeSequence([[["a", 0, 1.0], ["a", 1, 2.0]]])
        assert seqs == [[["a", 0, 2.0]]]

    def test_seq_op_without_sequence_fails(self):
        tp = TransformProcess.Builder(self.SCH).window(2).build()
        with pytest.raises(ValueError, match="sequence op before"):
            tp.execute(self.ROWS)


class TestEndToEndPipeline:
    def test_csv_join_window_iterator_fit(self, tmp_path):
        """CSV → join(meta) → transform → convertToSequence → window →
        SequenceRecordReaderDataSetIterator → LSTM fit (VERDICT r3 #6
        'done' criterion)."""
        # readings.csv: device, time, value
        readings = tmp_path / "readings.csv"
        rng = np.random.RandomState(0)
        lines = []
        for dev in ("d0", "d1", "d2", "d3"):
            bias = 2.0 if dev in ("d1", "d3") else -2.0
            for t in range(8):
                lines.append(f"{dev},{t},{rng.randn() * 0.3 + bias:.4f}")
        readings.write_text("\n".join(lines) + "\n")
        # devices.csv: device, label
        devices = tmp_path / "devices.csv"
        devices.write_text("d0,0\nd1,1\nd2,0\nd3,1\n")

        r_schema = _schema(("dev", "String"), ("t", "Integer"),
                           ("v", "Double"))
        d_schema = _schema(("dev", "String"), ("label", "Integer"))

        left = list(CSVRecordReader().initialize(str(readings)))
        right = list(CSVRecordReader().initialize(str(devices)))
        join = (Join.Builder("Inner").setJoinColumns("dev")
                .setSchemas(r_schema, d_schema).build())
        joined = executeJoin(join, left, right)
        assert len(joined) == 32 and len(joined[0]) == 4

        tp = (TransformProcess.Builder(join.outputSchema())
              .convertToSequence("dev", "t")
              .removeColumns("dev", "t")
              .window(4, 2)
              .build())
        windows = tp.execute(joined)
        assert all(len(w) == 4 for w in windows)
        assert tp.getFinalSchema().getColumnNames() == ["v", "label"]

        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader(windows), batch_size=32,
            label_index=1, num_classes=2)

        from deeplearning4j_tpu.nn.config import (InputType,
                                                  NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train import updaters
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(updaters.Adam(1e-2)).weightInit("xavier").list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
                .setInputType(InputType.recurrent(1, 4)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=1)
        first = net.score()
        net.fit(it, epochs=15)
        assert net.score() < first * 0.8, (first, net.score())


class TestReviewRegressions:
    SCH = _schema(("s", "String"))

    def test_seq_mode_column_add_no_schema_duplication(self):
        tp = (TransformProcess.Builder(self.SCH)
              .stringLengthColumn("s", "len").build())
        seqs = tp.executeSequence([[["ab"], ["abc"]], [["x"]], [["yyyy"]]])
        assert tp.getFinalSchema().getColumnNames() == ["s", "len"]
        assert seqs[0] == [["ab", 2], ["abc", 3]]

    def test_execute_sequence_empty_input(self):
        tp = (TransformProcess.Builder(self.SCH)
              .trimStringTransform("s")
              .stringLengthColumn("s", "len").build())
        assert tp.executeSequence([]) == []
        assert tp.getFinalSchema().getColumnNames() == ["s", "len"]

    def test_trim_zero_from_end_is_noop(self):
        sch = _schema(("dev", "String"), ("t", "Integer"), ("v", "Double"))
        tp = (TransformProcess.Builder(sch)
              .convertToSequence("dev", "t")
              .trimSequence(0, from_start=False).build())
        seqs = tp.execute([["a", 0, 1.0], ["a", 1, 2.0]])
        assert [len(s) for s in seqs] == [2]
