"""Exact ROC, ROCBinary, EvaluationCalibration tests (ref: the
nd4j-evaluation classification suite — SURVEY.md §2.2 Evaluation row,
VERDICT r2 item 9)."""

import numpy as np
import pytest

pytestmark = pytest.mark.quick

from deeplearning4j_tpu.evaluation import (EvaluationCalibration, ROC,
                                           ROCBinary)


def _auc_reference(y, p):
    """Exact AUC via the rank statistic (independent formulation)."""
    y = np.asarray(y, bool)
    p = np.asarray(p, np.float64)
    pos, neg = p[y], p[~y]
    wins = 0.0
    for v in pos:
        wins += (v > neg).sum() + 0.5 * (v == neg).sum()
    return wins / (len(pos) * len(neg))


class TestExactROC:
    def test_exact_auc_matches_rank_statistic(self):
        rng = np.random.RandomState(0)
        y = rng.rand(200) > 0.6
        p = np.clip(y * 0.3 + rng.rand(200) * 0.7, 0, 1)
        roc = ROC(threshold_steps=0)          # exact mode
        roc.eval(y.astype(np.float32), p.astype(np.float32))
        assert roc.calculateAUC() == pytest.approx(_auc_reference(y, p),
                                                   abs=1e-9)

    def test_exact_beats_stepped_on_clustered_scores(self):
        """All scores inside one histogram bin: the stepped ROC collapses,
        the exact ROC still separates them."""
        y = np.asarray([0, 0, 1, 1], np.float32)
        p = np.asarray([0.500, 0.501, 0.502, 0.503], np.float32)
        exact = ROC(0)
        exact.eval(y, p)
        assert exact.calculateAUC() == pytest.approx(1.0)
        stepped = ROC(100)
        stepped.eval(y, p)
        assert stepped.calculateAUC() < 1.0   # resolution-limited

    def test_incremental_eval_merges(self):
        rng = np.random.RandomState(1)
        y = (rng.rand(300) > 0.5).astype(np.float32)
        p = np.clip(y * 0.2 + rng.rand(300) * 0.8, 0, 1).astype(np.float32)
        whole = ROC(0)
        whole.eval(y, p)
        a, b = ROC(0), ROC(0)
        a.eval(y[:150], p[:150])
        b.eval(y[150:], p[150:])
        a.merge(b)
        assert a.calculateAUC() == pytest.approx(whole.calculateAUC())

    def test_aucpr_perfect_separation(self):
        roc = ROC(0)
        roc.eval(np.asarray([0, 0, 1, 1], np.float32),
                 np.asarray([0.1, 0.2, 0.8, 0.9], np.float32))
        assert roc.calculateAUCPR() == pytest.approx(1.0, abs=1e-6)


class TestROCBinary:
    def test_per_output_and_average(self):
        rng = np.random.RandomState(2)
        n = 200
        y = (rng.rand(n, 3) > 0.5).astype(np.float32)
        # col 0: perfect, col 1: random, col 2: anti-correlated
        p = np.stack([y[:, 0] * 0.98 + 0.01,
                      rng.rand(n),
                      1.0 - (y[:, 2] * 0.98 + 0.01)], axis=1)
        rb = ROCBinary(threshold_steps=0)
        rb.eval(y, p.astype(np.float32))
        assert rb.numLabels() == 3
        assert rb.calculateAUC(0) == pytest.approx(1.0)
        assert 0.35 < rb.calculateAUC(1) < 0.65
        assert rb.calculateAUC(2) == pytest.approx(0.0)
        want = np.mean([rb.calculateAUC(i) for i in range(3)])
        assert rb.calculateAverageAUC() == pytest.approx(want)


class TestEvaluationCalibration:
    def test_perfectly_calibrated(self):
        rng = np.random.RandomState(3)
        p = rng.rand(20000)
        y = (rng.rand(20000) < p).astype(np.float32)   # calibrated by design
        ec = EvaluationCalibration(reliability_bins=10)
        ec.eval(y, p.astype(np.float32))
        mean_p, frac, counts = ec.getReliabilityInfo()
        np.testing.assert_allclose(mean_p, frac, atol=0.05)
        assert ec.expectedCalibrationError() < 0.05
        assert counts.sum() == 20000

    def test_overconfident_model_flagged(self):
        rng = np.random.RandomState(4)
        y = (rng.rand(5000) < 0.5).astype(np.float32)   # truth: coin flip
        p = np.where(rng.rand(5000) < 0.5, 0.95, 0.05)  # claims certainty
        ec = EvaluationCalibration()
        ec.eval(y, p.astype(np.float32))
        assert ec.expectedCalibrationError() > 0.3

    def test_histograms_and_merge(self):
        rng = np.random.RandomState(5)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 100)]
        p = rng.rand(100, 2).astype(np.float32)
        a, b = EvaluationCalibration(), EvaluationCalibration()
        a.eval(y[:50], p[:50])
        b.eval(y[50:], p[50:])
        a.merge(b)
        whole = EvaluationCalibration()
        whole.eval(y, p)
        np.testing.assert_array_equal(a.getResidualPlot(),
                                      whole.getResidualPlot())
        np.testing.assert_array_equal(a.getProbabilityHistogram(0),
                                      whole.getProbabilityHistogram(0))
        assert a.getProbabilityHistogram(1).sum() == 100
