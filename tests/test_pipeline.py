"""Staged multi-worker image pipeline: correctness of the shared-memory
megabatch ring, the composable stage API, cursor/seek, worker-death
detection, and the on-device augmentation path (ref test model:
datavec-data-image record-reader round-trip tests +
AsyncDataSetIterator ordering tests, SURVEY.md §4)."""

import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.pipeline import (DataPipelineError,
                                              ImagePipeline,
                                              MultiWorkerImageIterator,
                                              _decode_one)


def _build_conv_net(h=16, w=16, seed=0, dtype="float", n_out=3):
    from deeplearning4j_tpu.nn.config import (InputType,
                                              NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              GlobalPoolingLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder().seed(seed).dataType(dtype)
            .list()
            .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=4,
                                    activation="relu"))
            .layer(GlobalPoolingLayer())
            .layer(OutputLayer(nOut=n_out, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.convolutional(h, w, 3))
            .build())
    return MultiLayerNetwork(conf).init()


def _leaves(net):
    import jax
    return [np.asarray(a) for a in jax.tree_util.tree_leaves(net._params)]

@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """37 tiny JPEGs across 3 class dirs (non-divisible by batch size)."""
    from PIL import Image
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    n = 0
    for cls in ("ant", "bee", "cat"):
        d = root / cls
        d.mkdir()
        for i in range(13 if cls != "cat" else 11):
            arr = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg", quality=90)
            n += 1
    assert n == 37
    return str(root)


def _reference_pairs(root, h, w):
    """Single-threaded decode of every file -> {(label, checksum)}."""
    out = []
    for cls in sorted(os.listdir(root)):
        for f in sorted(os.listdir(os.path.join(root, cls))):
            img = _decode_one(os.path.join(root, cls, f), h, w, 3)
            out.append((cls, int(img.astype(np.int64).sum())))
    return sorted(out)


class TestMultiWorkerPipeline:
    @pytest.mark.quick
    def test_full_epoch_matches_single_thread(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=False)
        try:
            got = []
            while it.hasNext():
                ds = it.next()
                assert ds.features.dtype == np.uint8
                assert ds.features.shape[1:] == (3, 16, 16)
                for r in range(ds.features.shape[0]):
                    lab = it.labels[int(np.argmax(ds.labels[r]))]
                    got.append((lab,
                                int(ds.features[r].astype(np.int64).sum())))
            assert sorted(got) == _reference_pairs(image_root, 16, 16)
        finally:
            it.close()

    def test_drop_last_and_second_epoch(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True)
        try:
            n1 = sum(it.next().features.shape[0] for _ in
                     iter(lambda: it.hasNext(), False))
            assert n1 == 32            # 37 -> 4 full batches of 8
            it.reset()
            n2 = 0
            while it.hasNext():
                n2 += it.next().features.shape[0]
            assert n2 == 32
        finally:
            it.close()

    def test_mid_epoch_reset_recovers_all_batches(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True)
        try:
            it.next()                  # consume one, then reset mid-epoch
            it.reset()
            n = 0
            while it.hasNext():
                n += it.next().features.shape[0]
            assert n == 32
        finally:
            it.close()

    def test_shuffle_changes_order_keeps_set(self, image_root):
        def epoch_sums(shuffle):
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=1, shuffle=shuffle,
                                          drop_last=False, seed=7)
            try:
                sums = []
                while it.hasNext():
                    ds = it.next()
                    sums += [int(ds.features[r].astype(np.int64).sum())
                             for r in range(ds.features.shape[0])]
                return sums
            finally:
                it.close()
        plain, shuf = epoch_sums(False), epoch_sums(True)
        assert sorted(plain) == sorted(shuf)

    def test_float32_mode_supports_host_normalizer(self, image_root):
        from deeplearning4j_tpu.data.dataset import ImagePreProcessingScaler
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, dtype="float32")
        it.setPreProcessor(ImagePreProcessingScaler())
        try:
            ds = it.next()
            assert ds.features.dtype == np.float32
            assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        finally:
            it.close()

    @pytest.mark.quick
    def test_uint8_batches_train_end_to_end(self, image_root):
        """uint8 features cast on device inside the jitted step
        (nn/layers.policy_cast) — both fp32 and bf16 policies."""
        for dtype in ("float", "bfloat16"):
            net = _build_conv_net(dtype=dtype)
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=1, drop_last=True)
            try:
                net.fit(it, epochs=1)
                assert np.isfinite(net.score())
            finally:
                it.close()


class TestStagedPipeline:
    """The stage graph: megabatch staging, dispatch_stream, interleave,
    the builder API, and the one-transfer-per-dispatch pin."""

    @pytest.mark.quick
    def test_dispatch_stream_matches_per_batch(self, image_root):
        """dispatch_stream emits [K,B,C,H,W] MegaBatches for full groups
        + plain DataSets for the leftover/tail, content identical to the
        per-batch pull order (in-order emission, deterministic)."""
        from deeplearning4j_tpu.train.stepping import MegaBatch
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=False,
                                      steps_per_dispatch=2)
        try:
            items = list(it.dispatch_stream())
            # 37 imgs, B=8 -> 4 full batches -> 2 megas of K=2, tail of 5
            kinds = [type(x).__name__ for x in items]
            assert kinds == ["MegaBatch", "MegaBatch", "DataSet"]
            assert items[0].features.shape == (2, 8, 3, 16, 16)
            assert items[0].features.dtype == np.uint8
            assert items[0].labels.shape == (2, 8, 3)
            assert items[2].features.shape[0] == 5      # drop_last=False
            flat = []
            for x in items:
                if isinstance(x, MegaBatch):
                    flat.extend((x.features[j], x.labels[j])
                                for j in range(x.steps))
                else:
                    flat.append((x.features, x.labels))
            it.reset()
            pulled = []
            while it.hasNext():
                ds = it.next()
                pulled.append((ds.features, ds.labels))
            assert len(pulled) == len(flat)
            for (f1, y1), (f2, y2) in zip(flat, pulled):
                np.testing.assert_array_equal(f1, f2)
                np.testing.assert_array_equal(y1, y2)
        finally:
            it.close()

    def test_partial_group_falls_back_to_singles(self, image_root):
        """3 full batches with K=2: one full mega + one single (the
        signature-stable tail fallback), then the host-decoded tail."""
        from deeplearning4j_tpu.train.stepping import MegaBatch
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=12,
                                      workers=2, drop_last=False,
                                      steps_per_dispatch=2)
        try:
            items = list(it.dispatch_stream())
            # 37 imgs, B=12 -> 3 full batches: 1 mega[2] + 1 single + tail(1)
            assert [type(x).__name__ for x in items] == \
                ["MegaBatch", "DataSet", "DataSet"]
            assert isinstance(items[0], MegaBatch) and items[0].steps == 2
            assert items[1].features.shape[0] == 12
            assert items[2].features.shape[0] == 1
        finally:
            it.close()

    def test_native_megabatch_fit_bit_exact_vs_stacked(self, image_root):
        """fit() pulling native megabatches dispatches the SAME compiled
        program on the same data as the group-and-stack path — params
        bit-identical."""
        n1 = _build_conv_net()
        it1 = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                       workers=1, drop_last=True,
                                       steps_per_dispatch=2)
        n2 = _build_conv_net()
        it2 = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                       workers=1, drop_last=True)  # K=1
        try:
            n1.fit(it1, epochs=1, steps_per_dispatch=2)   # native stream
            n2.fit(it2, epochs=1, steps_per_dispatch=2)   # stacked groups
            for a, b in zip(_leaves(n1), _leaves(n2)):
                np.testing.assert_array_equal(a, b)
        finally:
            it1.close()
            it2.close()

    @pytest.mark.quick
    def test_one_uint8_transfer_per_dispatch(self, image_root):
        """THE megabatch H2D pin: each K-step dispatch stages exactly ONE
        5-D uint8 feature transfer (today's path), not K per-batch puts."""
        import jax
        puts = []
        orig = jax.device_put

        def counting_put(x, *a, **kw):
            if getattr(x, "ndim", 0) >= 4 and \
                    getattr(x, "dtype", None) == np.uint8:
                puts.append(x.shape)
            return orig(x, *a, **kw)
        net = _build_conv_net()
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, drop_last=True,
                                      steps_per_dispatch=2)
        jax.device_put = counting_put
        try:
            net.fit(it, epochs=1, steps_per_dispatch=2)
        finally:
            jax.device_put = orig
            it.close()
        # 4 full batches = 2 dispatches = 2 megabatch transfers, 5-D each
        assert puts == [(2, 8, 3, 16, 16), (2, 8, 3, 16, 16)]

    def test_interleave_mixes_directories_keeps_set(self, image_root):
        plain = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                         workers=1, drop_last=False)
        inter = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                         workers=1, drop_last=False,
                                         interleave=3)
        try:
            def epoch(it):
                out = []
                while it.hasNext():
                    ds = it.next()
                    out += [(int(np.argmax(ds.labels[r])),
                             int(ds.features[r].astype(np.int64).sum()))
                            for r in range(ds.features.shape[0])]
                return out
            a, b = epoch(plain), epoch(inter)
            assert sorted(a) == sorted(b)           # same multiset
            assert a != b                           # different order
            # un-interleaved directory order is class-sorted: the first
            # batch is single-class; interleaved it must mix classes
            assert len({cls for cls, _ in b[:8]}) > 1
        finally:
            plain.close()
            inter.close()

    @pytest.mark.quick
    def test_builder_api(self, image_root):
        p = (ImagePipeline.list(image_root).shuffle(seed=3)
             .interleave(shards=2).decode(height=16, width=16, workers=2)
             .batch(8).stage(steps_per_dispatch=2).prefetch(3))
        names = [s.name for s in p.describe()]
        assert names == ["list", "shuffle", "interleave", "decode",
                         "batch", "stage", "prefetch"]
        it = p.build()
        try:
            assert it.megabatch_steps == 2
            assert it.n_slots == 3
            assert it.shuffle
            n = 0
            while it.hasNext():
                n += it.next().features.shape[0]
            assert n == 32
        finally:
            it.close()

    def test_builder_requires_core_stages(self, image_root):
        with pytest.raises(ValueError, match="list"):
            ImagePipeline.list(image_root).decode(height=8, width=8).build()

    def test_overlap_ratio_and_stage_metrics(self, image_root):
        """One instrumented staged fit records the overlap ratio AND the
        per-stage pipeline series (decode/stage/tail seconds, h2d
        bytes)."""
        from deeplearning4j_tpu import profiler as prof
        reg = prof.get_registry()
        stage = reg.get("dl4j_pipeline_stage_seconds")
        before = {lv: c.count for (lv,), c in stage.children().items()}
        net = _build_conv_net()
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=False,
                                      steps_per_dispatch=2)
        prev = prof.get_profiling_mode()
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        try:
            net.fit(it, epochs=1, steps_per_dispatch=2)
            ratio = prof.data_overlap_ratio()
            assert ratio is not None and 0.0 < ratio <= 1.0
            gauge = reg.get("dl4j_train_overlap_ratio")
            assert gauge is not None and 0.0 < gauge.value <= 1.0
        finally:
            prof.set_profiling_mode(prev)
            it.close()
        after = {lv: c.count for (lv,), c in stage.children().items()}
        for lv in ("decode", "stage", "tail"):
            assert after.get(lv, 0) > before.get(lv, 0), lv
        assert reg.get("dl4j_pipeline_h2d_bytes_total").value > 0


class TestCursorSeek:
    """PR-5 cursor protocol on the staged pipeline: exact mid-epoch
    resume with the seeded shuffle order rebuilt like
    ListDataSetIterator."""

    @pytest.mark.quick
    def test_seek_resumes_exactly(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, shuffle=True,
                                      drop_last=True, seed=7)
        try:
            it.next()
            cur = it.cursor()
            assert cur == {"batch": 1, "epoch": 1}
            rest = [int(it.next().features.astype(np.int64).sum())
                    for _ in range(3)]
            it.seek(cur)
            resumed = [int(it.next().features.astype(np.int64).sum())
                       for _ in range(3)]
            assert rest == resumed
        finally:
            it.close()

    def test_seek_across_epochs_and_instances(self, image_root):
        """Epoch e's order rebuilds from seed+e-1 on a FRESH instance
        (what checkpoint resume does) regardless of worker count."""
        it1 = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                       workers=1, shuffle=True,
                                       drop_last=True, seed=11)
        try:
            it1.reset()                 # epoch 2
            it1.next()
            cur = it1.cursor()
            want = [int(it1.next().features.astype(np.int64).sum())
                    for _ in range(2)]
        finally:
            it1.close()
        it2 = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                       workers=1, shuffle=True,
                                       drop_last=True, seed=11)
        try:
            it2.seek(cur)
            got = [int(it2.next().features.astype(np.int64).sum())
                   for _ in range(2)]
            assert want == got
        finally:
            it2.close()

    def test_seek_into_tail_region(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, drop_last=False)
        try:
            it.seek({"batch": 4, "epoch": 0})   # all full batches consumed
            assert it.hasNext()
            ds = it.next()
            assert ds.features.shape[0] == 5    # the 37 % 8 tail
            assert not it.hasNext()
        finally:
            it.close()

    def test_mid_group_seek_decodes_only_the_tail(self, image_root):
        """ISSUE 12 satellite (PR-10 carried follow-up): a mid-group
        seek() is an EXACT slot resume — sub-batches before the resume
        offset are never re-decoded (37 imgs / batch 8 / K=2: seek to
        batch 3 = megabatch 1 offset 1 -> only (1, 1) is tasked)."""
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, shuffle=True,
                                      drop_last=True, seed=7,
                                      steps_per_dispatch=2)
        try:
            want = [int(it.next().features.astype(np.int64).sum())
                    for _ in range(4)]
            tasks = []
            orig_put = it._task_q.put

            def spying_put(task, *a, **kw):
                tasks.append(task)
                return orig_put(task, *a, **kw)

            it._task_q.put = spying_put
            it.seek({"batch": 3, "epoch": 1})
            it._task_q.put = orig_put
            subs = [(t[0], t[1]) for t in tasks if t is not None]
            assert (1, 0) not in subs, subs   # consumed head: NOT re-decoded
            assert (1, 1) in subs, subs       # the resumed tail: decoded
            assert int(it.next().features.astype(np.int64).sum()) == want[3]
            assert not it.hasNext()
        finally:
            it.close()

    def test_mid_group_seek_dispatch_stream_falls_back_per_batch(
            self, image_root):
        """The group a mid-group seek resumed into holds stale rows
        below the offset — dispatch_stream must emit it per batch, then
        return to whole MegaBatches for the next group."""
        from deeplearning4j_tpu.train.stepping import MegaBatch
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, shuffle=True,
                                      drop_last=True, seed=7,
                                      steps_per_dispatch=2)
        try:
            want = [int(it.next().features.astype(np.int64).sum())
                    for _ in range(4)]
            it.seek({"batch": 1, "epoch": 1})
            items = list(it.dispatch_stream())
            # batch 1 (offset 1 of group 0) arrives as a plain DataSet;
            # group 1 arrives whole
            assert not isinstance(items[0], MegaBatch)
            assert isinstance(items[1], MegaBatch)
            got = [int(items[0].features.astype(np.int64).sum())]
            got += [int(items[1].features[j].astype(np.int64).sum())
                    for j in range(2)]
            assert got == want[1:]
        finally:
            it.close()

    def test_shuffle_epochs_differ_deterministically(self, image_root):
        def two_epochs(workers):
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=workers, shuffle=True,
                                          drop_last=True, seed=5)
            try:
                e1 = [int(it.next().features.astype(np.int64).sum())
                      for _ in range(4)]
                it.reset()
                e2 = [int(it.next().features.astype(np.int64).sum())
                      for _ in range(4)]
                return e1, e2
            finally:
                it.close()
        a1, a2 = two_epochs(workers=2)  # pool size must not change order
        b1, b2 = two_epochs(workers=1)
        assert a1 == b1 and a2 == b2    # deterministic across pool sizes
        assert a1 != a2                 # epochs reshuffle


class TestWorkerDeath:
    """Satellite: a dead decode worker raises a structured error within
    the liveness timeout instead of hanging next() forever."""

    @pytest.mark.chaos
    def test_killed_workers_raise_structured_error(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True,
                                      liveness_poll=0.2)
        try:
            for p in it._procs:
                p.terminate()
            t0 = time.monotonic()
            with pytest.raises(DataPipelineError) as ei:
                for _ in range(4):
                    it.next()
            assert time.monotonic() - t0 < 10.0     # bounded, no hang
            msg = str(ei.value)
            assert "decode worker died" in msg and "exitcode" in msg
            from deeplearning4j_tpu.data.dataset import is_transient_error
            assert not is_transient_error(ei.value)
        finally:
            it.close()

    @pytest.mark.chaos
    def test_reset_rebuilds_dead_pool(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, drop_last=True,
                                      liveness_poll=0.2)
        try:
            for p in it._procs:
                p.terminate()
            with pytest.raises(DataPipelineError):
                for _ in range(4):
                    it.next()
            it.reset()
            n = 0
            while it.hasNext():
                n += it.next().features.shape[0]
            assert n == 32
        finally:
            it.close()

    @pytest.mark.chaos
    def test_decode_error_surfaces_not_hangs(self, image_root, tmp_path):
        """A corrupt file is a decode error delivered to the consumer,
        not a dead worker or a silent skip."""
        import shutil
        root = tmp_path / "imgs"
        shutil.copytree(image_root, root)
        bad = root / "ant" / "0.jpg"
        bad.write_bytes(b"not a jpeg at all")
        it = MultiWorkerImageIterator(str(root), 16, 16, batch_size=8,
                                      workers=1, drop_last=True,
                                      liveness_poll=0.2)
        try:
            with pytest.raises(DataPipelineError, match="decode failed"):
                for _ in range(4):
                    it.next()
            # the error is latched: a retried pull re-raises promptly
            # instead of waiting forever for the megabatch that can
            # never complete (its errored sub-batch is gone for good)
            t0 = time.monotonic()
            with pytest.raises(DataPipelineError, match="decode failed"):
                it.next()
            assert time.monotonic() - t0 < 2.0
        finally:
            it.close()


@pytest.mark.races
class TestResetCloseRace:
    """Satellite: mid-epoch reset()'s count-based drain vs a concurrent
    close() — lifecycle calls serialize instead of deadlocking or
    crashing on a torn-down queue."""

    def test_concurrent_reset_and_close(self, image_root):
        from deeplearning4j_tpu.faults import preemptive_stress
        for seed in range(2):
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=1, drop_last=True,
                                          liveness_poll=0.2)
            it.next()                       # mid-epoch: tasks in flight
            errs = []

            def run(fn):
                try:
                    fn()
                except Exception as e:      # pragma: no cover - failure path
                    errs.append(e)
            with preemptive_stress(seed=seed):
                threads = [threading.Thread(target=run, args=(it.reset,)),
                           threading.Thread(target=run, args=(it.close,))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30)
                assert not any(t.is_alive() for t in threads), \
                    "reset/close deadlocked"
            assert not errs, errs
            it.close()                      # idempotent afterwards

    def test_reset_after_close_restarts(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, drop_last=True)
        it.next()
        it.close()
        it.reset()
        try:
            n = 0
            while it.hasNext():
                n += it.next().features.shape[0]
            assert n == 32
        finally:
            it.close()


class TestDeviceAugmentation:
    """nn.augment: the seeded on-device crop/flip/normalize prelude."""

    @pytest.mark.quick
    def test_bit_reproducible_per_seed(self):
        import jax
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        rng = np.random.RandomState(0)
        batches = [DataSet(rng.randint(0, 255, (8, 3, 16, 16), np.uint8),
                           np.eye(3, dtype=np.float32)[
                               rng.randint(0, 3, 8)]) for _ in range(4)]

        def run(aug_seed):
            net = _build_conv_net(h=12, w=12)       # crop 4: 16 -> 12
            aug = (DeviceAugmentation(seed=aug_seed).crop(4)
                   .random_flip().scale_to(0, 1))
            net.fit(list(batches), steps_per_dispatch=2, augment=aug)
            return _leaves(net)
        a, b = run(7), run(7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)     # same seed: identical
        # a different seed draws different crops/flips
        aug7 = DeviceAugmentation(seed=7).crop(4).random_flip()
        aug8 = DeviceAugmentation(seed=8).crop(4).random_flip()
        x = batches[0].features
        o7 = np.asarray(aug7.apply(x, aug7.step_key(jax.numpy.int32(0))))
        o8 = np.asarray(aug8.apply(x, aug8.step_key(jax.numpy.int32(0))))
        assert not np.array_equal(o7, o8)

    def test_host_transform_parity_fixture_epoch(self, image_root):
        """Loss-curve parity pin: a deterministic transform (fixed flip)
        run on the host in the workers vs compiled on device produces
        BIT-IDENTICAL training (uint8-preserving op, same data, same
        step RNG) — fp32 and bf16 policies."""
        from deeplearning4j_tpu.data.image import FlipImageTransform
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        from deeplearning4j_tpu.train.listeners import ScoreIterationListener
        for dtype in ("float",):       # bf16 uint8-cast parity covered above
            host = _build_conv_net(seed=3, dtype=dtype)
            h_scores = ScoreIterationListener(1, out=lambda m: None)
            host.setListeners([h_scores])
            it_h = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                            workers=2, drop_last=True,
                                            transform=FlipImageTransform(1))
            dev = _build_conv_net(seed=3, dtype=dtype)
            d_scores = ScoreIterationListener(1, out=lambda m: None)
            dev.setListeners([d_scores])
            it_d = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                            workers=2, drop_last=True)
            try:
                host.fit(it_h, epochs=1)
                dev.fit(it_d, epochs=1, augment=DeviceAugmentation
                        .from_transforms([FlipImageTransform(1)]))
                np.testing.assert_array_equal(h_scores.history,
                                              d_scores.history)
                for a, b in zip(_leaves(host), _leaves(dev)):
                    np.testing.assert_array_equal(a, b)
            finally:
                it_h.close()
                it_d.close()

    @pytest.mark.quick
    def test_zero_steady_state_recompiles(self, image_root):
        """Acceptance pin: augmented megastep fits compile ONE signature
        — the W201 churn counter records no steady-state growth."""
        from deeplearning4j_tpu.analysis.churn import get_churn_detector
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        det = get_churn_detector()
        net = _build_conv_net(h=12, w=12)
        aug = DeviceAugmentation(seed=1).crop(4).random_flip()
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True,
                                      steps_per_dispatch=2)
        try:
            for _ in range(2):
                net.fit(it, epochs=1, steps_per_dispatch=2, augment=aug)
        finally:
            it.close()
        assert det.signature_count("MultiLayerNetwork.megastep",
                                   owner=net) == 1
        assert det.diagnostics_for(net) == []

    def test_same_signature_reattach_keeps_cache(self, image_root):
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        net = _build_conv_net()
        a1 = DeviceAugmentation(seed=1).flip(1)
        a2 = DeviceAugmentation(seed=1).flip(1)
        assert a1.signature() == a2.signature()
        net.setDeviceAugmentation(a1)
        net._train_step_cache["sentinel"] = "x"
        net.setDeviceAugmentation(a2)               # equal: cache kept
        assert "sentinel" in net._train_step_cache
        net.setDeviceAugmentation(DeviceAugmentation(seed=2).flip(1))
        assert "sentinel" not in net._train_step_cache

    def test_from_transforms_unsupported_raises(self):
        from deeplearning4j_tpu.data.image import (ImageTransform,
                                                   PipelineImageTransform,
                                                   RotateImageTransform,
                                                   ScaleImageTransform)
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation

        class ExoticTransform(ImageTransform):
            pass

        with pytest.raises(ValueError, match="no device kernel"):
            DeviceAugmentation.from_transforms([ExoticTransform()])
        with pytest.raises(ValueError, match="probabilistic"):
            DeviceAugmentation.from_transforms([PipelineImageTransform(
                [(ScaleImageTransform(0.5), 0.3)])])
        # Rotate gained a device kernel in PR 14 — it compiles now
        aug = DeviceAugmentation.from_transforms([RotateImageTransform(10)])
        assert aug.signature()[1][0] == "rotate"

    def test_output_hw_and_crop_shapes(self):
        import jax
        from deeplearning4j_tpu.nn.augment import DeviceAugmentation
        aug = DeviceAugmentation(seed=0).crop(4).random_flip()
        assert aug.output_hw(16, 16) == (12, 12)
        x = np.arange(2 * 3 * 16 * 16, dtype=np.uint8).reshape(2, 3, 16, 16)
        out = aug.apply(x, jax.random.PRNGKey(0))
        assert out.shape == (2, 3, 12, 12)
        assert out.dtype == np.float32
