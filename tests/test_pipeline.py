"""Multi-worker image pipeline: correctness of the shared-memory ring
(ref test model: datavec-data-image record-reader round-trip tests +
AsyncDataSetIterator ordering tests, SURVEY.md §4)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.pipeline import (MultiWorkerImageIterator,
                                              _decode_one)

@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """37 tiny JPEGs across 3 class dirs (non-divisible by batch size)."""
    from PIL import Image
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    n = 0
    for cls in ("ant", "bee", "cat"):
        d = root / cls
        d.mkdir()
        for i in range(13 if cls != "cat" else 11):
            arr = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg", quality=90)
            n += 1
    assert n == 37
    return str(root)


def _reference_pairs(root, h, w):
    """Single-threaded decode of every file -> {(label, checksum)}."""
    out = []
    for cls in sorted(os.listdir(root)):
        for f in sorted(os.listdir(os.path.join(root, cls))):
            img = _decode_one(os.path.join(root, cls, f), h, w, 3)
            out.append((cls, int(img.astype(np.int64).sum())))
    return sorted(out)


class TestMultiWorkerPipeline:
    @pytest.mark.quick
    def test_full_epoch_matches_single_thread(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=False)
        try:
            got = []
            while it.hasNext():
                ds = it.next()
                assert ds.features.dtype == np.uint8
                assert ds.features.shape[1:] == (3, 16, 16)
                for r in range(ds.features.shape[0]):
                    lab = it.labels[int(np.argmax(ds.labels[r]))]
                    got.append((lab,
                                int(ds.features[r].astype(np.int64).sum())))
            assert sorted(got) == _reference_pairs(image_root, 16, 16)
        finally:
            it.close()

    def test_drop_last_and_second_epoch(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True)
        try:
            n1 = sum(it.next().features.shape[0] for _ in
                     iter(lambda: it.hasNext(), False))
            assert n1 == 32            # 37 -> 4 full batches of 8
            it.reset()
            n2 = 0
            while it.hasNext():
                n2 += it.next().features.shape[0]
            assert n2 == 32
        finally:
            it.close()

    def test_mid_epoch_reset_recovers_all_batches(self, image_root):
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=2, drop_last=True)
        try:
            it.next()                  # consume one, then reset mid-epoch
            it.reset()
            n = 0
            while it.hasNext():
                n += it.next().features.shape[0]
            assert n == 32
        finally:
            it.close()

    def test_shuffle_changes_order_keeps_set(self, image_root):
        def epoch_sums(shuffle):
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=1, shuffle=shuffle,
                                          drop_last=False, seed=7)
            try:
                sums = []
                while it.hasNext():
                    ds = it.next()
                    sums += [int(ds.features[r].astype(np.int64).sum())
                             for r in range(ds.features.shape[0])]
                return sums
            finally:
                it.close()
        plain, shuf = epoch_sums(False), epoch_sums(True)
        assert sorted(plain) == sorted(shuf)

    def test_float32_mode_supports_host_normalizer(self, image_root):
        from deeplearning4j_tpu.data.dataset import ImagePreProcessingScaler
        it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                      workers=1, dtype="float32")
        it.setPreProcessor(ImagePreProcessingScaler())
        try:
            ds = it.next()
            assert ds.features.dtype == np.float32
            assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        finally:
            it.close()

    @pytest.mark.quick
    def test_uint8_batches_train_end_to_end(self, image_root):
        """uint8 features cast on device inside the jitted step
        (nn/layers.policy_cast) — both fp32 and bf16 policies."""
        from deeplearning4j_tpu.nn.config import (InputType,
                                                  NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                                  GlobalPoolingLayer,
                                                  OutputLayer)
        for dtype in ("float", "bfloat16"):
            conf = (NeuralNetConfiguration.Builder().seed(0).dataType(dtype)
                    .list()
                    .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=4,
                                            activation="relu"))
                    .layer(GlobalPoolingLayer())
                    .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                       activation="softmax"))
                    .setInputType(InputType.convolutional(16, 16, 3))
                    .build())
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(conf).init()
            it = MultiWorkerImageIterator(image_root, 16, 16, batch_size=8,
                                          workers=1, drop_last=True)
            try:
                net.fit(it, epochs=1)
                assert np.isfinite(net.score())
            finally:
                it.close()
