"""Fault-tolerant training (ISSUE 5): auto-checkpoint/resume, preemption,
NaN recovery policies, transient-I/O retry — every recovery path pinned
by a DETERMINISTIC injected fault (deeplearning4j_tpu.faults).

The hard guarantee under test: ``fit(N)`` == ``fit(k)`` + preemption +
resume, BIT-EXACT for params, updater state, and the step-RNG clock —
on MultiLayerNetwork, ComputationGraph, and steps_per_dispatch>1
megastep runs.
"""

import json
import os
import signal
import zipfile

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import (AsyncDataSetIterator, DataSet,
                                             DevicePrefetcher,
                                             ListDataSetIterator,
                                             NormalizerStandardize,
                                             RetryingDataSetIterator,
                                             TransientDataError)
from deeplearning4j_tpu.faults import FaultPlan
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, DropoutLayer, OutputLayer
from deeplearning4j_tpu.train import updaters
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    LocalFileModelSaver, MaxEpochsTerminationCondition)
from deeplearning4j_tpu.train.resilience import (CheckpointConfig,
                                                 CheckpointManager,
                                                 CorruptCheckpointError,
                                                 NanPolicy, NanRecovery,
                                                 StepPreemption)
from deeplearning4j_tpu.train.serializer import (CorruptModelError,
                                                 ModelSerializer)
from deeplearning4j_tpu.utils.environment import NumericsPanicError

NIN, NOUT, BATCH, NBATCH = 6, 3, 4, 10


def mlp(seed=42, lr=0.01, dropout=False):
    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(updaters.Adam(lr)).list()
         .layer(DenseLayer(nOut=8, activation="relu")))
    if dropout:
        b = b.layer(DropoutLayer(0.5))
    conf = (b.layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                                activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def graph_net(seed=7):
    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(updaters.Adam(0.01)).graphBuilder())
    b.addInputs("in").setInputTypes(InputType.feedForward(NIN))
    b.addLayer("d1", DenseLayer(nOut=8, activation="relu"), "in")
    b.addLayer("out", OutputLayer(nOut=NOUT, lossFunction="mcxent",
                                  activation="softmax"), "d1")
    b.setOutputs("out")
    return ComputationGraph(b.build()).init()


def dataset(n=NBATCH * BATCH, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, NIN).astype(np.float32)
    y = np.eye(NOUT, dtype=np.float32)[rng.randint(0, NOUT, n)]
    return DataSet(x, y)


def iterator(seed=0, shuffle=False):
    return ListDataSetIterator(dataset(seed=seed), batch_size=BATCH,
                               shuffle=shuffle)


def leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def assert_training_state_equal(a, b):
    assert np.array_equal(np.asarray(a.params()), np.asarray(b.params())), \
        "params not bit-exact"
    assert leaves_equal(a._opt_state, b._opt_state), "opt state not bit-exact"
    assert a._iteration == b._iteration
    assert int(a._ensure_clock()) == int(b._ensure_clock()), \
        "step-RNG clock diverged"


# ===================================================================== resume
class TestResumeEquivalence:
    def _run(self, build, tmp_path, k=1, preempt_at=6):
        """fit(10) vs fit->preempt@6->resume(4); returns (straight, resumed)."""
        straight = build()
        straight.fit(iterator(), epochs=1, steps_per_dispatch=k)
        d = str(tmp_path / "ckpts")
        pre = build()
        pre.fit(iterator(), epochs=1, steps_per_dispatch=k,
                checkpoint=CheckpointConfig(d, every_steps=2),
                faults=FaultPlan(preempt_at_step=preempt_at))
        assert pre._preempted and pre._iteration == preempt_at
        res = build()
        res.fit(iterator(), epochs=1, steps_per_dispatch=k,
                checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == NBATCH
        return straight, res

    def test_multilayer_bit_exact(self, tmp_path):
        a, b = self._run(mlp, tmp_path)
        assert_training_state_equal(a, b)

    def test_multilayer_dropout_rng_bit_exact(self, tmp_path):
        # dropout keys come from fold_in(seed, t): resume restores t, so
        # the post-resume dropout masks are the straight run's exactly
        a, b = self._run(lambda: mlp(dropout=True), tmp_path)
        assert_training_state_equal(a, b)

    def test_graph_bit_exact(self, tmp_path):
        a, b = self._run(graph_net, tmp_path)
        assert_training_state_equal(a, b)

    def test_megastep_bit_exact(self, tmp_path):
        a, b = self._run(mlp, tmp_path, k=2)
        assert_training_state_equal(a, b)

    def test_preempted_manifest_status_and_cursor(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=3),
                faults=FaultPlan(preempt_at_step=7))
        mgr = CheckpointManager(CheckpointConfig(d))
        path, manifest = mgr.latest_valid()
        assert manifest["status"] == "preempted"
        assert manifest["step"] == 7
        with open(os.path.join(path, "extra.json")) as f:
            cursor = json.load(f)["cursor"]
        assert cursor == {"pos": 7 * BATCH, "epoch": 0}

    def test_shuffled_iterator_cursor_resume(self, tmp_path):
        # seek() rebuilds the seeded shuffle order for the stored epoch
        d = str(tmp_path / "c")
        build = lambda: mlp()
        a = build()
        a.fit(iterator(shuffle=True), epochs=1)
        pre = build()
        pre.fit(iterator(shuffle=True), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=2),
                faults=FaultPlan(preempt_at_step=4))
        res = build()
        res.fit(iterator(shuffle=True), epochs=1,
                checkpoint=CheckpointConfig(d, resume=True))
        assert_training_state_equal(a, res)

    def test_resume_with_empty_dir_is_fresh_run(self, tmp_path):
        d = str(tmp_path / "nothing")
        a = mlp()
        a.fit(iterator(), epochs=1, checkpoint=CheckpointConfig(d, resume=True))
        b = mlp()
        b.fit(iterator(), epochs=1)
        assert_training_state_equal(a, b)

    def test_multi_epoch_resume_runs_remaining_epochs(self, tmp_path):
        d = str(tmp_path / "c")
        a = mlp()
        a.fit(iterator(), epochs=3)
        pre = mlp()
        pre.fit(iterator(), epochs=3, checkpoint=CheckpointConfig(d, every_steps=5),
                faults=FaultPlan(preempt_at_step=15))   # mid-epoch 1
        assert pre._iteration == 15
        res = mlp()
        res.fit(iterator(), epochs=3, checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == 3 * NBATCH
        assert_training_state_equal(a, res)


# ============================================================== NaN policies
class TestNanPolicies:
    def test_raise(self):
        net = mlp()
        with pytest.raises(NumericsPanicError, match="iteration 3"):
            net.fit(iterator(), nan_policy=NanPolicy.RAISE,
                    faults=FaultPlan(nan_grads_at=[3]))

    def test_skip_step_bit_exact_vs_manual_skip(self):
        # SKIP_STEP drops the poisoned update but consumes the iteration
        # (t advances): reproduce by hand and compare bit-exact
        batches = dataset().batchBy(BATCH)
        a = mlp()
        a.fit(iterator(), nan_policy=NanPolicy.SKIP_STEP,
              faults=FaultPlan(nan_grads_at=[3]))
        assert a._iteration == NBATCH
        b = mlp()
        for j, ds in enumerate(batches):
            if j == 2:                      # batch 3 never lands...
                b._iteration += 1           # ...but its step number is spent
                b._t_dev = b._ensure_clock() + 1
                continue
            b._fit_one(ds)
        assert_training_state_equal(a, b)
        assert np.isfinite(np.asarray(a.params())).all()

    def test_skip_step_megastep_dispatch_granularity(self):
        # a poisoned sub-step skips the WHOLE K-step dispatch (the
        # compiled program is atomic): steps 3..4 both roll back
        a = mlp()
        a.fit(iterator(), steps_per_dispatch=2,
              nan_policy=NanPolicy.SKIP_STEP,
              faults=FaultPlan(nan_grads_at=[3]))
        assert a._iteration == NBATCH
        assert np.isfinite(np.asarray(a.params())).all()

    def test_backoff_lr_halves_then_recovers(self):
        net = mlp()
        net.fit(iterator(),
                nan_policy=NanRecovery(NanPolicy.BACKOFF_LR,
                                       cooldown_steps=100),  # no recovery yet
                faults=FaultPlan(nan_grads_at=[3]))
        assert getattr(net.conf.base.updater, "_lr_scale", 1.0) == 0.5
        assert np.isfinite(np.asarray(net.params())).all()
        net.conf.base.updater._lr_scale = 1.0   # don't leak into other tests

    def test_backoff_lr_recovers_after_cooldown(self):
        net = mlp()
        net.fit(iterator(),
                nan_policy=NanRecovery(NanPolicy.BACKOFF_LR, cooldown_steps=3),
                faults=FaultPlan(nan_grads_at=[3]))
        # 7 clean steps after the backoff > cooldown: scale recovered
        assert getattr(net.conf.base.updater, "_lr_scale", 1.0) == 1.0

    def test_backoff_lr_scale_survives_resume(self, tmp_path):
        # the halved LR is training state: a resume restoring full LR
        # would re-trip the instability the backoff was suppressing
        d = str(tmp_path / "c")
        pre = mlp()
        pre.fit(iterator(),
                checkpoint=CheckpointConfig(d, every_steps=2),
                nan_policy=NanRecovery(NanPolicy.BACKOFF_LR,
                                       cooldown_steps=100),
                faults=FaultPlan(nan_grads_at=[3], preempt_at_step=6))
        assert getattr(pre.conf.base.updater, "_lr_scale", 1.0) == 0.5
        pre.conf.base.updater._lr_scale = 1.0    # fresh conf in resumed run
        res = mlp()
        res.fit(iterator(),
                checkpoint=CheckpointConfig(d, resume=True),
                nan_policy=NanRecovery(NanPolicy.BACKOFF_LR,
                                       cooldown_steps=100))
        assert getattr(res.conf.base.updater, "_lr_scale", 1.0) == 0.5
        res.conf.base.updater._lr_scale = 1.0    # don't leak across tests

    def test_rollback_restores_last_checkpoint(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=CheckpointConfig(d, every_steps=2),
                nan_policy=NanPolicy.ROLLBACK,
                faults=FaultPlan(nan_grads_at=[5]))
        # rolled 5 -> 4, then the remaining 5 batches: 9 total
        assert net._iteration == 9
        assert np.isfinite(np.asarray(net.params())).all()

    def test_rollback_without_checkpoint_raises(self):
        net = mlp()
        with pytest.raises(NumericsPanicError, match="ROLLBACK requires"):
            net.fit(iterator(), nan_policy=NanPolicy.ROLLBACK,
                    faults=FaultPlan(nan_grads_at=[3]))

    def test_nonfinite_metric_counted(self):
        from deeplearning4j_tpu.train.resilience import NONFINITE_STEPS
        before = NONFINITE_STEPS.value
        net = mlp()
        net.fit(iterator(), nan_policy=NanPolicy.SKIP_STEP,
                faults=FaultPlan(nan_grads_at=[2, 6]))
        assert NONFINITE_STEPS.value - before == 2


# =============================================================== preemption
class TestPreemption:
    def test_mid_megastep_finishes_dispatch_then_checkpoints(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), steps_per_dispatch=4,
                checkpoint=CheckpointConfig(d),
                faults=FaultPlan(preempt_at_step=2))
        # the signal fired during the first 4-step dispatch: it completes
        # before the preemption is honored
        assert net._iteration == 4
        _, manifest = CheckpointManager(CheckpointConfig(d)).latest_valid()
        assert manifest["status"] == "preempted" and manifest["step"] == 4

    def test_sigterm_checkpoints_and_returns(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()

        class Bomb:
            def iterationDone(self, model, iteration, epoch):
                if iteration == 3:
                    os.kill(os.getpid(), signal.SIGTERM)
        net.setListeners(Bomb())
        net.fit(iterator(), epochs=1, checkpoint=CheckpointConfig(d))
        assert net._preempted and net._iteration < NBATCH
        _, manifest = CheckpointManager(CheckpointConfig(d)).latest_valid()
        assert manifest["status"] == "preempted"
        # handlers restored after fit
        assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL,
                                                    signal.Handlers.SIG_DFL)

    def test_step_preemption_signal_api(self):
        sig = StepPreemption(5)
        assert not sig.requested(4)
        assert sig.requested(5) and sig.requested(6)


# ============================================================== checkpoints
class TestCheckpointManager:
    def test_rotation_keep_last(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=CheckpointConfig(d, every_steps=2,
                                                        keep_last=2))
        mgr = CheckpointManager(CheckpointConfig(d))
        steps = [s for s, _ in mgr.checkpoints()]
        assert steps == [8, 10]

    def test_corrupt_checkpoint_quarantined_resume_uses_older(self, tmp_path):
        d = str(tmp_path / "c")
        pre = mlp()
        pre.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=2, keep_last=10),
                faults=FaultPlan(checkpoint_corrupt_at=[6],
                                 preempt_at_step=6))
        # the preempted save re-wrote step 6 cleanly over the corrupt one;
        # corrupt it again by hand so resume really faces damage
        target = os.path.join(d, "ckpt_0000000006", "model.zip")
        with open(target, "r+b") as f:
            f.seek(os.path.getsize(target) // 2)
            f.write(b"\x00" * 64)
        with pytest.warns(UserWarning, match="quarantined corrupt checkpoint"):
            res = mlp()
            res.fit(iterator(), epochs=1,
                    checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == NBATCH
        entries = os.listdir(d)
        assert any(e.startswith("quarantine_ckpt_0000000006") for e in entries)
        # the older step-4 checkpoint carried the resume
        mgr = CheckpointManager(CheckpointConfig(d))
        assert 4 in [s for s, _ in mgr.checkpoints()]

    def test_validate_names_bad_file(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=CheckpointConfig(d, every_steps=5))
        mgr = CheckpointManager(CheckpointConfig(d))
        path = mgr.checkpoints()[-1][1]
        with open(os.path.join(path, "model.zip"), "ab") as f:
            f.write(b"garbage")
        with pytest.raises(CorruptCheckpointError, match="model.zip"):
            mgr.validate(path)

    def test_write_failure_retried(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(),
                checkpoint=CheckpointConfig(d, every_steps=4, io_backoff=0.01),
                faults=FaultPlan(checkpoint_write_fail_at=[4]))
        mgr = CheckpointManager(CheckpointConfig(d))
        steps = [s for s, _ in mgr.checkpoints()]
        assert 4 in steps               # the failed write succeeded on retry
        for _, p in mgr.checkpoints():
            mgr.validate(p)

    def test_normalizer_round_trip(self, tmp_path):
        d = str(tmp_path / "c")
        it = iterator()
        norm = NormalizerStandardize()
        norm.fit(it.data)
        it.setPreProcessor(norm)
        net = mlp()
        net.fit(it, checkpoint=CheckpointConfig(d, every_steps=5))
        path = CheckpointManager(CheckpointConfig(d)).checkpoints()[-1][1]
        assert os.path.exists(os.path.join(path, "normalizer.npz"))
        it2 = iterator()
        norm2 = NormalizerStandardize()
        it2.setPreProcessor(norm2)       # un-fit: resume must fill it in
        res = mlp()
        res.fit(it2, checkpoint=CheckpointConfig(d, resume=True))
        np.testing.assert_array_equal(norm2.mean, norm.mean)
        np.testing.assert_array_equal(norm2.std, norm.std)

    def test_every_epochs(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), epochs=2,
                checkpoint=CheckpointConfig(d, every_epochs=1))
        steps = [s for s, _ in CheckpointManager(
            CheckpointConfig(d)).checkpoints()]
        assert steps == [NBATCH, 2 * NBATCH]

    def test_epoch_boundary_resume_trains_all_remaining_epochs(self, tmp_path):
        # an epoch-end checkpoint must NOT carry the exhausted iterator
        # cursor: resuming from it would seek past the data and silently
        # run the first resumed epoch with zero batches
        d = str(tmp_path / "c")
        a = mlp()
        a.fit(iterator(), epochs=3)
        partial = mlp()
        partial.fit(iterator(), epochs=1,
                    checkpoint=CheckpointConfig(d, every_epochs=1))
        res = mlp()
        res.fit(iterator(), epochs=3,
                checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == 3 * NBATCH
        assert_training_state_equal(a, res)


# ============================================================ data pipeline
class _FlakyIterator(ListDataSetIterator):
    """Raises a transient error on chosen pull indices (once each)."""

    def __init__(self, *a, fail_at=(), transient=True, **kw):
        super().__init__(*a, **kw)
        self._fail_at = set(fail_at)
        self._transient = transient
        self._pulls = 0

    def next(self):
        self._pulls += 1
        if self._pulls in self._fail_at:
            self._fail_at.discard(self._pulls)
            self._pulls -= 1
            if self._transient:
                raise TransientDataError(f"flaky pull {self._pulls + 1}")
            raise IOError("permanent failure")
        return super().next()


class TestDataRetry:
    def test_fit_retries_transient_iterator_error_bit_exact(self):
        from deeplearning4j_tpu.data.dataset import _DATA_RETRIES
        before = _DATA_RETRIES.value
        a = mlp()
        a.fit(iterator(), faults=FaultPlan(data_error_at=[4]))
        assert a._iteration == NBATCH
        assert _DATA_RETRIES.value > before
        b = mlp()
        b.fit(iterator())
        assert_training_state_equal(a, b)   # the retry delivered batch 4

    def test_fit_permanent_error_propagates(self):
        net = mlp()
        with pytest.raises(IOError, match="permanent"):
            net.fit(iterator(),
                    faults=FaultPlan(data_error_at=[4],
                                     data_error_transient=False))

    def test_retrying_iterator_direct(self):
        it = RetryingDataSetIterator(
            _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[2, 5]),
            max_retries=2, backoff=0.001)
        n = 0
        while it.hasNext():
            it.next()
            n += 1
        assert n == NBATCH

    def test_retrying_iterator_gives_up(self):
        it = RetryingDataSetIterator(
            _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[2],
                           transient=False),
            max_retries=3, backoff=0.001)
        it.next()
        with pytest.raises(IOError):
            it.next()

    def test_async_iterator_retries_transient(self):
        base = _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[3])
        it = AsyncDataSetIterator(base, max_retries=2, retry_backoff=0.001)
        got = 0
        while it.hasNext():
            it.next()
            got += 1
        it.close()
        assert got == NBATCH

    def test_async_close_propagates_undelivered_error(self):
        base = _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[2],
                              transient=False)
        it = AsyncDataSetIterator(base, prefetch=8)
        it.next()                        # consume one good batch
        import time
        time.sleep(0.2)                  # let the worker hit the failure
        with pytest.raises(IOError, match="permanent"):
            it.close()
        it.close()                       # double close: idempotent, no raise

    def test_async_error_delivered_via_next_not_reraised_on_close(self):
        base = _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[1],
                              transient=False)
        it = AsyncDataSetIterator(base)
        with pytest.raises(IOError):
            while it.hasNext():
                it.next()
        it.close()                       # already delivered: no raise

    def test_prefetcher_close_propagates_undelivered_error(self):
        def stream():
            yield dataset().batchBy(BATCH)[0]
            raise IOError("boom in worker")
        pf = DevicePrefetcher(stream(), prefetch=4)
        next(iter(pf))
        import time
        time.sleep(0.2)
        with pytest.raises(IOError, match="boom"):
            pf.close()
        pf.close()                       # idempotent

    def test_prefetcher_retry_on_iterator_source(self):
        base = _FlakyIterator(dataset(), batch_size=BATCH, fail_at=[3])
        pf = DevicePrefetcher(base, steps_per_dispatch=1, max_retries=2,
                              retry_backoff=0.001)
        items = list(pf)
        assert len(items) == NBATCH


# =========================================================== early stopping
class TestEarlyStoppingResume:
    def _trainer(self, net, d, max_epochs, ckpt):
        val = iterator(seed=99)
        cfg = (EarlyStoppingConfiguration.Builder()
               .scoreCalculator(DataSetLossCalculator(val))
               .epochTerminationConditions(
                   MaxEpochsTerminationCondition(max_epochs))
               .modelSaver(LocalFileModelSaver(os.path.join(d, "best")))
               .build())
        return EarlyStoppingTrainer(cfg, net, iterator(), checkpoint=ckpt)

    def test_resume_keeps_best_score_state(self, tmp_path):
        d = str(tmp_path)
        ckdir = os.path.join(d, "ck")
        t1 = self._trainer(mlp(), d, 2, CheckpointConfig(ckdir))
        r1 = t1.fit()
        assert r1.total_epochs == 2 and len(r1.score_vs_epoch) == 2
        # resumed trainer continues at epoch 3 with the best state intact
        t2 = self._trainer(mlp(), d, 4,
                           CheckpointConfig(ckdir, resume=True))
        r2 = t2.fit()
        assert r2.total_epochs == 4
        assert set(r2.score_vs_epoch) == {1, 2, 3, 4}
        for e, s in r1.score_vs_epoch.items():
            assert r2.score_vs_epoch[e] == pytest.approx(s)
        assert r2.best_score <= r1.best_score
        assert r2.getBestModel() is not None

    def test_resume_with_in_memory_saver_warns_and_returns_final(self,
                                                                 tmp_path):
        # the default InMemoryModelSaver cannot reload a best model from a
        # dead process: the resumed run must warn and fall back to the
        # final model instead of crashing at getBestModel()
        from deeplearning4j_tpu.train.earlystopping import InMemoryModelSaver
        ckdir = str(tmp_path / "ck")
        val = iterator(seed=99)

        def trainer(max_epochs, ckpt):
            cfg = (EarlyStoppingConfiguration.Builder()
                   .scoreCalculator(DataSetLossCalculator(val))
                   .epochTerminationConditions(
                       MaxEpochsTerminationCondition(max_epochs))
                   .modelSaver(InMemoryModelSaver())
                   .build())
            return EarlyStoppingTrainer(cfg, mlp(), iterator(),
                                        checkpoint=ckpt)
        trainer(2, CheckpointConfig(ckdir)).fit()
        # make the restored best unbeatable so the resumed run never saves
        mgr = CheckpointManager(CheckpointConfig(ckdir))
        path = mgr.checkpoints()[-1][1]
        extra_path = os.path.join(path, "extra.json")
        with open(extra_path) as f:
            payload = json.load(f)
        payload["extra"]["earlystopping"]["best_score"] = -1e9
        with open(extra_path, "w") as f:
            json.dump(payload, f)
        man_path = os.path.join(path, "manifest.json")
        with open(man_path) as f:
            manifest = json.load(f)
        from deeplearning4j_tpu.train.resilience import _sha256_file
        manifest["files"]["extra.json"] = _sha256_file(extra_path)
        with open(man_path, "w") as f:
            json.dump(manifest, f)
        with pytest.warns(UserWarning, match="cannot reload the best MODEL"):
            r = trainer(3, CheckpointConfig(ckdir, resume=True)).fit()
        assert r.getBestModel() is not None     # final-model fallback

    def test_resume_with_missing_best_zip_falls_back_to_final(self, tmp_path):
        # LocalFileModelSaver pointed at a directory with no bestModel.zip
        # (fresh machine): the resumed run must return the final model,
        # not crash in getBestModel()
        import shutil
        d = str(tmp_path)
        ckdir = os.path.join(d, "ck")
        t1 = self._trainer(mlp(), d, 2, CheckpointConfig(ckdir))
        t1.fit()
        shutil.rmtree(os.path.join(d, "best"))
        # make the restored best unbeatable so no new save happens
        mgr = CheckpointManager(CheckpointConfig(ckdir))
        path = mgr.checkpoints()[-1][1]
        extra_path = os.path.join(path, "extra.json")
        with open(extra_path) as f:
            payload = json.load(f)
        payload["extra"]["earlystopping"]["best_score"] = -1e9
        with open(extra_path, "w") as f:
            json.dump(payload, f)
        man_path = os.path.join(path, "manifest.json")
        with open(man_path) as f:
            manifest = json.load(f)
        from deeplearning4j_tpu.train.resilience import _sha256_file
        manifest["files"]["extra.json"] = _sha256_file(extra_path)
        with open(man_path, "w") as f:
            json.dump(manifest, f)
        with pytest.warns(UserWarning, match="cannot reload the best MODEL"):
            r = self._trainer(mlp(), d, 3,
                              CheckpointConfig(ckdir, resume=True)).fit()
        assert r.getBestModel() is not None

    def test_async_iterator_source_warns_approximate_cursor(self, tmp_path):
        net = mlp()
        it = AsyncDataSetIterator(iterator())
        with pytest.warns(UserWarning, match="APPROXIMATE"):
            net.fit(it, epochs=1,
                    checkpoint=CheckpointConfig(str(tmp_path / "c")))
        it.close()

    def test_uninterrupted_equals_resumed(self, tmp_path):
        d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
        ra = self._trainer(mlp(), d1, 4, None).fit()
        t1 = self._trainer(mlp(), d2, 2,
                           CheckpointConfig(os.path.join(d2, "ck")))
        t1.fit()
        rb = self._trainer(mlp(), d2, 4,
                           CheckpointConfig(os.path.join(d2, "ck"),
                                            resume=True)).fit()
        assert rb.best_epoch == ra.best_epoch
        assert rb.best_score == pytest.approx(ra.best_score)
        for e in ra.score_vs_epoch:
            assert rb.score_vs_epoch[e] == pytest.approx(
                ra.score_vs_epoch[e])


# ================================================================ serializer
class TestSerializerRobustness:
    def test_write_model_atomic_no_tmp_left(self, tmp_path):
        p = str(tmp_path / "m.zip")
        net = mlp()
        net.save(p)
        assert zipfile.ZipFile(p).testzip() is None
        assert [f for f in os.listdir(tmp_path)] == ["m.zip"]

    def test_restore_truncated_zip_structured_error(self, tmp_path):
        p = str(tmp_path / "m.zip")
        net = mlp()
        net.save(p)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        with pytest.raises(CorruptModelError):
            ModelSerializer.restoreMultiLayerNetwork(p)

    def test_restore_missing_entry_named(self, tmp_path):
        p = str(tmp_path / "m.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("conf.json", "{}")
            z.writestr("meta.json", "{}")
        with pytest.raises(CorruptModelError, match="arrays.npz"):
            ModelSerializer.restoreMultiLayerNetwork(p)

    def test_restore_crc_damage_named(self, tmp_path):
        p = str(tmp_path / "m.zip")
        net = mlp()
        net.save(p)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(32)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        with pytest.raises(CorruptModelError):
            ModelSerializer.restoreMultiLayerNetwork(p)

    def test_graph_load_corrupt_structured_error(self, tmp_path):
        p = str(tmp_path / "g.zip")
        g = graph_net()
        g.save(p)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 3)
        with pytest.raises(CorruptModelError):
            ComputationGraph.load(p)

    def test_normalizer_atomic_and_structured_error(self, tmp_path):
        p = str(tmp_path / "n.npz")
        norm = NormalizerStandardize()
        norm.fit(dataset())
        ModelSerializer.writeNormalizer(norm, p)
        back = ModelSerializer.restoreNormalizer(p)
        np.testing.assert_array_equal(back.mean, norm.mean)
        with open(p, "wb") as f:
            f.write(b"not an npz")
        with pytest.raises(CorruptModelError):
            ModelSerializer.restoreNormalizer(p)


# ======================================================== sharded checkpoint
class TestShardedChecksums:
    def _tree(self):
        rng = np.random.RandomState(0)
        return {"w": jax.numpy.asarray(rng.randn(8, 4).astype(np.float32)),
                "b": jax.numpy.asarray(rng.randn(4).astype(np.float32))}

    def test_round_trip_with_checksums(self, tmp_path):
        from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                            save_sharded)
        d = str(tmp_path / "s")
        tree = self._tree()
        save_sharded(d, tree, step=3)
        with open(os.path.join(d, "manifest.json")) as f:
            manifest = json.load(f)
        for entry in manifest["leaves"].values():
            for v in entry["shards"].values():
                assert len(v["sha256"]) == 64
        out, step = load_sharded(d, tree)
        assert step == 3
        assert leaves_equal(out, tree)

    def test_corrupt_shard_rejected(self, tmp_path):
        from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                            save_sharded)
        d = str(tmp_path / "s")
        tree = self._tree()
        save_sharded(d, tree, step=1)
        # rewrite the shard file with different data: checksums mismatch
        shard = os.path.join(d, "shards_p0.npz")
        data = dict(np.load(shard))
        data = {k: v + 1 for k, v in data.items()}
        np.savez(shard, **data)
        with pytest.raises(CorruptCheckpointError, match="checksum mismatch"):
            load_sharded(d, tree)

    def test_newer_sub_manifest_step_rejected(self, tmp_path):
        from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                            save_sharded)
        d = str(tmp_path / "s")
        tree = self._tree()
        save_sharded(d, tree, step=5)
        with open(os.path.join(d, "manifest_p0.json"), "w") as f:
            json.dump({"step": 7, "leaves": {}}, f)
        with pytest.raises(CorruptCheckpointError, match="step 7"):
            load_sharded(d, tree)

    def test_older_stale_sub_manifest_ignored(self, tmp_path):
        # leftovers from an earlier save with a larger process count must
        # not make a complete, checksum-clean checkpoint unloadable
        from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                            save_sharded)
        d = str(tmp_path / "s")
        tree = self._tree()
        save_sharded(d, tree, step=10)
        with open(os.path.join(d, "manifest_p3.json"), "w") as f:
            json.dump({"step": 4, "leaves": {}}, f)
        out, step = load_sharded(d, tree)
        assert step == 10 and leaves_equal(out, tree)

    def test_single_process_save_cleans_stale_sub_manifests(self, tmp_path):
        from deeplearning4j_tpu.parallel.checkpoint import save_sharded
        d = str(tmp_path / "s")
        os.makedirs(d)
        with open(os.path.join(d, "manifest_p5.json"), "w") as f:
            json.dump({"step": 1, "leaves": {}}, f)
        save_sharded(d, self._tree(), step=2)
        assert not os.path.exists(os.path.join(d, "manifest_p5.json"))

    def test_legacy_manifest_without_checksums_loads(self, tmp_path):
        from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                            save_sharded)
        d = str(tmp_path / "s")
        tree = self._tree()
        save_sharded(d, tree, step=2)
        man = os.path.join(d, "manifest.json")
        with open(man) as f:
            manifest = json.load(f)
        for entry in manifest["leaves"].values():     # downgrade format
            entry["shards"] = {k: v["file"]
                               for k, v in entry["shards"].items()}
        with open(man, "w") as f:
            json.dump(manifest, f)
        out, _ = load_sharded(d, tree)
        assert leaves_equal(out, tree)


# ==================================================================== cursor
class TestIteratorCursor:
    def test_list_iterator_cursor_seek(self):
        it = iterator()
        for _ in range(3):
            it.next()
        c = it.cursor()
        first = it.next()
        it2 = iterator()
        it2.seek(c)
        np.testing.assert_array_equal(it2.next().features, first.features)

    def test_shuffled_cursor_rebuilds_order(self):
        it = iterator(shuffle=True)
        for _ in range(4):
            it.next()
        c = it.cursor()
        rest = [it.next().features for _ in range(3)]
        it2 = iterator(shuffle=True)
        it2.seek(c)
        for want in rest:
            np.testing.assert_array_equal(it2.next().features, want)

    def test_base_iterator_defaults(self):
        from deeplearning4j_tpu.data.dataset import DataSetIterator
        it = DataSetIterator()
        assert it.cursor() is None
        with pytest.raises(NotImplementedError):
            it.seek({"pos": 0})


# ============================================================ parallel wrapper
class TestParallelWrapperResilience:
    """Data-parallel fit over the 8-device virtual mesh: resume restores
    BEFORE replication, so the restored params distribute like fresh
    ones and the resumed run stays bit-exact."""

    def _iter(self):
        return ListDataSetIterator(dataset(n=80, seed=5), batch_size=8)

    def test_wrapper_resume_bit_exact(self, tmp_path):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        d = str(tmp_path / "c")
        a = mlp()
        ParallelWrapper(a).fit(self._iter(), epochs=1)
        pre = mlp()
        ParallelWrapper(pre).fit(self._iter(), epochs=1,
                                 checkpoint=CheckpointConfig(d, every_steps=2),
                                 faults=FaultPlan(preempt_at_step=6))
        assert pre._preempted and pre._iteration == 6
        res = mlp()
        ParallelWrapper(res).fit(self._iter(), epochs=1,
                                 checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == 10
        assert np.array_equal(np.asarray(a.params()), np.asarray(res.params()))

    def test_wrapper_megastep_resume_bit_exact(self, tmp_path):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        d = str(tmp_path / "c")
        a = mlp()
        ParallelWrapper(a).fit(self._iter(), epochs=1, steps_per_dispatch=2)
        pre = mlp()
        ParallelWrapper(pre).fit(self._iter(), epochs=1, steps_per_dispatch=2,
                                 checkpoint=CheckpointConfig(d, every_steps=2),
                                 faults=FaultPlan(preempt_at_step=6))
        res = mlp()
        ParallelWrapper(res).fit(self._iter(), epochs=1, steps_per_dispatch=2,
                                 checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == 10
        assert np.array_equal(np.asarray(a.params()), np.asarray(res.params()))


# ======================================================= async checkpointing
class TestAsyncCheckpointing:
    """ISSUE 6 tentpole (3): snapshot on device -> serialize/fsync on a
    background writer, bounded queue, errors propagated into the next
    fit step."""

    def _cfg(self, d, **kw):
        kw.setdefault("every_steps", 2)
        return CheckpointConfig(d, async_write=True, **kw)

    def test_async_checkpoints_validate_and_rotate(self, tmp_path):
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=self._cfg(d, keep_last=2))
        mgr = CheckpointManager(CheckpointConfig(d))
        steps = [s for s, _ in mgr.checkpoints()]
        assert steps == [8, 10]         # writer flushed at fit exit
        for _, p in mgr.checkpoints():
            mgr.validate(p)

    def test_async_resume_bit_exact(self, tmp_path):
        d = str(tmp_path / "c")
        straight = mlp()
        straight.fit(iterator(), epochs=1)
        pre = mlp()
        pre.fit(iterator(), epochs=1, checkpoint=self._cfg(d),
                faults=FaultPlan(preempt_at_step=6))
        assert pre._preempted and pre._iteration == 6
        res = mlp()
        res.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == NBATCH
        assert_training_state_equal(straight, res)

    def test_snapshot_isolated_from_donation(self, tmp_path):
        # the snapshot must deep-copy on device: the step that runs WHILE
        # the writer serializes donates (deletes) the live buffers, so an
        # aliasing snapshot would checkpoint freed memory. Pin by checking
        # the checkpoint for step k holds step-k params even though
        # training ran on past it before the writer caught up.
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=self._cfg(d, every_steps=4,
                                                 keep_last=10))
        mgr = CheckpointManager(CheckpointConfig(d))
        path = dict(mgr.checkpoints())[4]
        loaded = MultiLayerNetwork.load(os.path.join(path, "model.zip"))
        replay = mlp()
        for ds in list(iterator())[:4]:
            replay._fit_one(ds)
        assert np.array_equal(np.asarray(loaded.params()),
                              np.asarray(replay.params()))

    def test_writer_failure_surfaces_in_fit(self, tmp_path):
        from deeplearning4j_tpu.train.resilience import AsyncCheckpointError
        d = str(tmp_path / "c")
        net = mlp()
        with pytest.raises(AsyncCheckpointError, match="background "
                                                       "checkpoint write"):
            net.fit(iterator(),
                    checkpoint=self._cfg(d, io_retries=0),
                    faults=FaultPlan(checkpoint_write_fail_at=[2]))

    def test_write_failure_retried_in_writer_thread(self, tmp_path):
        # transient write error + io_retries: the WRITER retries and the
        # fit never notices
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(),
                checkpoint=self._cfg(d, every_steps=4, io_backoff=0.01),
                faults=FaultPlan(checkpoint_write_fail_at=[4]))
        mgr = CheckpointManager(CheckpointConfig(d))
        assert 4 in [s for s, _ in mgr.checkpoints()]

    def test_queue_depth_gauge_registered(self):
        from deeplearning4j_tpu.train.resilience import CKPT_ASYNC_QUEUE
        assert CKPT_ASYNC_QUEUE.value >= 0

    def test_async_archive_meta_type_matches_sync(self, tmp_path):
        # the snapshot proxy must not leak its own class name into the
        # archive: async and sync checkpoints are byte-compatible formats
        d = str(tmp_path / "c")
        net = mlp()
        net.fit(iterator(), checkpoint=self._cfg(d, every_steps=5))
        path = CheckpointManager(CheckpointConfig(d)).checkpoints()[-1][1]
        with zipfile.ZipFile(os.path.join(path, "model.zip")) as z:
            meta = json.loads(z.read("meta.json"))
        assert meta["type"] == "MultiLayerNetwork"


# ======================================================== TBPTT x resilience
class TestTbpttResilience:
    """Carried PR-5 follow-up: segment-level step accounting + batch-level
    cursor accounting make ``backpropType('tbptt')`` fits resume
    bit-exactly instead of being guarded off."""

    SEGS = 3    # T=12, L=4

    def _net(self, seed=11):
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(updaters.Sgd(0.05)).list()
                .layer(LSTM(nOut=6))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
                .setInputType(InputType.recurrent(3, 12))
                .backpropType("tbptt", 4)
                .build())
        return MultiLayerNetwork(conf).init(seed=seed)

    def _iter(self, n=24, seed=0):
        rng = np.random.RandomState(seed)
        feats = rng.rand(n, 3, 12).astype(np.float32)
        labs = np.zeros((n, 2, 12), np.float32)
        labs[::2, 0] = 1.0
        labs[1::2, 1] = 1.0
        return ListDataSetIterator(DataSet(feats, labs), batch_size=4)

    def test_resume_bit_exact(self, tmp_path):
        d = str(tmp_path / "c")
        straight = self._net()
        straight.fit(self._iter(), epochs=1)    # 6 batches x 3 segs = 18
        pre = self._net()
        pre.fit(self._iter(), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=2),
                faults=FaultPlan(preempt_at_step=9))
        assert pre._preempted and pre._iteration == 9   # batch boundary
        res = self._net()
        res.fit(self._iter(), epochs=1,
                checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == 6 * self.SEGS
        assert_training_state_equal(straight, res)

    def test_checkpoints_land_on_batch_boundaries(self, tmp_path):
        # every_steps=2 but 3 segment-steps per batch: saves fire at the
        # first batch boundary past the mark, where no RNN segment state
        # is carried (what makes the resume exact)
        d = str(tmp_path / "c")
        net = self._net()
        net.fit(self._iter(), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=2, keep_last=99))
        steps = [s for s, _ in
                 CheckpointManager(CheckpointConfig(d)).checkpoints()]
        assert steps and all(s % self.SEGS == 0 for s in steps)
        # the saved cursor is the matching batch-boundary position
        mgr = CheckpointManager(CheckpointConfig(d))
        for step, path in mgr.checkpoints():
            with open(os.path.join(path, "extra.json")) as f:
                cursor = json.load(f)["cursor"]
            assert cursor["pos"] == (step // self.SEGS) * 4

    def test_nan_policy_skip_drops_whole_batch(self, tmp_path):
        # batch 2 poisoned -> its 3 segment updates all skip (the batch is
        # the recovery unit), training finishes finite
        net = self._net()
        net.fit(self._iter(), epochs=1, nan_policy=NanPolicy.SKIP_STEP,
                faults=FaultPlan(nan_grads_at=[2]))
        assert net._iteration == 6 * self.SEGS
        assert np.isfinite(np.asarray(net.params())).all()
        from deeplearning4j_tpu.train.resilience import NONFINITE_STEPS
        assert NONFINITE_STEPS.value > 0

    def test_non_sequence_batches_still_single_step(self, tmp_path):
        # the W002 fallback path (non-sequence batch under a TBPTT
        # config) keeps working with a session attached
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(updaters.Sgd(0.1)).list()
                .layer(DenseLayer(nOut=8, activation="relu"))
                .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(NIN))
                .backpropType("tbptt", 4)
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(str(tmp_path / "c"),
                                            every_steps=3))
        assert net._iteration == NBATCH


# ===================================================================== chaos
@pytest.mark.chaos
class TestChaosSweep:
    """Seeded FaultPlan sweep: whatever combination of NaN batches, flaky
    pulls, and checkpoint corruption a seed draws, a SKIP_STEP +
    checkpointed fit must finish all steps with finite params."""

    @pytest.mark.parametrize("seed", range(4))
    def test_seeded_sweep(self, seed, tmp_path):
        plan = FaultPlan.seeded(seed, horizon=NBATCH, n_nan=1,
                                n_data_errors=1)
        net = mlp()
        net.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(str(tmp_path / "c"),
                                            every_steps=3, io_backoff=0.01),
                nan_policy=NanPolicy.SKIP_STEP, faults=plan)
        assert net._iteration == NBATCH
        assert np.isfinite(np.asarray(net.params())).all()

    @pytest.mark.parametrize("seed", range(2))
    def test_seeded_preemption_resume(self, seed, tmp_path):
        plan = FaultPlan.seeded(seed, horizon=NBATCH - 2, n_nan=0,
                                n_data_errors=1, preempt=True)
        d = str(tmp_path / "c")
        pre = mlp()
        pre.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(d, every_steps=2),
                nan_policy=NanPolicy.SKIP_STEP, faults=plan)
        assert pre._preempted
        res = mlp()
        res.fit(iterator(), epochs=1,
                checkpoint=CheckpointConfig(d, resume=True))
        assert res._iteration == NBATCH
        assert np.isfinite(np.asarray(res.params())).all()
