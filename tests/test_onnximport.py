"""ONNX import conformance tests.

Reference parity: ``samediff-import-onnx``'s conformance suite (SURVEY.md
§2.2). No ``onnx`` package exists in this image, so test models are
CONSTRUCTED with the in-repo wire-format encoder (the wire format is
standard protobuf; files from real exporters decode identically) and
goldens are computed with numpy.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import imports as IMP
from deeplearning4j_tpu.modelimport import onnx_proto as P
from deeplearning4j_tpu.modelimport.onnx import (OnnxImportError,
                                                 importOnnxModel)


def _model(nodes, inputs, outputs, initializers=()):
    return P.encode_model(
        nodes=nodes,
        inputs=[P.encode_value_info(n, d, s) for n, d, s in inputs],
        outputs=[P.encode_value_info(n, d, s) for n, d, s in outputs],
        initializers=[P.encode_tensor(n, a) for n, a in initializers])


class TestProtoCodec:
    def test_tensor_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        t = P.TensorProto.parse(P.encode_tensor("w", arr))
        assert t.name == "w"
        np.testing.assert_array_equal(t.array, arr)

    def test_model_parse(self):
        rng = np.random.RandomState(0)
        w = rng.randn(4, 3).astype(np.float32)
        blob = _model(
            nodes=[P.encode_node("MatMul", ["x", "w"], ["y"])],
            inputs=[("x", np.float32, [None, 4])],
            outputs=[("y", np.float32, [None, 3])],
            initializers=[("w", w)])
        m = P.load_model(blob)
        assert m.graph.nodes[0].op_type == "MatMul"
        assert m.graph.inputs[0].shape == [None, 4]
        np.testing.assert_array_equal(m.graph.initializers[0].array, w)


class TestOnnxImport:
    def _run(self, blob, feeds, out_names):
        sd = importOnnxModel(blob)
        return sd.output(feeds, out_names)

    def test_gemm_relu_mlp(self):
        rng = np.random.RandomState(0)
        w1 = rng.randn(6, 8).astype(np.float32)
        b1 = rng.randn(8).astype(np.float32)
        w2 = rng.randn(8, 3).astype(np.float32)
        blob = _model(
            nodes=[
                P.encode_node("Gemm", ["x", "w1", "b1"], ["h"], transB=0),
                P.encode_node("Relu", ["h"], ["hr"]),
                P.encode_node("MatMul", ["hr", "w2"], ["logits"]),
                P.encode_node("Softmax", ["logits"], ["probs"], axis=-1),
            ],
            inputs=[("x", np.float32, [None, 6])],
            outputs=[("probs", np.float32, [None, 3])],
            initializers=[("w1", w1), ("b1", b1), ("w2", w2)])
        x = rng.randn(4, 6).astype(np.float32)
        got = np.asarray(self._run(blob, {"x": x}, ["probs"])["probs"])
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2
        e = np.exp(logits - logits.max(-1, keepdims=True))
        want = e / e.sum(-1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)

    def test_conv_pool_batchnorm(self):
        rng = np.random.RandomState(1)
        w = (rng.randn(4, 2, 3, 3) * 0.2).astype(np.float32)
        g = (rng.rand(4) + 0.5).astype(np.float32)
        be = rng.randn(4).astype(np.float32)
        mean = rng.randn(4).astype(np.float32)
        var = (rng.rand(4) + 0.5).astype(np.float32)
        blob = _model(
            nodes=[
                P.encode_node("Conv", ["x", "w"], ["c"], pads=[1, 1, 1, 1],
                              strides=[1, 1], kernel_shape=[3, 3]),
                P.encode_node("BatchNormalization",
                              ["c", "g", "be", "mean", "var"], ["bn"],
                              epsilon=1e-5),
                P.encode_node("Relu", ["bn"], ["r"]),
                P.encode_node("MaxPool", ["r"], ["p"], kernel_shape=[2, 2],
                              strides=[2, 2]),
                P.encode_node("GlobalAveragePool", ["p"], ["gap"]),
                P.encode_node("Flatten", ["gap"], ["y"], axis=1),
            ],
            inputs=[("x", np.float32, [2, 2, 8, 8])],
            outputs=[("y", np.float32, [2, 4])],
            initializers=[("w", w), ("g", g), ("be", be), ("mean", mean),
                          ("var", var)])
        x = rng.randn(2, 2, 8, 8).astype(np.float32)
        got = np.asarray(self._run(blob, {"x": x}, ["y"])["y"])
        # numpy golden
        import jax
        c = jax.lax.conv_general_dilated(x, w, (1, 1), [(1, 1), (1, 1)],
                                         dimension_numbers=("NCHW", "OIHW",
                                                            "NCHW"))
        c = np.asarray(c)
        bn = (c - mean.reshape(1, -1, 1, 1)) / np.sqrt(
            var.reshape(1, -1, 1, 1) + 1e-5) * g.reshape(1, -1, 1, 1) \
            + be.reshape(1, -1, 1, 1)
        r = np.maximum(bn, 0)
        p = r.reshape(2, 4, 4, 2, 4, 2).max(axis=(3, 5))
        want = p.mean(axis=(2, 3))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_shape_ops_and_const_folding(self):
        rng = np.random.RandomState(2)
        blob = _model(
            nodes=[
                P.encode_node("Transpose", ["x"], ["t"], perm=[0, 2, 1]),
                P.encode_node("Reshape", ["t", "shp"], ["r"]),
                P.encode_node("Concat", ["r", "r"], ["cc"], axis=1),
                P.encode_node("Slice", ["cc", "st", "en"], ["s"]),
                P.encode_node("Unsqueeze", ["s", "ax"], ["u"]),
                P.encode_node("Squeeze", ["u", "ax"], ["y"]),
            ],
            inputs=[("x", np.float32, [2, 3, 4])],
            outputs=[("y", np.float32, None)],
            initializers=[("shp", np.asarray([2, 12], np.int64)),
                          ("st", np.asarray([0, 2], np.int64)),
                          ("en", np.asarray([2, 10], np.int64)),
                          ("ax", np.asarray([0], np.int64))])
        x = rng.randn(2, 3, 4).astype(np.float32)
        got = np.asarray(self._run(blob, {"x": x}, ["y"])["y"])
        t = np.transpose(x, (0, 2, 1)).reshape(2, 12)
        cc = np.concatenate([t, t], 1)
        want = cc[0:2, 2:10]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_reduce_and_elementwise(self):
        rng = np.random.RandomState(3)
        blob = _model(
            nodes=[
                P.encode_node("ReduceMean", ["x"], ["m"], axes=[1],
                              keepdims=1),
                P.encode_node("Sub", ["x", "m"], ["d"]),
                P.encode_node("Mul", ["d", "d"], ["sq"]),
                P.encode_node("ReduceSum", ["sq"], ["v"], axes=[1],
                              keepdims=0),
                P.encode_node("Sqrt", ["v"], ["y"]),
            ],
            inputs=[("x", np.float32, [3, 5])],
            outputs=[("y", np.float32, [3])])
        x = rng.randn(3, 5).astype(np.float32)
        got = np.asarray(self._run(blob, {"x": x}, ["y"])["y"])
        d = x - x.mean(1, keepdims=True)
        want = np.sqrt((d * d).sum(1))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_constant_node_and_clip_cast(self):
        blob = _model(
            nodes=[
                P.encode_node("Constant", [], ["k"],
                              value=np.asarray([2.0], np.float32)),
                P.encode_node("Mul", ["x", "k"], ["m"]),
                P.encode_node("Clip", ["m"], ["c"], min=0.0, max=3.0),
                P.encode_node("Cast", ["c"], ["y"], to=P.DT_INT32),
            ],
            inputs=[("x", np.float32, [4])],
            outputs=[("y", np.int32, [4])])
        x = np.asarray([-1.0, 0.5, 1.0, 5.0], np.float32)
        got = np.asarray(self._run(blob, {"x": x}, ["y"])["y"])
        np.testing.assert_array_equal(got, [0, 1, 2, 3])
        assert got.dtype == np.int32

    def test_split_multi_output(self):
        blob = _model(
            nodes=[P.encode_node("Split", ["x"], ["a", "b"], axis=1)],
            inputs=[("x", np.float32, [2, 6])],
            outputs=[("a", np.float32, [2, 3]), ("b", np.float32, [2, 3])])
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        res = self._run(blob, {"x": x}, ["a", "b"])
        np.testing.assert_array_equal(np.asarray(res["a"]), x[:, :3])
        np.testing.assert_array_equal(np.asarray(res["b"]), x[:, 3:])

    def test_save_load_roundtrip(self, tmp_path):
        rng = np.random.RandomState(4)
        w = rng.randn(5, 2).astype(np.float32)
        blob = _model(
            nodes=[
                P.encode_node("Gemm", ["x", "w"], ["h"], transB=0, alpha=2.0),
                P.encode_node("Tanh", ["h"], ["y"]),
            ],
            inputs=[("x", np.float32, [3, 5])],
            outputs=[("y", np.float32, [3, 2])],
            initializers=[("w", w)])
        sd = importOnnxModel(blob)
        x = rng.randn(3, 5).astype(np.float32)
        want = np.asarray(sd.output({"x": x}, ["y"])["y"])
        p = str(tmp_path / "onnx.sdz")
        sd.save(p)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(p)
        got = np.asarray(sd2.output({"x": x}, ["y"])["y"])
        np.testing.assert_allclose(got, want, rtol=0, atol=0)
        np.testing.assert_allclose(want, np.tanh(2.0 * (x @ w)), rtol=1e-5)

    def test_unmapped_op_reported(self):
        blob = _model(
            nodes=[P.encode_node("NonMaxSuppression", ["x"], ["y"])],
            inputs=[("x", np.float32, [4])],
            outputs=[("y", np.float32, [4])])
        with pytest.raises(OnnxImportError, match="NonMaxSuppression"):
            importOnnxModel(blob)

    def test_file_roundtrip(self, tmp_path):
        blob = _model(
            nodes=[P.encode_node("Relu", ["x"], ["y"])],
            inputs=[("x", np.float32, [3])],
            outputs=[("y", np.float32, [3])])
        p = str(tmp_path / "m.onnx")
        with open(p, "wb") as f:
            f.write(blob)
        sd = importOnnxModel(p)
        got = np.asarray(sd.output(
            {"x": np.asarray([-1.0, 0.0, 2.0], np.float32)}, ["y"])["y"])
        np.testing.assert_array_equal(got, [0.0, 0.0, 2.0])

class TestHalfPrecisionIntData:
    """ADVICE r3 (low): fp16/bf16 tensors serialized via int32_data hold raw
    bit patterns — decode must reinterpret bits, not value-cast."""

    def test_fp16_int_data_bit_pattern(self):
        vals = np.array([1.5, -2.25, 0.0078125], np.float16)
        buf = bytearray()
        P._w_int(buf, 1, 3)                 # dims
        P._w_int(buf, 2, P.DT_FLOAT16)      # data_type
        for bits in vals.view(np.uint16):   # int32_data as varints
            P._w_int(buf, 5, int(bits))
        t = P.TensorProto.parse(bytes(buf))
        assert t.array.dtype == np.float16
        np.testing.assert_array_equal(t.array, vals)

    def test_bf16_int_data_bit_pattern(self):
        import ml_dtypes
        vals = np.array([1.0, -3.5, 0.125], ml_dtypes.bfloat16)
        buf = bytearray()
        P._w_int(buf, 1, 3)
        P._w_int(buf, 2, P.DT_BFLOAT16)
        for bits in vals.view(np.uint16):
            P._w_int(buf, 5, int(bits))
        t = P.TensorProto.parse(bytes(buf))
        assert t.array.dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(t.array.astype(np.float32),
                                      vals.astype(np.float32))


class TestImportLints:
    """DL4J-E16x/W16x import-time lints (ISSUE 18): the jax-free ONNX
    pre-scan, the report the importer attaches as ``import_report``, and
    full lint parity through ``sd.validate()`` on an imported graph."""

    def _codes(self, diags):
        return [d.code for d in diags]

    # ---- E161: unmapped op (pre-scan reports ALL, importer raises) ----

    def test_e161_prescan_reports_every_unmapped_op(self):
        blob = _model(
            nodes=[P.encode_node("NonMaxSuppression", ["x"], ["y"]),
                   P.encode_node("StringNormalizer", ["y"], ["z"])],
            inputs=[("x", np.float32, [4])],
            outputs=[("z", np.float32, [4])])
        report = IMP.lint_onnx_model(P.load_model(blob))
        codes = self._codes(report)
        assert codes.count("DL4J-E161") == 2, report.format()
        text = report.format()
        assert "NonMaxSuppression" in text and "StringNormalizer" in text

    def test_supported_ops_pin_matches_importer(self):
        from deeplearning4j_tpu.modelimport.onnx import _BUILDERS
        assert IMP.SUPPORTED_ONNX_OPS == frozenset(_BUILDERS) | {"Constant"}

    # ---- E162: attribute semantics the lowering does not honor ----

    def test_e162_ceil_mode_pool(self):
        blob = _model(
            nodes=[P.encode_node("MaxPool", ["x"], ["y"],
                                 kernel_shape=[2, 2], strides=[2, 2],
                                 ceil_mode=1)],
            inputs=[("x", np.float32, [1, 3, 5, 5])],
            outputs=[("y", np.float32, [1, 3, 3, 3])])
        report = IMP.lint_onnx_model(P.load_model(blob))
        assert "DL4J-E161" not in self._codes(report)
        assert "DL4J-E162" in self._codes(report), report.format()
        assert "ceil_mode" in report.format()

    def test_e162_same_lower_conv(self):
        w = np.zeros((4, 3, 3, 3), np.float32)
        blob = _model(
            nodes=[P.encode_node("Conv", ["x", "w"], ["y"],
                                 kernel_shape=[3, 3],
                                 auto_pad="SAME_LOWER")],
            inputs=[("x", np.float32, [1, 3, 8, 8])],
            outputs=[("y", np.float32, [1, 4, 8, 8])],
            initializers=[("w", w)])
        report = IMP.lint_onnx_model(P.load_model(blob))
        assert "DL4J-E162" in self._codes(report), report.format()

    def test_e162_clean_pool_has_no_findings(self):
        blob = _model(
            nodes=[P.encode_node("MaxPool", ["x"], ["y"],
                                 kernel_shape=[2, 2], strides=[2, 2])],
            inputs=[("x", np.float32, [None, 3, 8, 8])],
            outputs=[("y", np.float32, [None, 3, 4, 4])])
        report = IMP.lint_onnx_model(P.load_model(blob))
        assert not report.diagnostics, report.format()

    # ---- E163: lossy dtype narrowing ----

    def test_e163_float64_initializer(self):
        w = np.eye(3, dtype=np.float64)
        blob = _model(
            nodes=[P.encode_node("MatMul", ["x", "w"], ["y"])],
            inputs=[("x", np.float32, [None, 3])],
            outputs=[("y", np.float32, [None, 3])],
            initializers=[("w", w)])
        report = IMP.lint_onnx_model(P.load_model(blob))
        assert "DL4J-E163" in self._codes(report), report.format()
        assert "float64" in report.format()

    def test_e163_int64_only_when_out_of_int32_range(self):
        big = np.asarray([2 ** 40], np.int64)
        small = np.asarray([1, 2, 3], np.int64)
        for arr, expect in ((big, True), (small, False)):
            diags = IMP.lint_narrowed_array(arr, "initializer 'ax'")
            has = "DL4J-E163" in self._codes(diags)
            assert has is expect, (arr, [str(d) for d in diags])

    # ---- W161: dynamic-dim placeholders ----

    def test_w161_dynamic_non_batch_dim(self):
        diags = IMP.lint_placeholder_shape((None, None, 224), "input 'x'")
        assert self._codes(diags) == ["DL4J-W161"]
        # a dynamic BATCH dim alone is the normal serving contract
        assert not IMP.lint_placeholder_shape((None, 3, 224), "input 'x'")

    def test_w161_fully_dynamic_graph_input(self):
        blob = _model(
            nodes=[P.encode_node("Relu", ["x"], ["y"])],
            inputs=[("x", np.float32, [None, None, None])],
            outputs=[("y", np.float32, [None, None, None])])
        report = IMP.lint_onnx_model(P.load_model(blob))
        assert "DL4J-W161" in self._codes(report), report.format()
        # rank-unknown (no shape recorded at all) is the worst case
        assert IMP.lint_placeholder_shape(None, "input 'x'")

    # ---- W162: frozen-graph constants under a TrainingConfig ----

    def test_w162_frozen_weight_with_training_config(self):
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        w = np.ones((4, 3), np.float32)
        blob = _model(
            nodes=[P.encode_node("MatMul", ["x", "w"], ["y"])],
            inputs=[("x", np.float32, [None, 4])],
            outputs=[("y", np.float32, [None, 3])],
            initializers=[("w", w)])
        sd = importOnnxModel(blob)
        assert not IMP.lint_frozen_constants(sd)   # no config, no finding
        sd.setTrainingConfig(TrainingConfig())
        diags = IMP.lint_frozen_constants(sd)
        assert self._codes(diags) == ["DL4J-W162"], [str(d) for d in diags]
        sd.convertToVariables("w")
        assert not IMP.lint_frozen_constants(sd)

    # ---- W163: const-folding overflow ----

    def test_w163_folded_inf(self):
        a = np.asarray([3.0e38], np.float32)
        blob = _model(
            nodes=[P.encode_node("Add", ["a", "a"], ["s"]),
                   P.encode_node("Add", ["x", "s"], ["y"])],
            inputs=[("x", np.float32, [None, 1])],
            outputs=[("y", np.float32, [None, 1])],
            initializers=[("a", a)])
        sd = importOnnxModel(blob)
        codes = self._codes(sd.import_report)
        assert "DL4J-W163" in codes, sd.import_report.format()

    def test_fold_overflow_direct(self):
        assert IMP.fold_overflow_diags(
            "Add", "s", [np.asarray([np.inf], np.float32)])
        assert IMP.fold_overflow_diags(
            "Mul", "s", [np.asarray([2 ** 40], np.int64)])
        assert not IMP.fold_overflow_diags(
            "Add", "s", [np.asarray([1.0], np.float32)])

    # ---- report plumbing + full-parity acceptance ----

    def test_clean_import_attaches_empty_report(self):
        w = np.ones((4, 4), np.float32)
        blob = _model(
            nodes=[P.encode_node("MatMul", ["x", "w"], ["y"])],
            inputs=[("x", np.float32, [None, 4])],
            outputs=[("y", np.float32, [None, 4])],
            initializers=[("w", w)])
        sd = importOnnxModel(blob)
        assert hasattr(sd, "import_report")
        assert not sd.import_report.diagnostics, sd.import_report.format()

    def _resnet_ish(self, classes=260):
        """Conv stem -> GAP -> classifier, ONNX-exporter shaped."""
        rng = np.random.RandomState(0)
        w = rng.randn(32, 3, 3, 3).astype(np.float32) * 0.1
        fcw = rng.randn(32, classes).astype(np.float32) * 0.1
        fcb = np.zeros((classes,), np.float32)
        return _model(
            nodes=[
                P.encode_node("Conv", ["x", "w"], ["c"],
                              kernel_shape=[3, 3], strides=[2, 2],
                              pads=[1, 1, 1, 1]),
                P.encode_node("Relu", ["c"], ["r"]),
                P.encode_node("GlobalAveragePool", ["r"], ["g"]),
                P.encode_node("Flatten", ["g"], ["f"]),
                P.encode_node("Gemm", ["f", "fcw", "fcb"], ["y"],
                              transB=0),
            ],
            inputs=[("x", np.float32, [None, 3, 32, 32])],
            outputs=[("y", np.float32, [None, classes])],
            initializers=[("w", w), ("fcw", fcw), ("fcb", fcb)])

    def test_full_lint_parity_on_imported_model(self):
        """ISSUE 18 acceptance: sd.validate(mesh=..., policy='bf16',
        data_range='0..255') on an imported graph emits layout +
        distribution + numerics codes — the exact codes a native config
        would get."""
        sd = importOnnxModel(self._resnet_ish(classes=260))
        report = sd.validate(batch_size=12, mesh={"data": 8},
                             policy="bf16", data_range="0..255")
        codes = set(report.codes())
        assert "DL4J-W101" in codes, report.format()   # layout: 260 lanes
        assert "DL4J-E101" in codes, report.format()   # dist: 12 % 8 != 0
        assert "DL4J-W303" in codes, report.format()   # numerics: 0..255
        # and the well-configured spelling is fully clean
        sd2 = importOnnxModel(self._resnet_ish(classes=256))
        clean = sd2.validate(batch_size=16, mesh={"data": 8},
                             policy="bf16", data_range="0..1,normalized")
        assert clean.ok(warnings_as_errors=True), clean.format()

    def test_cli_onnx_path(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        p = str(tmp_path / "m.onnx")
        with open(p, "wb") as f:
            f.write(self._resnet_ish(classes=256))
        assert main(["--onnx", p]) == 0
        out = capsys.readouterr().out
        assert "clean" in out

    def test_cli_onnx_unmapped_op_fails(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        blob = _model(
            nodes=[P.encode_node("NonMaxSuppression", ["x"], ["y"])],
            inputs=[("x", np.float32, [4])],
            outputs=[("y", np.float32, [4])])
        p = str(tmp_path / "bad.onnx")
        with open(p, "wb") as f:
            f.write(blob)
        assert main(["--onnx", p]) == 1
        assert "DL4J-E161" in capsys.readouterr().out
