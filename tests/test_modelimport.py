"""Keras h5 import parity tests.

Reference parity: the reference's Keras-import tests load stored .h5
fixtures and compare per-layer outputs against Keras-computed goldens
(SURVEY.md §4 "Keras import tests"). Keras itself is available in this
environment, so the fixtures are GENERATED live and the goldens are
Keras's own predict() — stronger than stored files.
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers as KL  # noqa: E402

from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    KerasImportError, importKerasModelAndWeights,
    importKerasSequentialModelAndWeights)


def _save(tmp_path, model, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def _nchw(x):
    return np.transpose(x, (0, 3, 1, 2))


class TestSequentialImport:
    def test_mlp_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6,)),
            KL.Dense(8, activation="relu", name="d1"),
            KL.Dense(3, activation="softmax", name="d2"),
        ])
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_cnn_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(8, 8, 3)),
            KL.Conv2D(4, 3, padding="same", activation="relu", name="c1"),
            KL.MaxPooling2D(2, name="p1"),
            KL.BatchNormalization(name="bn1"),
            KL.Conv2D(6, 3, padding="valid", strides=2, activation="tanh",
                      name="c2"),
            KL.Flatten(name="f1"),
            KL.Dense(5, activation="softmax", name="d1"),
        ])
        x = np.random.RandomState(1).randn(2, 8, 8, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_avgpool_depthwise_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6, 6, 4)),
            KL.DepthwiseConv2D(3, padding="same", depth_multiplier=2,
                               activation="relu", name="dw"),
            KL.AveragePooling2D(2, name="ap"),
            KL.GlobalAveragePooling2D(name="gap"),
            KL.Dense(3, name="d"),
        ])
        x = np.random.RandomState(2).randn(2, 6, 6, 4).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_lstm_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(5, 3)),       # [T, C] keras
            KL.LSTM(7, return_sequences=True, name="l1"),
            KL.LSTM(4, return_sequences=False, name="l2"),
            KL.Dense(2, activation="softmax", name="d"),
        ])
        x = np.random.RandomState(3).randn(2, 5, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))  # [N, C, T]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_simple_rnn_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(4, 2)),
            KL.SimpleRNN(5, return_sequences=False, name="r1"),
            KL.Dense(2, name="d"),
        ])
        x = np.random.RandomState(4).randn(3, 4, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gru_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(5, 4)),
            KL.GRU(6, return_sequences=True, name="g1"),
            KL.GRU(3, name="g2"),
        ])
        x = np.random.RandomState(1).randn(2, 5, 4).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))  # [N,C,T]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_bidirectional_lstm_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6, 3)),
            KL.Bidirectional(KL.LSTM(5, return_sequences=True), name="bi1"),
            KL.Bidirectional(KL.LSTM(4), merge_mode="sum", name="bi2"),
        ])
        x = np.random.RandomState(2).randn(2, 6, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv1d_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(10, 3)),
            KL.Conv1D(8, 3, padding="causal", activation="relu", name="c1"),
            KL.Conv1D(4, 3, padding="same", name="c2"),
            KL.GlobalAveragePooling1D(name="gp"),
        ])
        x = np.random.RandomState(3).randn(2, 10, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_separable_pad_crop_upsample_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(8, 8, 3)),
            KL.ZeroPadding2D(((1, 2), (0, 1)), name="zp"),
            KL.SeparableConv2D(6, (3, 3), padding="valid",
                               activation="relu", name="sc"),
            KL.UpSampling2D((2, 2), name="up"),
            KL.Cropping2D(((1, 1), (2, 2)), name="cr"),
            KL.GlobalAveragePooling2D(name="gp"),
        ])
        x = np.random.RandomState(4).rand(2, 8, 8, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_init_pretrained_from_h5(self, tmp_path):
        from deeplearning4j_tpu.models.zoo import LeNet
        m = keras.Sequential([
            keras.Input(shape=(6,)),
            KL.Dense(4, activation="relu"),
            KL.Dense(2, activation="softmax"),
        ])
        p = _save(tmp_path, m, "pre.h5")
        net = LeNet().initPretrained(path=p)
        x = np.random.RandomState(5).randn(3, 6).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   m.predict(x, verbose=0),
                                   rtol=1e-4, atol=1e-5)

    def test_pool1d_layernorm_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(12, 6)),
            KL.Conv1D(8, 3, padding="same", activation="relu", name="c"),
            KL.MaxPooling1D(2, name="mp"),
            KL.LayerNormalization(name="ln"),
            KL.AveragePooling1D(2, name="ap"),
            KL.GlobalAveragePooling1D(name="gp"),
        ])
        x = np.random.RandomState(6).randn(2, 12, 6).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_prelu_elu_repeat_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(5,)),
            KL.Dense(6, name="d"),
            KL.PReLU(name="pr"),
            KL.ELU(name="el"),
            KL.RepeatVector(3, name="rv"),
            KL.GRU(4, name="g"),
        ])
        x = np.random.RandomState(7).randn(3, 5).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unsupported_layer_reported(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(8, 8, 3)),
            KL.RandomRotation(0.2, name="weird"),   # preprocessing layer
            KL.Conv2D(4, 3, name="c"),
        ])
        with pytest.raises(KerasImportError, match="RandomRotation"):
            importKerasSequentialModelAndWeights(_save(tmp_path, m))


class TestFunctionalImport:
    def test_two_branch_parity(self, tmp_path):
        inp = keras.Input(shape=(8, 8, 3), name="in0")
        a = KL.Conv2D(4, 3, padding="same", activation="relu", name="ca")(inp)
        b = KL.Conv2D(4, 5, padding="same", activation="relu", name="cb")(inp)
        s = KL.Add(name="add")([a, b])
        c = KL.Concatenate(name="cat")([s, a])
        g = KL.GlobalAveragePooling2D(name="gap")(c)
        out = KL.Dense(3, activation="softmax", name="d")(g)
        m = keras.Model(inp, out)
        x = np.random.RandomState(5).randn(2, 8, 8, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_functional_flatten_dense_parity(self, tmp_path):
        inp = keras.Input(shape=(6, 6, 2), name="in0")
        c = KL.Conv2D(3, 3, padding="valid", activation="relu", name="c")(inp)
        f = KL.Flatten(name="f")(c)
        out = KL.Dense(4, name="d")(f)
        m = keras.Model(inp, out)
        x = np.random.RandomState(6).randn(2, 6, 6, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_sequential_routes_through_entry_point(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(4,)),
            KL.Dense(2, name="d"),
        ])
        x = np.random.RandomState(7).randn(2, 4).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestBertImport:
    """Parity vs a real HuggingFace BertModel (randomly initialized tiny
    config — no downloads), through torch .bin and .safetensors paths."""

    @pytest.fixture(scope="class")
    def hf_bert(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = transformers.BertConfig(
            vocab_size=99, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=40, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        torch.manual_seed(0)
        model = transformers.BertModel(cfg).eval()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 99, (2, 10)).astype(np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).last_hidden_state.numpy()
        return model, ids, want

    def test_torch_bin_roundtrip_parity(self, hf_bert, tmp_path):
        import torch
        from deeplearning4j_tpu.modelimport.bert import importBertModelAndWeights
        from deeplearning4j_tpu.models import transformer as tfm
        model, ids, want = hf_bert
        p = str(tmp_path / "bert.bin")
        torch.save(model.state_dict(), p)
        cfg, params = importBertModelAndWeights(p, n_heads=4)
        assert cfg.n_layers == 2 and cfg.d_model == 32 and cfg.vocab_size == 99
        got = np.asarray(tfm.encode(params, ids.astype(np.int32), cfg))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_safetensors_parity(self, hf_bert, tmp_path):
        st = pytest.importorskip("safetensors.torch")
        from deeplearning4j_tpu.modelimport.bert import importBertModelAndWeights
        from deeplearning4j_tpu.models import transformer as tfm
        model, ids, want = hf_bert
        p = str(tmp_path / "bert.safetensors")
        st.save_file(model.state_dict(), p)
        cfg, params = importBertModelAndWeights(p, n_heads=4)
        got = np.asarray(tfm.encode(params, ids.astype(np.int32), cfg))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_attention_mask_parity(self, hf_bert, tmp_path):
        import torch
        from deeplearning4j_tpu.modelimport.bert import importBertModelAndWeights
        from deeplearning4j_tpu.models import transformer as tfm
        model, ids, _ = hf_bert
        mask = np.ones((2, 10), np.float32)
        mask[:, 7:] = 0.0
        with torch.no_grad():
            want = model(torch.from_numpy(ids),
                         attention_mask=torch.from_numpy(mask)
                         ).last_hidden_state.numpy()
        p = str(tmp_path / "bert.bin")
        torch.save(model.state_dict(), p)
        cfg, params = importBertModelAndWeights(p, n_heads=4)
        got = np.asarray(tfm.encode(params, ids.astype(np.int32), cfg,
                                    attn_mask=mask))
        # masked-out positions attend garbage in both frameworks; compare
        # the valid positions only
        np.testing.assert_allclose(got[:, :7], want[:, :7], rtol=1e-4, atol=1e-5)

    def test_tf_convention_checkpoint_parity(self, hf_bert, tmp_path):
        """A google-research-style TF-named checkpoint ([in,out] kernels,
        '/' separators, gamma/beta) imports to the same outputs as HF
        (advisor r2 medium: square q/k/v kernels were shape-guessed)."""
        import torch
        from deeplearning4j_tpu.modelimport.bert import importBertModelAndWeights
        from deeplearning4j_tpu.models import transformer as tfm
        model, ids, want = hf_bert
        tf_state = {}
        for k, v in model.state_dict().items():
            arr = v.detach().numpy()
            tk = "bert/" + k.replace("encoder.layer.", "encoder/layer_")
            tk = tk.replace(".", "/")
            if tk.endswith("/weight"):
                if "_embeddings" in tk:
                    tk = tk[:-len("/weight")]  # TF names tables bare
                elif arr.ndim == 2:
                    tk = tk[:-len("/weight")] + "/kernel"
                    arr = arr.T  # TF stores dense kernels [in, out]
                elif "LayerNorm" in tk:
                    tk = tk[:-len("/weight")] + "/gamma"
            if tk.endswith("/bias") and "LayerNorm" in tk:
                tk = tk[:-len("/bias")] + "/beta"
            tf_state[tk] = torch.from_numpy(arr.copy())
        p = str(tmp_path / "bert_tf.bin")
        torch.save(tf_state, p)
        cfg, params = importBertModelAndWeights(p, n_heads=4)
        got = np.asarray(tfm.encode(params, ids.astype(np.int32), cfg))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_imported_bert_trains(self, hf_bert, tmp_path):
        import torch
        from deeplearning4j_tpu.modelimport.bert import importBertModelAndWeights
        from deeplearning4j_tpu.models import transformer as tfm
        from deeplearning4j_tpu.train import updaters
        import jax.numpy as jnp
        model, ids, _ = hf_bert
        p = str(tmp_path / "bert.bin")
        torch.save(model.state_dict(), p)
        cfg, params = importBertModelAndWeights(p, n_heads=4)
        updater = updaters.Adam(1e-3)
        opt = tfm.init_opt_state(params, updater)
        step = tfm.make_train_step(cfg, updater, mesh=None)
        tok = jnp.asarray(ids, jnp.int32)
        tgt = jnp.asarray(np.roll(ids, 1, axis=1), jnp.int32)
        m = jnp.ones(ids.shape, jnp.float32)
        losses = []
        t_dev = jnp.asarray(0, jnp.int32)
        for i in range(8):
            params, opt, t_dev, loss = step(params, opt, t_dev, tok, tgt, m)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestR4Mappers:
    """r4 mapper breadth (VERDICT r3 #8): Conv3D, 3-D pooling, 1-D spatial
    ops, Masking, noise layers, TimeDistributed, MultiHeadAttention —
    each against live-Keras goldens."""

    def test_conv3d_pool3d_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 4, 4, 2)),
            KL.Conv3D(3, 2, activation="relu"),
            KL.MaxPooling3D(1),
            KL.AveragePooling3D(1),
            KL.Flatten(),
            KL.Dense(5, activation="softmax"),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(0).rand(3, 4, 4, 4, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        got = np.asarray(net.output(np.transpose(x, (0, 4, 1, 2, 3))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_1d_spatial_ops_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input((8, 3)),
            KL.ZeroPadding1D(1),
            KL.Conv1D(4, 3, activation="relu"),
            KL.UpSampling1D(2),
            KL.Cropping1D((1, 2)),
            KL.GlobalAveragePooling1D(),
            KL.Dense(2),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(1).rand(2, 8, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_masking_and_time_distributed_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 3)),
            KL.Masking(mask_value=0.0),
            KL.TimeDistributed(KL.Dense(4, activation="tanh")),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(2).rand(2, 6, 3).astype(np.float32)
        x[:, 4:] = 0.0   # masked tail
        want = m.predict(x, verbose=0)
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(np.transpose(got, (0, 2, 1))[:, :4],
                                   want[:, :4], rtol=1e-4, atol=1e-5)

    def test_noise_layers_inference_identity(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5,)),
            KL.GaussianNoise(0.5),
            KL.GaussianDropout(0.3),
            KL.AlphaDropout(0.2),
            KL.Dense(3),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(3).rand(4, 5).astype(np.float32)
        want = m.predict(x, verbose=0)   # noise is inference-inactive
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_relu_softmax_thresholded_layers(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6,)),
            KL.Dense(8),
            KL.ReLU(),
            KL.Dense(4),
            KL.Softmax(),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(4).randn(3, 6).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   m.predict(x, verbose=0),
                                   rtol=1e-4, atol=1e-5)

    def test_multi_head_attention_parity(self, tmp_path):
        inp = keras.Input((5, 8))
        y = KL.MultiHeadAttention(num_heads=2, key_dim=4, name="mha")(inp, inp)
        y = KL.GlobalAveragePooling1D()(y)
        out = KL.Dense(3, activation="softmax")(y)
        m = keras.Model(inp, out)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(5).rand(2, 5, 8).astype(np.float32)
        want = m.predict(x, verbose=0)
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_functional_add_concat_multibranch(self, tmp_path):
        inp = keras.Input((4, 4, 3))
        a = KL.Conv2D(4, 3, padding="same", activation="relu")(inp)
        b = KL.Conv2D(4, 1, activation="relu")(inp)
        s = KL.Add()([a, b])
        c = KL.Concatenate()([s, a])
        y = KL.GlobalAveragePooling2D()(c)
        out = KL.Dense(2, activation="softmax")(y)
        m = keras.Model(inp, out)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        x = np.random.RandomState(6).rand(2, 4, 4, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestR5Mappers:
    """Round-5 mapper additions (VERDICT r4 #8): Conv2DTranspose, the 3D
    pad/crop/upsample family, spatial dropouts, global 3D pools,
    ActivityRegularization, and the Dot merge vertex."""

    def test_conv2d_transpose_parity(self, tmp_path):
        for pad, strides in (("same", 2), ("valid", 1), ("valid", 2)):
            m = keras.Sequential([
                keras.Input(shape=(5, 5, 3)),
                KL.Conv2DTranspose(4, 3, strides=strides, padding=pad,
                                   activation="relu", name=f"dc_{pad}{strides}"),
            ])
            x = np.random.RandomState(7).randn(2, 5, 5, 3).astype(np.float32)
            want = m.predict(x, verbose=0)
            net = importKerasSequentialModelAndWeights(_save(tmp_path, m,
                                                            f"{pad}{strides}.h5"))
            got = np.asarray(net.output(_nchw(x)))
            np.testing.assert_allclose(got, _nchw(want), rtol=1e-4,
                                       atol=1e-5, err_msg=f"{pad}/{strides}")

    def test_3d_pad_crop_upsample_globalpool_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(4, 4, 4, 2)),
            KL.ZeroPadding3D(1, name="zp"),
            KL.Conv3D(3, 3, activation="relu", name="c3"),
            KL.UpSampling3D(2, name="up"),
            KL.Cropping3D(1, name="cr"),
            KL.GlobalAveragePooling3D(name="gap"),
        ])
        x = np.random.RandomState(8).randn(2, 4, 4, 4, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 4, 1, 2, 3))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_spatial_dropout_activity_reg_inference_identity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6, 3)),
            KL.SpatialDropout1D(0.4, name="sd1"),
            KL.ActivityRegularization(l2=0.01, name="ar"),
            KL.GlobalAveragePooling1D(name="gp"),
        ])
        x = np.random.RandomState(9).randn(2, 6, 3).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 2, 1))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_functional_dot_merge_parity(self, tmp_path):
        inp = keras.Input(shape=(6,), name="in0")
        a = KL.Dense(4, activation="tanh", name="da")(inp)
        b = KL.Dense(4, activation="tanh", name="db")(inp)
        dot = KL.Dot(axes=1, normalize=True, name="dot")([a, b])
        out = KL.Dense(2, activation="softmax", name="out")(dot)
        m = keras.Model(inp, out)
        x = np.random.RandomState(10).randn(3, 6).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_group_and_unit_normalization_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6, 6, 8)),
            KL.GroupNormalization(groups=4, name="gn"),
            KL.Conv2D(4, 3, name="c"),
            KL.GlobalAveragePooling2D(name="gp"),
            KL.UnitNormalization(name="un"),
        ])
        x = np.random.RandomState(11).randn(2, 6, 6, 8).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_group_norm_instance_and_weightfree_variants(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(5, 5, 6)),
            KL.GroupNormalization(groups=-1, name="inst"),     # instance norm
            KL.GroupNormalization(groups=3, center=False, scale=False,
                                  name="nw"),
            KL.GlobalAveragePooling2D(name="gp"),
        ])
        x = np.random.RandomState(12).randn(2, 5, 5, 6).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(_nchw(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv_lstm2d_parity(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(4, 8, 8, 2)),      # [T, H, W, C]
            KL.ConvLSTM2D(3, 3, padding="same", return_sequences=False,
                          name="cl"),
            KL.GlobalAveragePooling2D(name="gp"),
        ])
        x = np.random.RandomState(13).randn(2, 4, 8, 8, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        # keras [N, T, H, W, C] -> ours [N, C, T, H, W]
        got = np.asarray(net.output(np.transpose(x, (0, 4, 1, 2, 3))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv_lstm2d_sequences_valid_padding(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(3, 6, 6, 2)),
            KL.ConvLSTM2D(2, 3, padding="valid", return_sequences=True,
                          name="cl"),
            KL.GlobalAveragePooling3D(name="gp"),
        ])
        x = np.random.RandomState(14).randn(2, 3, 6, 6, 2).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        got = np.asarray(net.output(np.transpose(x, (0, 4, 1, 2, 3))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestKerasImportReport:
    """ISSUE 18: the Keras importer attaches an import_report (the
    DL4J-W16x/E16x import lints) to the returned network."""

    def test_clean_model_attaches_empty_report(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(6,)),
            KL.Dense(4, activation="relu", name="d1"),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        assert hasattr(net, "import_report")
        assert not net.import_report.diagnostics, \
            net.import_report.format()

    def test_w161_on_dynamic_sequence_length(self, tmp_path):
        m = keras.Sequential([
            keras.Input(shape=(None, 6)),      # free time dim
            KL.LSTM(4, name="l1"),
        ])
        net = importKerasSequentialModelAndWeights(_save(tmp_path, m))
        codes = [d.code for d in net.import_report]
        assert "DL4J-W161" in codes, net.import_report.format()

    def test_functional_import_attaches_report(self, tmp_path):
        inp = keras.Input(shape=(6,))
        out = KL.Dense(3, name="d")(inp)
        m = keras.Model(inp, out)
        net = importKerasModelAndWeights(_save(tmp_path, m))
        assert hasattr(net, "import_report")
