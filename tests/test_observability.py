"""Observability subsystem tests: StatsListener -> StatsStorage -> UIServer,
profiler tracing, NaN/Inf panic debug modes, and the unified profiler/
subsystem (span tracer -> Chrome trace, metrics registry -> Prometheus).

Reference parity: SURVEY.md §5 "Metrics/logging" (StatsListener/
InMemoryStatsStorage/FileStatsStorage/UIServer of deeplearning4j-ui-parent),
"Tracing/profiling" (ProfilingListener -> Chrome trace), and OpExecutioner
ProfilingMode OFF/BASIC/NAN_PANIC/INF_PANIC.
"""

import glob
import json
import os
import re
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import profiler
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.profiler import (MetricsRegistry, ProfilingMode,
                                         SpanTracer, trace_span)
from deeplearning4j_tpu.train.listeners import (MetricsListener,
                                                PerformanceListener,
                                                ProfilingListener,
                                                StatsListener)
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   StatsStorageRouter, UIServer)
from deeplearning4j_tpu.utils.environment import (Environment,
                                                  NumericsPanicError)


@pytest.fixture
def clean_profiler():
    """Tracing on against a clean buffer; everything off afterwards."""
    profiler.get_tracer().clear()
    profiler.enable_tracing()
    yield
    profiler.disable_tracing()
    profiler.set_profiling_mode(None)
    profiler.get_tracer().clear()


def _tiny_net_and_data(seed=0):
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(seed)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


class TestStatsStorage:
    def test_in_memory_sessions_and_updates(self):
        st = InMemoryStatsStorage()
        events = []
        st.registerStatsStorageListener(lambda e: events.append(e.kind))
        st.putStaticInfo({"session_id": "a", "model_class": "X"})
        st.putUpdate({"session_id": "a", "iteration": 1, "score": 1.0})
        st.putUpdate({"session_id": "a", "iteration": 2, "score": 0.5})
        assert st.listSessionIDs() == ["a"]
        assert st.getStaticInfo("a")["model_class"] == "X"
        assert [u["iteration"] for u in st.getAllUpdates("a")] == [1, 2]
        assert st.getLatestUpdate("a")["score"] == 0.5
        assert st.getAllUpdatesAfter("a", 1)[0]["iteration"] == 2
        assert "new_session" in events and "update" in events

    def test_file_storage_reload(self, tmp_path):
        p = str(tmp_path / "stats.jsonl")
        st = FileStatsStorage(p)
        st.putStaticInfo({"session_id": "s1", "n_parameters": 7})
        st.putUpdate({"session_id": "s1", "iteration": 3, "score": 0.1})
        st.close()
        st2 = FileStatsStorage(p)   # reload from disk
        assert st2.listSessionIDs() == ["s1"]
        assert st2.getStaticInfo("s1")["n_parameters"] == 7
        assert st2.getLatestUpdate("s1")["iteration"] == 3
        st2.close()

    def test_router_fans_out(self, tmp_path):
        a, b = InMemoryStatsStorage(), InMemoryStatsStorage()
        r = StatsStorageRouter(a, b)
        r.putUpdate({"session_id": "x", "iteration": 1})
        assert a.getAllUpdates("x") and b.getAllUpdates("x")


class TestStatsListener:
    def test_records_per_layer_stats_from_fit(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        lst = StatsListener(st, frequency=1, session_id="t1")
        net.setListeners(lst)
        for _ in range(3):
            net.fit(ds)
        ups = st.getAllUpdates("t1")
        assert len(ups) == 3
        u = ups[-1]
        assert np.isfinite(u["score"])
        assert u["minibatch_size"] == 8
        # per-layer records carry param/update stats incl. the ratio chart's
        # numerator/denominator
        assert u["layers"], "no layer stats captured"
        some = next(iter(u["layers"].values()))
        for k in ("param_mean", "param_std", "param_norm", "update_norm",
                  "update_ratio"):
            assert np.isfinite(some[k])
        # training actually moved the weights
        assert any(rec["update_norm"] > 0 for rec in u["layers"].values())
        static = st.getStaticInfo("t1")
        assert static["n_parameters"] > 0
        assert static["model_class"] == "MultiLayerNetwork"

    def test_frequency_sampling(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=2, session_id="t2"))
        for _ in range(4):
            net.fit(ds)
        iters = [u["iteration"] for u in st.getAllUpdates("t2")]
        assert iters == [2, 4]

    def test_histograms(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="t3",
                                       with_histograms=True, hist_bins=10))
        net.fit(ds)
        u = st.getLatestUpdate("t3")
        some = next(iter(u["layers"].values()))
        assert len(some["hist_counts"]) == 10
        assert len(some["hist_range"]) == 2

    def test_works_on_computation_graph(self):
        g = zoo.SqueezeNet(num_classes=3, input_shape=(3, 32, 32)).init()
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 32, 32).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 2)]
        st = InMemoryStatsStorage()
        g.setListeners(StatsListener(st, frequency=1, session_id="g1"))
        g.fit(DataSet(x, y))
        u = st.getLatestUpdate("g1")
        assert u is not None and u["layers"]


class TestUIServer:
    def test_dashboard_endpoints(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="ui1"))
        net.fit(ds)
        net.fit(ds)
        server = UIServer(port=0).attach(st)
        try:
            base = server.url
            sessions = json.load(urllib.request.urlopen(base + "api/sessions"))
            assert "ui1" in sessions
            ov = json.load(urllib.request.urlopen(
                base + "api/overview?session=ui1"))
            assert len(ov["iterations"]) == 2
            assert all(np.isfinite(s) for s in ov["scores"])
            mo = json.load(urllib.request.urlopen(
                base + "api/model?session=ui1"))
            assert mo["latest"] and mo["ratio_series"]
            page = urllib.request.urlopen(base).read().decode()
            assert "training UI" in page and "Score vs iteration" in page
        finally:
            server.stop()

    def test_histograms_endpoint(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="ui2",
                                       with_histograms=True, hist_bins=12))
        net.fit(ds)
        server = UIServer(port=0).attach(st)
        try:
            h = json.load(urllib.request.urlopen(
                server.url + "api/histograms?session=ui2"))
            assert h["iteration"] is not None and h["hists"]
            first = next(iter(h["hists"].values()))
            assert len(first["counts"]) == 12
            assert len(first["range"]) == 2
            # page renders the histogram card
            page = urllib.request.urlopen(server.url).read().decode()
            assert "Parameter histograms" in page
        finally:
            server.stop()


class TestProfiling:
    def test_profiling_listener_writes_trace(self, tmp_path):
        net, ds = _tiny_net_and_data()
        d = str(tmp_path / "trace")
        net.setListeners(ProfilingListener(d, start_iter=1, n_iters=2))
        for _ in range(4):
            net.fit(ds)
        files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
        assert any(("trace" in f or f.endswith(".pb") or ".xplane" in f)
                   and os.path.isfile(f) for f in files), files


class TestNumericsPanic:
    def test_nan_panic_raises(self):
        net, ds = _tiny_net_and_data()
        bad = DataSet(np.full((8, 256), np.nan, np.float32), ds.labels)
        Environment.reset()
        os.environ["DL4J_TPU_NAN_PANIC"] = "1"
        try:
            with pytest.raises(NumericsPanicError, match="NAN_PANIC"):
                net.fit(bad)
        finally:
            os.environ.pop("DL4J_TPU_NAN_PANIC", None)
            Environment.reset()

    def test_no_panic_when_disabled(self):
        net, ds = _tiny_net_and_data()
        bad = DataSet(np.full((8, 256), np.nan, np.float32), ds.labels)
        Environment.reset()
        net.fit(bad)   # silently produces NaN loss, as configured
        assert np.isnan(net.score())

    def test_unified_mode_panics_fit_loop(self):
        """set_profiling_mode(NAN_PANIC) == the env-var knob (unified)."""
        net, ds = _tiny_net_and_data()
        bad = DataSet(np.full((8, 256), np.nan, np.float32), ds.labels)
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        try:
            with pytest.raises(NumericsPanicError, match="NAN_PANIC"):
                net.fit(bad)
        finally:
            profiler.set_profiling_mode(None)


# ---------------------------------------------------------------------------
# profiler/ subsystem: span tracer
# ---------------------------------------------------------------------------

class TestSpanTracer:
    def test_disabled_records_nothing(self):
        t = profiler.get_tracer()
        t.clear()
        assert not profiler.tracing_enabled()
        with trace_span("should_not_appear"):
            pass
        assert len(t) == 0

    def test_nesting(self, clean_profiler):
        with trace_span("outer", layer="conv"):
            with trace_span("inner"):
                pass
        evs = profiler.get_tracer().events()
        outer = next(e for e in evs if e["name"] == "outer")
        inner = next(e for e in evs if e["name"] == "inner")
        # child's interval is contained in the parent's
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
        assert inner["args"]["depth"] == 1
        assert outer["args"]["layer"] == "conv"

    def test_decorator(self, clean_profiler):
        @trace_span("decorated_fn")
        def f(a, b):
            return a + b
        assert f(2, 3) == 5
        assert any(e["name"] == "decorated_fn"
                   for e in profiler.get_tracer().events())

    def test_thread_safety(self, clean_profiler):
        t = profiler.get_tracer()
        barrier = threading.Barrier(8)   # overlap all workers so OS thread
                                         # ids can't be reused between them

        def worker(i):
            barrier.wait()
            for _ in range(50):
                with trace_span(f"w{i}"):
                    pass
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        evs = t.events()
        assert len(evs) == 8 * 50
        assert len({e["tid"] for e in evs}) == 8   # spans keep their thread

    def test_ring_buffer_retention(self):
        t = SpanTracer(capacity=10)
        profiler.enable_tracing()
        try:
            for i in range(25):
                with trace_span(f"s{i}", tracer=t):
                    pass
        finally:
            profiler.disable_tracing()
        evs = t.events()
        assert len(evs) == 10
        assert evs[0]["name"] == "s15" and evs[-1]["name"] == "s24"

    def test_chrome_trace_json_validity(self, clean_profiler):
        with trace_span("a"):
            with trace_span("b"):
                pass
        doc = json.loads(profiler.get_tracer().export_chrome_trace())
        assert "traceEvents" in doc
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2
        for ev in xs:
            for key in ("ph", "ts", "name", "dur", "pid", "tid"):
                assert key in ev
            assert ev["dur"] >= 0
        # thread-name metadata present for perfetto row labels
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in doc["traceEvents"])

    def test_export_to_file(self, clean_profiler, tmp_path):
        with trace_span("file_span"):
            pass
        p = str(tmp_path / "trace.json")
        profiler.get_tracer().export_chrome_trace(p)
        with open(p) as f:
            doc = json.load(f)
        assert any(e["name"] == "file_span" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# profiler/ subsystem: metrics registry
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE\.\+\-]+$|^\S+ \+Inf$')


def _assert_valid_exposition(text):
    """Minimal Prometheus text-format 0.0.4 validation."""
    assert text.endswith("\n")
    for line in text.strip().split("\n"):
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


class TestMetricsRegistry:
    def test_counter_semantics(self):
        r = MetricsRegistry()
        c = r.counter("c_total", "help me")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_semantics(self):
        r = MetricsRegistry()
        g = r.gauge("g", "")
        g.set(10)
        g.inc()
        g.dec(0.5)
        assert g.value == 10.5

    def test_histogram_semantics(self):
        r = MetricsRegistry()
        h = r.histogram("h_seconds", "", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(55.55)
        text = r.exposition()
        assert 'h_seconds_bucket{le="0.1"} 1' in text
        assert 'h_seconds_bucket{le="1"} 2' in text
        assert 'h_seconds_bucket{le="10"} 3' in text      # cumulative
        assert 'h_seconds_bucket{le="+Inf"} 4' in text
        assert "h_seconds_count 4" in text

    def test_labels(self):
        r = MetricsRegistry()
        c = r.counter("ops_total", "", labelnames=("op", "status"))
        c.labels(op="add", status="ok").inc(3)
        c.labels("mul", "err").inc()
        with pytest.raises(ValueError):
            c.inc()            # labelled family: direct ops are an error
        with pytest.raises(ValueError):
            c.labels(op="add")  # wrong arity
        text = r.exposition()
        assert 'ops_total{op="add",status="ok"} 3' in text
        assert 'ops_total{op="mul",status="err"} 1' in text

    def test_get_or_create_and_type_conflict(self):
        r = MetricsRegistry()
        a = r.counter("same", "")
        b = r.counter("same", "")
        assert a is b
        with pytest.raises(ValueError):
            r.gauge("same", "")

    def test_exposition_parses(self):
        r = MetricsRegistry()
        r.counter("c_total", "a counter").inc()
        r.gauge("g", 'with "quotes"').set(-1.5)
        h = r.histogram("h", "", labelnames=("op",), buckets=(1,))
        h.labels(op='we"ird').observe(2)
        _assert_valid_exposition(r.exposition())

    def test_thread_safety(self):
        r = MetricsRegistry()
        c = r.counter("n_total", "")

        def worker():
            for _ in range(1000):
                c.inc()
        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 8000


# ---------------------------------------------------------------------------
# ProfilingMode + op-dispatch instrumentation
# ---------------------------------------------------------------------------

class TestOpDispatchProfiling:
    def test_mode_derived_from_environment(self):
        Environment.reset()
        os.environ["DL4J_TPU_NAN_PANIC"] = "1"
        try:
            Environment.reset()
            assert profiler.get_profiling_mode() is ProfilingMode.NAN_PANIC
        finally:
            os.environ.pop("DL4J_TPU_NAN_PANIC", None)
            Environment.reset()
        assert profiler.get_profiling_mode() is ProfilingMode.OFF

    def test_basic_mode_counts_dispatches(self):
        from deeplearning4j_tpu.ops import registry as R
        reg = profiler.get_registry()
        profiler.set_profiling_mode(ProfilingMode.BASIC)
        try:
            c = reg.get("dl4j_op_dispatch_total")
            before = c.labels(op="abs").value if c is not None else 0
            R.exec_op("abs", np.array([-1.0, 2.0]))
            R.exec_op("abs", np.array([3.0]))
            after = reg.get("dl4j_op_dispatch_total").labels(op="abs").value
            assert after - before == 2
            lat = reg.get("dl4j_op_dispatch_seconds")
            assert lat is not None
        finally:
            profiler.set_profiling_mode(None)

    def test_op_nan_panic(self):
        from deeplearning4j_tpu.ops import registry as R
        profiler.set_profiling_mode(ProfilingMode.NAN_PANIC)
        try:
            with pytest.raises(NumericsPanicError, match="op 'log'"):
                R.exec_op("log", np.array([-1.0], np.float32))
        finally:
            profiler.set_profiling_mode(None)

    def test_op_inf_panic(self):
        from deeplearning4j_tpu.ops import registry as R
        profiler.set_profiling_mode(ProfilingMode.INF_PANIC)
        try:
            with pytest.raises(NumericsPanicError, match="op 'reciprocal'"):
                R.exec_op("reciprocal", np.array([0.0], np.float32))
        finally:
            profiler.set_profiling_mode(None)

    def test_off_mode_is_uninstrumented(self):
        from deeplearning4j_tpu.ops import registry as R
        assert profiler.get_profiling_mode() is ProfilingMode.OFF
        t = profiler.get_tracer()
        t.clear()
        out = R.exec_op("neg", np.array([1.0]))
        assert float(out[0]) == -1.0
        assert len(t) == 0

    def test_op_spans_when_tracing(self, clean_profiler):
        from deeplearning4j_tpu.ops import registry as R
        R.exec_op("square", np.array([2.0]))
        assert any(e["name"] == "op:square"
                   for e in profiler.get_tracer().events())


# ---------------------------------------------------------------------------
# listener-bus -> registry bridges
# ---------------------------------------------------------------------------

class TestMetricsListener:
    def test_bridges_fit_into_registry(self):
        net, ds = _tiny_net_and_data()
        reg = MetricsRegistry()
        net.setListeners(MetricsListener(registry=reg))
        net.fit(ds, epochs=2)
        assert reg.get("dl4j_train_iterations_total").value == 2
        assert reg.get("dl4j_train_epochs_total").value == 2
        assert np.isfinite(reg.get("dl4j_train_score").value)
        assert reg.get("dl4j_train_iteration_seconds").count == 2
        _assert_valid_exposition(reg.exposition())

    def test_performance_listener_emits_throughput(self):
        net, ds = _tiny_net_and_data()
        net.setListeners(PerformanceListener(frequency=1, out=lambda m: None))
        for _ in range(3):
            net.fit(ds)
        g = profiler.get_registry().get("dl4j_throughput_samples_per_sec")
        assert g is not None and g.value > 0
        gb = profiler.get_registry().get("dl4j_throughput_batches_per_sec")
        assert gb is not None and gb.value > 0


# ---------------------------------------------------------------------------
# UIServer profiler endpoints
# ---------------------------------------------------------------------------

class TestProfilerEndpoints:
    def test_metrics_endpoint(self):
        from deeplearning4j_tpu.ops import registry as R
        profiler.set_profiling_mode(ProfilingMode.BASIC)
        try:
            R.exec_op("exp", np.array([1.0]))
            net, ds = _tiny_net_and_data()
            net.fit(ds)
        finally:
            profiler.set_profiling_mode(None)
        server = UIServer(port=0).attach(InMemoryStatsStorage())
        try:
            resp = urllib.request.urlopen(server.url + "metrics")
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode()
        finally:
            server.stop()
        assert ctype.startswith("text/plain")
        _assert_valid_exposition(text)
        # op-dispatch counters and compile-cache hit/miss are exposed
        assert 'dl4j_op_dispatch_total{op="exp"}' in text
        assert "dl4j_native_compile_cache_hits_total" in text
        assert "dl4j_native_compile_cache_misses_total" in text
        assert "dl4j_train_step_seconds_count" in text
        assert "dl4j_train_data_wait_seconds_count" in text

    def test_trace_endpoint_nested_fit_spans(self, clean_profiler):
        net, ds = _tiny_net_and_data()
        net.fit(ds, epochs=2)
        server = UIServer(port=0).attach(InMemoryStatsStorage())
        try:
            doc = json.load(urllib.request.urlopen(server.url + "trace"))
        finally:
            server.stop()
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        for ev in evs:
            for key in ("ph", "ts", "name"):
                assert key in ev
        names = {e["name"] for e in evs}
        assert {"train:epoch", "train:step", "train:data_wait"} <= names
        # real nesting from a real fit() run: step inside its epoch span
        epochs = [e for e in evs if e["name"] == "train:epoch"]
        steps = [e for e in evs if e["name"] == "train:step"]
        assert len(epochs) == 2 and len(steps) == 2
        contained = sum(
            1 for s in steps for ep in epochs
            if ep["ts"] <= s["ts"]
            and s["ts"] + s["dur"] <= ep["ts"] + ep["dur"] + 1e-3)
        assert contained == 2
