"""Observability subsystem tests: StatsListener -> StatsStorage -> UIServer,
profiler tracing, NaN/Inf panic debug modes.

Reference parity: SURVEY.md §5 "Metrics/logging" (StatsListener/
InMemoryStatsStorage/FileStatsStorage/UIServer of deeplearning4j-ui-parent),
"Tracing/profiling" (ProfilingListener -> Chrome trace), and OpExecutioner
ProfilingMode NAN_PANIC/INF_PANIC.
"""

import glob
import json
import os
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.train.listeners import (ProfilingListener,
                                                StatsListener)
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   StatsStorageRouter, UIServer)
from deeplearning4j_tpu.utils.environment import (Environment,
                                                  NumericsPanicError)


def _tiny_net_and_data(seed=0):
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(seed)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


class TestStatsStorage:
    def test_in_memory_sessions_and_updates(self):
        st = InMemoryStatsStorage()
        events = []
        st.registerStatsStorageListener(lambda e: events.append(e.kind))
        st.putStaticInfo({"session_id": "a", "model_class": "X"})
        st.putUpdate({"session_id": "a", "iteration": 1, "score": 1.0})
        st.putUpdate({"session_id": "a", "iteration": 2, "score": 0.5})
        assert st.listSessionIDs() == ["a"]
        assert st.getStaticInfo("a")["model_class"] == "X"
        assert [u["iteration"] for u in st.getAllUpdates("a")] == [1, 2]
        assert st.getLatestUpdate("a")["score"] == 0.5
        assert st.getAllUpdatesAfter("a", 1)[0]["iteration"] == 2
        assert "new_session" in events and "update" in events

    def test_file_storage_reload(self, tmp_path):
        p = str(tmp_path / "stats.jsonl")
        st = FileStatsStorage(p)
        st.putStaticInfo({"session_id": "s1", "n_parameters": 7})
        st.putUpdate({"session_id": "s1", "iteration": 3, "score": 0.1})
        st.close()
        st2 = FileStatsStorage(p)   # reload from disk
        assert st2.listSessionIDs() == ["s1"]
        assert st2.getStaticInfo("s1")["n_parameters"] == 7
        assert st2.getLatestUpdate("s1")["iteration"] == 3
        st2.close()

    def test_router_fans_out(self, tmp_path):
        a, b = InMemoryStatsStorage(), InMemoryStatsStorage()
        r = StatsStorageRouter(a, b)
        r.putUpdate({"session_id": "x", "iteration": 1})
        assert a.getAllUpdates("x") and b.getAllUpdates("x")


class TestStatsListener:
    def test_records_per_layer_stats_from_fit(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        lst = StatsListener(st, frequency=1, session_id="t1")
        net.setListeners(lst)
        for _ in range(3):
            net.fit(ds)
        ups = st.getAllUpdates("t1")
        assert len(ups) == 3
        u = ups[-1]
        assert np.isfinite(u["score"])
        assert u["minibatch_size"] == 8
        # per-layer records carry param/update stats incl. the ratio chart's
        # numerator/denominator
        assert u["layers"], "no layer stats captured"
        some = next(iter(u["layers"].values()))
        for k in ("param_mean", "param_std", "param_norm", "update_norm",
                  "update_ratio"):
            assert np.isfinite(some[k])
        # training actually moved the weights
        assert any(rec["update_norm"] > 0 for rec in u["layers"].values())
        static = st.getStaticInfo("t1")
        assert static["n_parameters"] > 0
        assert static["model_class"] == "MultiLayerNetwork"

    def test_frequency_sampling(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=2, session_id="t2"))
        for _ in range(4):
            net.fit(ds)
        iters = [u["iteration"] for u in st.getAllUpdates("t2")]
        assert iters == [2, 4]

    def test_histograms(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="t3",
                                       with_histograms=True, hist_bins=10))
        net.fit(ds)
        u = st.getLatestUpdate("t3")
        some = next(iter(u["layers"].values()))
        assert len(some["hist_counts"]) == 10
        assert len(some["hist_range"]) == 2

    def test_works_on_computation_graph(self):
        g = zoo.SqueezeNet(num_classes=3, input_shape=(3, 32, 32)).init()
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 32, 32).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 2)]
        st = InMemoryStatsStorage()
        g.setListeners(StatsListener(st, frequency=1, session_id="g1"))
        g.fit(DataSet(x, y))
        u = st.getLatestUpdate("g1")
        assert u is not None and u["layers"]


class TestUIServer:
    def test_dashboard_endpoints(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="ui1"))
        net.fit(ds)
        net.fit(ds)
        server = UIServer(port=0).attach(st)
        try:
            base = server.url
            sessions = json.load(urllib.request.urlopen(base + "api/sessions"))
            assert "ui1" in sessions
            ov = json.load(urllib.request.urlopen(
                base + "api/overview?session=ui1"))
            assert len(ov["iterations"]) == 2
            assert all(np.isfinite(s) for s in ov["scores"])
            mo = json.load(urllib.request.urlopen(
                base + "api/model?session=ui1"))
            assert mo["latest"] and mo["ratio_series"]
            page = urllib.request.urlopen(base).read().decode()
            assert "training UI" in page and "Score vs iteration" in page
        finally:
            server.stop()

    def test_histograms_endpoint(self):
        net, ds = _tiny_net_and_data()
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, frequency=1, session_id="ui2",
                                       with_histograms=True, hist_bins=12))
        net.fit(ds)
        server = UIServer(port=0).attach(st)
        try:
            h = json.load(urllib.request.urlopen(
                server.url + "api/histograms?session=ui2"))
            assert h["iteration"] is not None and h["hists"]
            first = next(iter(h["hists"].values()))
            assert len(first["counts"]) == 12
            assert len(first["range"]) == 2
            # page renders the histogram card
            page = urllib.request.urlopen(server.url).read().decode()
            assert "Parameter histograms" in page
        finally:
            server.stop()


class TestProfiling:
    def test_profiling_listener_writes_trace(self, tmp_path):
        net, ds = _tiny_net_and_data()
        d = str(tmp_path / "trace")
        net.setListeners(ProfilingListener(d, start_iter=1, n_iters=2))
        for _ in range(4):
            net.fit(ds)
        files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
        assert any(("trace" in f or f.endswith(".pb") or ".xplane" in f)
                   and os.path.isfile(f) for f in files), files


class TestNumericsPanic:
    def test_nan_panic_raises(self):
        net, ds = _tiny_net_and_data()
        bad = DataSet(np.full((8, 256), np.nan, np.float32), ds.labels)
        Environment.reset()
        os.environ["DL4J_TPU_NAN_PANIC"] = "1"
        try:
            with pytest.raises(NumericsPanicError, match="NAN_PANIC"):
                net.fit(bad)
        finally:
            os.environ.pop("DL4J_TPU_NAN_PANIC", None)
            Environment.reset()

    def test_no_panic_when_disabled(self):
        net, ds = _tiny_net_and_data()
        bad = DataSet(np.full((8, 256), np.nan, np.float32), ds.labels)
        Environment.reset()
        net.fit(bad)   # silently produces NaN loss, as configured
        assert np.isnan(net.score())
