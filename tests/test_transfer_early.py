"""Transfer learning, early stopping, streaming RNN (rnnTimeStep/tBPTT)."""

import numpy as np

from deeplearning4j_tpu.data import DataSet, IrisDataSetIterator, ListDataSetIterator, NormalizerStandardize
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (DenseLayer, LSTM, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                            TransferLearning,
                                            TransferLearningHelper)
from deeplearning4j_tpu.train import updaters
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)


def iris_data():
    it = IrisDataSetIterator(150)
    ds = it.next()
    ds.shuffle(seed=0)
    norm = NormalizerStandardize()
    norm.fit(ds)
    norm.transform(ds)
    return ds.splitTestAndTrain(0.8)


def base_net():
    conf = (NeuralNetConfiguration.Builder().seed(42)
            .updater(updaters.Adam(0.05)).list()
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(nOut=3, lossFunction="mcxent", activation="softmax"))
            .setInputType(InputType.feedForward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestTransferLearning:
    def test_frozen_layers_do_not_update(self):
        split = iris_data()
        net = base_net()
        net.fit(ListDataSetIterator(split.getTrain(), 32), epochs=3)
        new_net = (TransferLearning.Builder(net)
                   .fineTuneConfiguration(
                       FineTuneConfiguration.Builder()
                       .updater(updaters.Adam(0.05)).build())
                   .setFeatureExtractor(0)
                   .build())
        w0_before = np.asarray(new_net._params[0]["W"]).copy()
        w1_before = np.asarray(new_net._params[1]["W"]).copy()
        new_net.fit(ListDataSetIterator(split.getTrain(), 32), epochs=3)
        np.testing.assert_array_equal(np.asarray(new_net._params[0]["W"]), w0_before)
        assert not np.allclose(np.asarray(new_net._params[1]["W"]), w1_before)

    def test_replace_output_layer(self):
        net = base_net()
        new_net = (TransferLearning.Builder(net)
                   .removeOutputLayer()
                   .addLayer(OutputLayer(nOut=5, lossFunction="mcxent",
                                         activation="softmax", nIn=8))
                   .build())
        out = new_net.output(np.zeros((2, 4), np.float32))
        assert out.shape == (2, 5)
        # retained layers share the source's weights
        np.testing.assert_array_equal(np.asarray(new_net._params[0]["W"]),
                                      np.asarray(net._params[0]["W"]))

    def test_helper_featurize_and_fit(self):
        split = iris_data()
        net = base_net()
        helper = TransferLearningHelper(net, frozen_until=0)
        feat_ds = helper.featurize(split.getTrain())
        assert feat_ds.features.shape == (120, 16)
        before = np.asarray(net._params[0]["W"]).copy()
        helper.fitFeaturized(feat_ds, epochs=3)
        np.testing.assert_array_equal(np.asarray(net._params[0]["W"]), before)
        ev_out = net.output(split.getTest().features)
        assert ev_out.shape[1] == 3


class TestEarlyStopping:
    def test_stops_on_patience_and_returns_best(self):
        split = iris_data()
        net = base_net()
        train_it = ListDataSetIterator(split.getTrain(), 32, shuffle=True)
        val_it = ListDataSetIterator(split.getTest(), 30)
        cfg = (EarlyStoppingConfiguration.Builder()
               .scoreCalculator(DataSetLossCalculator(val_it))
               .epochTerminationConditions(
                   MaxEpochsTerminationCondition(40),
                   ScoreImprovementEpochTerminationCondition(5))
               .modelSaver(InMemoryModelSaver())
               .build())
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.best_epoch >= 1
        assert result.total_epochs <= 40
        assert np.isfinite(result.best_score)
        best = result.getBestModel()
        ev = best.evaluate(ListDataSetIterator(split.getTest(), 30))
        assert ev.accuracy() > 0.7

    def test_iteration_condition_aborts(self):
        split = iris_data()
        net = base_net()
        train_it = ListDataSetIterator(split.getTrain(), 32)
        cfg = (EarlyStoppingConfiguration.Builder()
               .scoreCalculator(DataSetLossCalculator(
                   ListDataSetIterator(split.getTest(), 30)))
               .epochTerminationConditions(MaxEpochsTerminationCondition(100))
               .iterationTerminationConditions(
                   MaxScoreIterationTerminationCondition(1e-9))  # triggers at once
               .build())
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.termination_reason == "IterationTerminationCondition"


class TestStreamingRnn:
    def _rnn_net(self, T):
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(updaters.Adam(0.01)).list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent",
                                      activation="softmax"))
                .setInputType(InputType.recurrent(3, T))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_rnn_time_step_matches_full_forward(self):
        T = 8
        net = self._rnn_net(T)
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, T).astype(np.float32)
        full = np.asarray(net.output(x))
        net.rnnClearPreviousState()
        parts = [np.asarray(net.rnnTimeStep(x[:, :, i:i + 2])) for i in range(0, T, 2)]
        streamed = np.concatenate(parts, axis=2)
        np.testing.assert_allclose(streamed, full, rtol=1e-4, atol=1e-5)
        # state persists: a cleared run differs from a continuing run
        cont = np.asarray(net.rnnTimeStep(x[:, :, :2]))
        net.rnnClearPreviousState()
        fresh = np.asarray(net.rnnTimeStep(x[:, :, :2]))
        assert not np.allclose(cont, fresh)

    def test_tbptt_reduces_loss(self):
        T = 12
        net = self._rnn_net(T)
        rng = np.random.RandomState(1)
        x = rng.randn(16, 3, T).astype(np.float32)
        y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        labels = np.concatenate([y, 1 - y], axis=1)
        ds = DataSet(x, labels)
        first = None
        for _ in range(10):
            net.fitTBPTT(ds, tbptt_length=4)
            first = first if first is not None else net.score()
        assert net.score() < first
