"""Attention layers + SameDiffLayer escape hatch (VERDICT r3 #5;
ref: layers.samediff.{SelfAttentionLayer, LearnedSelfAttentionLayer,
RecurrentAttentionLayer}, nn.conf.layers.samediff.SameDiffLayer)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import (GlobalPoolingLayer,
                                          LearnedSelfAttentionLayer,
                                          OutputLayer,
                                          RecurrentAttentionLayer,
                                          RnnOutputLayer, SameDiffLayer,
                                          SelfAttentionLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import updaters


def _seq_net(*mid_layers, n_in=6, t=5, n_classes=3, pool=True):
    b = (NeuralNetConfiguration.Builder().seed(5)
         .updater(updaters.Adam(5e-3)).weightInit("xavier").list())
    for l in mid_layers:
        b = b.layer(l)
    if pool:
        b = b.layer(GlobalPoolingLayer(poolingType="avg"))
    b = (b.layer(OutputLayer(nOut=n_classes, lossFunction="mcxent",
                             activation="softmax"))
         .setInputType(InputType.recurrent(n_in, t)))
    return MultiLayerNetwork(b.build()).init()


def _toy_seq_data(n=24, n_in=6, t=5, n_classes=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in, t).astype(np.float32)
    # label depends on mean over time of feature 0 — attention-learnable
    y_idx = (x[:, 0].mean(-1) > 0).astype(int)
    y = np.eye(n_classes, dtype=np.float32)[y_idx]
    return DataSet(x, y)


class TestSelfAttentionLayer:
    def test_shapes_and_training(self):
        net = _seq_net(SelfAttentionLayer(nOut=8, nHeads=2, headSize=4))
        ds = _toy_seq_data()
        out = np.asarray(net.output(ds.features))
        assert out.shape == (24, 3)
        net.fit(ds)
        first = net.score()
        for _ in range(60):
            net.fit(ds)
        assert net.score() < first * 0.7, (first, net.score())

    def test_unprojected_requires_matching_dims(self):
        with pytest.raises(ValueError, match="projectInput=False"):
            _seq_net(SelfAttentionLayer(nOut=8, nHeads=2, projectInput=False))

    def test_unprojected_identity_dims(self):
        net = _seq_net(SelfAttentionLayer(nOut=6, nHeads=1,
                                          projectInput=False))
        out = np.asarray(net.output(_toy_seq_data().features))
        assert out.shape == (24, 3)

    def test_mask_blocks_padded_timesteps(self):
        net = _seq_net(SelfAttentionLayer(nOut=8, nHeads=2, headSize=4))
        ds = _toy_seq_data()
        x = ds.features
        # same data, padded tail timesteps + mask: output on valid prefix
        # must not depend on junk in padded positions
        mask = np.ones((24, 5), np.float32)
        mask[:, 3:] = 0.0
        x_junk = np.array(x)
        x_junk[:, :, 3:] = 999.0
        d1 = DataSet(np.array(x), ds.labels, features_mask=mask)
        d2 = DataSet(x_junk, ds.labels, features_mask=mask)
        net.fit(d1)
        s1 = net.score()
        net2 = _seq_net(SelfAttentionLayer(nOut=8, nHeads=2, headSize=4))
        net2.fit(d2)
        s2 = net2.score()
        assert np.isclose(s1, s2, rtol=1e-4), (s1, s2)

    def test_fd_gradcheck(self):
        """Central-FD check of dLoss/dWq through the attention layer."""
        layer = SelfAttentionLayer(nOut=4, nHeads=2, headSize=2)
        net = _seq_net(layer, n_in=3, t=4)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 3, 4).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 2]])

        def loss_of(params):
            l, _ = net._loss_and_reg(params, net._states, x, y, False,
                                     jax.random.PRNGKey(0), None, None)
            return l
        g = jax.grad(loss_of)(net._params)[0]["Wq"]
        eps = 1e-3
        for idx in [(0, 0), (1, 3), (2, 2)]:
            p = jax.tree_util.tree_map(jnp.copy, net._params)
            p[0]["Wq"] = p[0]["Wq"].at[idx].add(eps)
            up = float(loss_of(p))
            p[0]["Wq"] = p[0]["Wq"].at[idx].add(-2 * eps)
            dn = float(loss_of(p))
            fd = (up - dn) / (2 * eps)
            an = float(g[idx])
            assert abs(fd - an) / max(abs(fd), abs(an), 1e-3) < 5e-2, \
                (idx, fd, an)


class TestLearnedSelfAttentionLayer:
    def test_fixed_size_summary(self):
        net = _seq_net(LearnedSelfAttentionLayer(nOut=8, nHeads=2,
                                                 headSize=4, nQueries=3))
        ds = _toy_seq_data()
        out = np.asarray(net.output(ds.features))
        assert out.shape == (24, 3)
        # the layer itself emits [N, nOut, nQueries]
        acts = net.feedForward(ds.features)
        assert np.asarray(acts[1]).shape == (24, 8, 3)
        net.fit(ds)
        first = net.score()
        for _ in range(60):
            net.fit(ds)
        assert net.score() < first * 0.7


class TestRecurrentAttentionLayer:
    def test_shapes_and_training(self):
        net = _seq_net(RecurrentAttentionLayer(nOut=8))
        ds = _toy_seq_data()
        acts = net.feedForward(ds.features)
        assert np.asarray(acts[1]).shape == (24, 8, 5)
        net.fit(ds)
        first = net.score()
        for _ in range(60):
            net.fit(ds)
        assert net.score() < first * 0.8

    def test_rnn_output_head(self):
        b = (NeuralNetConfiguration.Builder().seed(3)
             .updater(updaters.Adam(1e-2)).weightInit("xavier").list()
             .layer(RecurrentAttentionLayer(nOut=6))
             .layer(RnnOutputLayer(nOut=2, lossFunction="mcxent"))
             .setInputType(InputType.recurrent(4, 7)))
        net = MultiLayerNetwork(b.build()).init()
        rng = np.random.RandomState(0)
        x = rng.randn(8, 4, 7).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, (8, 7))]
        y = np.transpose(y, (0, 2, 1))
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score())


class _MyGatedDense(SameDiffLayer):
    """User-defined layer: gated dense y = sigmoid(x Wg) * tanh(x W)."""

    def defineParameters(self):
        return {"W": (self.nIn, self.nOut), "Wg": (self.nIn, self.nOut)}

    def defineLayer(self, sd, layerInput, paramTable, mask=None):
        h = layerInput.mmul(paramTable["W"]).tanh()
        g = layerInput.mmul(paramTable["Wg"]).sigmoid()
        return h * g


class TestSameDiffLayer:
    def test_escape_hatch_trains_in_stack(self):
        b = (NeuralNetConfiguration.Builder().seed(9)
             .updater(updaters.Adam(5e-3)).weightInit("xavier").list()
             .layer(_MyGatedDense(nOut=16))
             .layer(OutputLayer(nOut=3, lossFunction="mcxent",
                                activation="softmax"))
             .setInputType(InputType.feedForward(10)))
        net = MultiLayerNetwork(b.build()).init()
        rng = np.random.RandomState(0)
        x = rng.randn(32, 10).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
        ds = DataSet(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (32, 3)
        net.fit(ds)
        first = net.score()
        for _ in range(80):
            net.fit(ds)
        assert net.score() < first * 0.5, (first, net.score())

    def test_gradients_flow_through_fragment(self):
        layer = _MyGatedDense(nOut=4, nIn=5, weightInit="xavier")
        params, _ = layer.initialize(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0).randn(3, 5), jnp.float32)

        def loss(p):
            y, _ = layer.apply(p, {}, x, False, jax.random.PRNGKey(0))
            return jnp.sum(jnp.square(y))
        g = jax.grad(loss)(params)
        assert float(jnp.sum(jnp.abs(g["W"]))) > 0
        assert float(jnp.sum(jnp.abs(g["Wg"]))) > 0
