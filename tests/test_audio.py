"""DataVec audio pipeline tests (ref: datavec-data-audio —
SURVEY.md §2.2 "DataVec image/audio"): WAV round-trip, STFT/mel/MFCC
feature sanity, reader + iterator feeding a Conv1D classifier."""

import os

import numpy as np

from deeplearning4j_tpu.data.audio import (AudioDataSetIterator,
                                           WavFileRecordReader, mel_filterbank,
                                           mel_spectrogram, mfcc, read_wav,
                                           spectrogram, write_wav)


def _tone(freq, rate=8000, dur=0.25, amp=0.5):
    t = np.arange(int(rate * dur)) / rate
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


class TestWavIO:
    def test_roundtrip_16bit(self, tmp_path):
        p = str(tmp_path / "t.wav")
        x = _tone(440)
        write_wav(p, x, 8000)
        y, rate = read_wav(p)
        assert rate == 8000
        np.testing.assert_allclose(y, x, atol=1e-3)

    def test_stereo(self, tmp_path):
        p = str(tmp_path / "s.wav")
        x = np.stack([_tone(300), _tone(600)], axis=1)
        write_wav(p, x, 8000)
        y, _ = read_wav(p)
        assert y.shape == x.shape


class TestFeatures:
    def test_spectrogram_peak_tracks_frequency(self):
        rate, n_fft = 8000, 256
        for freq in (500.0, 1500.0):
            s = np.asarray(spectrogram(_tone(freq, rate), n_fft, 128))
            peak_bin = int(s.mean(0).argmax())
            want_bin = round(freq * n_fft / rate)
            assert abs(peak_bin - want_bin) <= 1, (freq, peak_bin, want_bin)

    def test_mel_filterbank_partitions_spectrum(self):
        fb = np.asarray(mel_filterbank(20, 256, 8000))
        assert fb.shape == (20, 129)
        assert (fb >= 0).all() and fb.max() <= 1.0
        # every filter has some support
        assert (fb.sum(1) > 0).all()

    def test_mfcc_shape_and_finite(self):
        m = np.asarray(mfcc(_tone(700), 8000, n_mfcc=13))
        assert m.shape[1] == 13
        assert np.isfinite(m).all()

    def test_mel_distinguishes_tones(self):
        lo = np.asarray(mel_spectrogram(_tone(300), 8000)).mean(0)
        hi = np.asarray(mel_spectrogram(_tone(3000), 8000)).mean(0)
        assert lo.argmax() < hi.argmax()


class TestReaderAndTraining:
    def _make_tree(self, root):
        rng = np.random.RandomState(0)
        for cls, freq in (("low", 400), ("high", 2500)):
            for i in range(6):
                x = _tone(freq + rng.uniform(-50, 50), dur=0.3)
                x += rng.randn(len(x)).astype(np.float32) * 0.02
                write_wav(os.path.join(root, cls, f"{i}.wav"), x, 8000)

    def test_reader_labels_and_shapes(self, tmp_path):
        self._make_tree(str(tmp_path))
        rr = WavFileRecordReader(feature="mfcc", n_frames=16).initialize(
            str(tmp_path))
        assert rr.labels == ["high", "low"]
        f, l = rr.next()
        assert f.value.shape == (16, 13)
        assert l.value in (0, 1)

    def test_conv1d_classifier_trains_from_wavs(self, tmp_path):
        """End-to-end: on-disk WAVs -> MFCC NCW batches -> Conv1D net."""
        from deeplearning4j_tpu.nn.config import (InputType,
                                                  NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import (Convolution1D,
                                                  GlobalPoolingLayer,
                                                  OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train import updaters

        from deeplearning4j_tpu.data.dataset import NormalizerStandardize
        self._make_tree(str(tmp_path))
        rr = WavFileRecordReader(feature="mfcc", n_frames=16).initialize(
            str(tmp_path))
        it = AudioDataSetIterator(rr, batch_size=12)
        # the canonical normalization flow: raw MFCCs span +/-50 and would
        # saturate the softmax
        norm = NormalizerStandardize()
        norm.fit(it.next())
        it.reset()
        it.setPreProcessor(norm)
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(updaters.Adam(3e-3)).list()
                .layer(Convolution1D(kernelSize=3, nOut=8, activation="relu",
                                     convolutionMode="same"))
                .layer(GlobalPoolingLayer("avg"))
                .layer(OutputLayer(nOut=2, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.recurrent(13, 16))
                .build())
        net = MultiLayerNetwork(conf).init()
        first = None
        for _ in range(20):
            it.reset()
            net.fit(it)
            if first is None:
                first = net.score()
        assert np.isfinite(net.score())
        assert net.score() < first * 0.7, (first, net.score())
